"""bigdl_tpu — a TPU-native deep-learning framework with the capabilities of
BigDL (reference: yiheng/BigDL, a fork of Intel's Spark-based BigDL 0.x).

Not a port: the reference's Scala/JVM + MKL JNI + Spark-BlockManager design is
rebuilt idiomatically on JAX/XLA — modules are pure ``init/apply`` pairs under
a BigDL-style stateful facade, training steps compile to single SPMD programs
via ``jax.jit`` over a ``jax.sharding.Mesh``, and the distributed gradient
plane is XLA collectives (``psum`` / ``psum_scatter`` + ``all_gather``) over
ICI instead of Spark BlockManager shuffles.

Layer map (mirrors SURVEY.md §1):
    bigdl_tpu.tensor        — Tensor facade over jax.Array        (ref L1)
    bigdl_tpu.nn            — Module/Criterion/layers/Graph       (ref L2)
    bigdl_tpu.optim         — Optimizer/OptimMethod/Trigger/...   (ref L3)
    bigdl_tpu.dataset       — DataSet/Transformer/Sample/...      (ref L4)
    bigdl_tpu.models        — model zoo                           (ref L6)
    bigdl_tpu.serving       — continuous-batching inference       (no ref)
    bigdl_tpu.parallel      — distributed parameter plane         (ref L7)
    bigdl_tpu.utils         — Engine/Table/File/RNG               (ref L8)
    bigdl_tpu.visualization — TrainSummary/ValidationSummary      (ref L10)
"""

__version__ = "0.1.0"

__all__ = ["Engine", "EngineType", "__version__"]


def __getattr__(name):
    # PEP 562 lazy re-export: utils.engine drags in utils.table and
    # with it jax (~2s of import on the dev box). The static-analysis
    # plane (`python -m bigdl_tpu.analysis`, pure stdlib by contract)
    # lives under this package and must not pay that — so the facade
    # imports resolve on first ATTRIBUTE access, not at package import.
    if name in ("Engine", "EngineType"):
        from bigdl_tpu.utils.engine import Engine, EngineType

        return {"Engine": Engine, "EngineType": EngineType}[name]
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
