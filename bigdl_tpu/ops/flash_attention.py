"""Flash attention as Pallas TPU kernels (forward + backward).

No reference counterpart (SURVEY.md §5.7 — the reference is attention-free);
this is part of the framework's long-context extension. The dense
``attention`` in ``bigdl_tpu.parallel.ring_attention`` materialises the
(T, T) score matrix in HBM; these kernels keep scores in VMEM tiles with an
online softmax (running max / normaliser), so memory is linear in T and the
QK^T / PV gemms stay on the MXU back-to-back without round-tripping HBM.

Layout: public API takes (B, T, H, D) to match the attention layers; the
kernels run on (B*H, T, D) with a (batch*heads, seq-block) grid. The
backward pass is the FlashAttention-2 split: a dq kernel gridded over query
blocks and a dk/dv kernel gridded over key blocks, both replaying the
online softmax from the saved logsumexp.

Numerics: accumulation is f32 regardless of input dtype (bf16 in, f32
softmax state, cast on write) — the `jax.default_matmul_precision` analog
of the reference's fp32 MKL paths.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30  # finite sentinel: keeps exp() well-defined for masked rows


def _auto_interpret() -> bool:
    # shared platform probe (utils.compat.auto_interpret): one dispatch
    # decision for every Pallas kernel in ops/, so flash and the pooled
    # decode kernel can't drift on the CPU/TPU interpret choice
    from bigdl_tpu.utils.compat import auto_interpret

    return auto_interpret()


# ---------------------------------------------------------------- forward


def _dot_nt(a, b):
    """a @ b.T without materializing the transpose: dot_general contracting
    the trailing (lane) dims — the layout Mosaic feeds the MXU directly."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, off_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale, causal, kv_len, kp_len, skip):
    """Grid (BH, n_q, n_k) — the KV axis is a GRID dimension, so only one
    (block_q, d) q tile and one (block_k, d) k/v tile are VMEM-resident per
    step (O(block²) VMEM at any T); the online-softmax state lives in
    scratch that persists across the inner kv steps.

    Interior blocks skip ALL masking work (statically when the sequence is
    unpadded and non-causal; via a separate unmasked pl.when branch for
    causal blocks fully below the diagonal) — the iota/compare/select chain
    on a block² tile otherwise rivals the softmax itself in VPU time."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]
    padded = kp_len != kv_len  # static: does any key block need a tail mask?
    # diagonal offset: 0 = standard causal (col <= row), -1 = STRICT causal
    # (col < row) — striped ring attention's future-originated blocks.
    # full-block read, not [0, 0]: the HLO interpreter's vma check rejects
    # a dynamic_slice of a device-varying operand with invariant indices
    off = jnp.reshape(off_ref[...], ())

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    # causal: key blocks entirely above the (offset) diagonal contribute
    # nothing
    needed = True
    if causal and skip:
        needed = kj * bk <= (qi + 1) * bq - 1 + off

    def _accumulate(s):
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)

    def _scores():
        # dots run on the INPUT dtype (bf16 stays on the fast MXU path)
        # with f32 accumulation; softmax state is always f32
        return _dot_nt(q_ref[0], k_ref[0]) * scale

    def _masked_step():
        s = _scores()
        rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        cols = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        if padded:
            mask = cols < kv_len
            if causal:
                mask = jnp.logical_and(mask, cols <= rows + off)
        else:
            mask = cols <= rows + off
        _accumulate(jnp.where(mask, s, _NEG_INF))

    if not skip:
        # interpret mode: traced pl.when predicates are rejected inside
        # shard_map — run one unconditional step (mask when anything at
        # all needs masking)
        if causal or padded:
            _masked_step()
        else:
            _accumulate(_scores())
    elif not causal and not padded:
        _accumulate(_scores())
    elif not causal:  # padded, non-causal: only the LAST key block is masked
        pl.when(kj < n_k - 1)(lambda: _accumulate(_scores()))
        pl.when(kj == n_k - 1)(_masked_step)
    else:
        # causal: full (entirely below-diagonal, untouched by padding)
        # blocks take the unmasked path; diagonal/tail blocks pay the mask
        full_below = (kj + 1) * bk - 1 <= qi * bq + off
        if padded:
            full_below = jnp.logical_and(full_below, kj < n_k - 1)
        pl.when(full_below)(lambda: _accumulate(_scores()))
        pl.when(jnp.logical_and(needed, jnp.logical_not(full_below)))(
            _masked_step)

    @pl.when(kj == n_k - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_scr[...] + jnp.log(l_safe)).astype(jnp.float32)


# --------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, off_ref,
               dq_ref, dq_scr, *, scale, causal, kv_len, kp_len, skip):
    """Grid (BH, n_q, n_k): dq accumulates in scratch across kv steps.
    Same masked/unmasked step split as the forward kernel."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]
    padded = kp_len != kv_len
    off = jnp.reshape(off_ref[...], ())

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    needed = True
    if causal and skip:
        needed = kj * bk <= (qi + 1) * bq - 1 + off

    def _step(with_mask):
        q = q_ref[0]
        do = do_ref[0]                                  # (BQ, D)
        lse = lse_ref[0]                                # (BQ, 1)
        delta = delta_ref[0]                            # (BQ, 1)
        k = k_ref[0]
        v = v_ref[0]
        s = _dot_nt(q, k) * scale
        if with_mask:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
            cols = kj * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
            if padded:
                mask = cols < kv_len
                if causal:
                    mask = jnp.logical_and(mask, cols <= rows + off)
            else:
                mask = cols <= rows + off
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                            # (BQ, BK) f32
        dp = _dot_nt(do, v)
        ds = p * (dp - delta)
        dq_scr[...] = dq_scr[...] + jnp.dot(
            ds.astype(k.dtype), k, preferred_element_type=jnp.float32)

    if not skip:
        _step(causal or padded)
    elif not causal and not padded:
        _step(False)
    elif not causal:
        pl.when(kj < n_k - 1)(lambda: _step(False))
        pl.when(kj == n_k - 1)(lambda: _step(True))
    else:
        full_below = (kj + 1) * bk - 1 <= qi * bq + off
        if padded:
            full_below = jnp.logical_and(full_below, kj < n_k - 1)
        pl.when(full_below)(lambda: _step(False))
        pl.when(jnp.logical_and(needed, jnp.logical_not(full_below)))(
            lambda: _step(True))

    @pl.when(kj == n_k - 1)
    def _finish():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, off_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, skip):
    """Grid (BH, n_k, n_q): dk/dv accumulate in scratch across query steps.
    Padded query rows are safe: q and delta are zero-padded so ds and do
    vanish there."""
    ki = pl.program_id(1)
    qj = pl.program_id(2)
    n_q = pl.num_programs(2)
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]
    off = jnp.reshape(off_ref[...], ())

    @pl.when(qj == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    needed = True
    if causal and skip:  # query blocks entirely above the diagonal contribute 0
        needed = (qj + 1) * bq - 1 + off >= ki * bk

    def _step(with_mask):
        k = k_ref[0]                                    # (BK, D)
        v = v_ref[0]
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = _dot_nt(q, k) * scale
        if with_mask:
            rows = qj * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
            cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
            s = jnp.where(cols <= rows + off, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = _dot_nt(do, v)
        ds = p * (dp - delta)
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if not skip:
        _step(causal)
    elif not causal:
        _step(False)
    else:
        # query block entirely BELOW the diagonal (all rows >= all cols):
        # no causal mask needed
        full_below = qj * bq + off >= (ki + 1) * bk - 1
        pl.when(full_below)(lambda: _step(False))
        pl.when(jnp.logical_and(needed, jnp.logical_not(full_below)))(
            lambda: _step(True))

    @pl.when(qj == n_q - 1)
    def _finish():
        # the q·k^T scale folds into dk once here (ds was computed on the
        # unscaled s gradient path)
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


# ----------------------------------------------------------- host wrappers


def _pad_seq(x, block):
    t = x.shape[1]
    pad = (-t) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _out_struct(shape, dtype, *refs):
    """ShapeDtypeStruct carrying the UNION of the operands' varying-manual-
    axes sets, so pallas_call type-checks inside shard_map (check_vma) even
    when operands vary over different axes."""
    from bigdl_tpu.utils.compat import varying_axes

    vma = frozenset()
    for ref in refs:
        vma = vma | varying_axes(ref)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _off_arr(causal_offset):
    """Diagonal-offset operand: (1, 1) int32, 0 unless given (a possibly
    TRACED scalar — striped ring passes src-vs-rank dependent offsets)."""
    if causal_offset is None:
        return jnp.zeros((1, 1), jnp.int32)
    return jnp.asarray(causal_offset, jnp.int32).reshape(1, 1)


def _flash_fwd(q3, k3, v3, scale, causal, block, interpret,
               causal_offset=None):
    from jax.experimental.pallas import tpu as pltpu

    from bigdl_tpu.utils.compat import pallas_tpu_compiler_params

    bh, t, d = q3.shape
    tp = t + (-t) % block
    qp, kp, vp = (_pad_seq(x, block) for x in (q3, k3, v3))
    off = _off_arr(causal_offset)
    kv_len = k3.shape[1]
    kp_len = kp.shape[1]
    # grid: kv axis INNERmost so the scratch softmax state carries across it
    grid = (bh, tp // block, kp_len // block)
    qblk = lambda n: pl.BlockSpec((1, block, n), lambda b, i, j: (b, i, 0))
    kblk = lambda n: pl.BlockSpec((1, block, n), lambda b, i, j: (b, j, 0))
    oblk = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          kv_len=kv_len, kp_len=kp_len, skip=not interpret),
        grid=grid,
        in_specs=[qblk(d), kblk(d), kblk(d), oblk],
        out_specs=[qblk(d), qblk(1)],
        out_shape=[
            _out_struct((bh, tp, d), q3.dtype, q3, k3, v3, off),
            _out_struct((bh, tp, 1), jnp.float32, q3, k3, v3, off),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
        compiler_params=None if interpret else pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, off)
    return o[:, :t], lse[:, :t]


def _flash_bwd(q3, k3, v3, o3, lse, do3, scale, causal, block, interpret,
               causal_offset=None):
    from jax.experimental.pallas import tpu as pltpu

    from bigdl_tpu.utils.compat import pallas_tpu_compiler_params

    bh, t, d = q3.shape
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)             # (BH, T, 1)
    qp, kp, vp, dop = (_pad_seq(x, block) for x in (q3, k3, v3, do3))
    off = _off_arr(causal_offset)
    lsep = jnp.pad(lse, ((0, 0), (0, qp.shape[1] - t), (0, 0)))
    deltap = jnp.pad(delta, ((0, 0), (0, qp.shape[1] - t), (0, 0)))
    tp = qp.shape[1]
    kp_len = kp.shape[1]
    qblk = lambda n: pl.BlockSpec((1, block, n), lambda b, i, j: (b, i, 0))
    kblk = lambda n: pl.BlockSpec((1, block, n), lambda b, i, j: (b, j, 0))
    oblk = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          kv_len=k3.shape[1], kp_len=kp_len,
                          skip=not interpret),
        grid=(bh, tp // block, kp_len // block),
        in_specs=[qblk(d), kblk(d), kblk(d), qblk(d), qblk(1), qblk(1),
                  oblk],
        out_specs=qblk(d),
        out_shape=_out_struct((bh, tp, d), q3.dtype, q3, k3, v3, off),
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
        compiler_params=None if interpret else pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap, off)

    # dk/dv: key axis is the carried (outer-block) dim, queries innermost
    kblk2 = lambda n: pl.BlockSpec((1, block, n), lambda b, i, j: (b, i, 0))
    qblk2 = lambda n: pl.BlockSpec((1, block, n), lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          skip=not interpret),
        grid=(bh, kp_len // block, tp // block),
        in_specs=[qblk2(d), kblk2(d), kblk2(d), qblk2(d), qblk2(1), qblk2(1),
                  oblk],
        out_specs=[kblk2(d), kblk2(d)],
        out_shape=[_out_struct((bh, kp_len, d), k3.dtype, q3, k3, v3, off),
                   _out_struct((bh, kp_len, d), v3.dtype, q3, k3, v3, off)],
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32),
                        pltpu.VMEM((block, d), jnp.float32)],
        compiler_params=None if interpret else pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap, off)
    return dq[:, :t], dk[:, :k3.shape[1]], dv[:, :v3.shape[1]]


# ------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q3, k3, v3, scale, causal, block, interpret):
    o, _ = _flash_fwd(q3, k3, v3, scale, causal, block, interpret)
    return o


def _flash_vjp_fwd(q3, k3, v3, scale, causal, block, interpret):
    o, lse = _flash_fwd(q3, k3, v3, scale, causal, block, interpret)
    return o, (q3, k3, v3, o, lse)


def _flash_vjp_bwd(scale, causal, block, interpret, res, do3):
    q3, k3, v3, o3, lse = res
    return _flash_bwd(q3, k3, v3, o3, lse, do3, scale, causal, block,
                      interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _bthd_plumbing(q, k, v, scale, interpret):
    """Shared layout/default handling: (B,T,H,D) API ↔ (B*H,T,D) kernels.
    Returns (q3, k3, v3, scale, interpret, from3, to3): from3 restores the
    public layout, to3 maps further (B,T,H,D) operands (o, do) down."""
    if interpret is None:
        interpret = _auto_interpret()
    b, t, h, d = q.shape
    if scale is None:
        scale = d ** -0.5

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    def from3(o3):
        return o3.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    return (to3(q), to3(k), to3(v), float(scale), bool(interpret), from3,
            to3)


def _check_causal_offset(causal, causal_offset):
    if causal_offset is not None and not causal:
        raise ValueError(
            "causal_offset requires causal=True — the non-causal kernel "
            "branches apply no mask, so the offset would be silently "
            "ignored")


def _auto_block(t_max: int) -> int:
    """Pick the VMEM tile length: as large as the scoped-VMEM budget allows
    (the block² f32 score tile caps at 1024 → 4 MB) — big tiles amortize
    grid-step overhead, the dominant cost at long T (measured on v5e:
    T=32k causal fwd+bwd 215 ms at block 128 → 52 ms at block 1024)."""
    padded = ((max(t_max, 1) + 127) // 128) * 128
    return max(128, min(1024, padded))


def flash_attention_with_lse(q, k, v, scale: Optional[float] = None,
                             block: Optional[int] = None,
                             interpret: Optional[bool] = None,
                             causal: bool = False,
                             causal_offset=None):
    """Forward-only fused attention returning ``(out, lse)`` — the
    per-query log-sum-exp lets callers merge partial attention blocks with
    the online-softmax rule (ring attention's flash path; ``causal=True``
    for the diagonal block of a causal ring).
    ``causal_offset`` shifts the diagonal: -1 = strict causal
    (``col < row``), as striped ring attention needs for blocks from
    later-ranked stripes; may be a traced scalar.
    ``out``: (B, T, H, D); ``lse``: (B, H, T) float32.
    """
    _check_causal_offset(causal, causal_offset)
    b, t, h, d = q.shape
    if block is None:
        block = _auto_block(max(q.shape[1], k.shape[1]))
    q3, k3, v3, scale, interpret, from3, _ = _bthd_plumbing(
        q, k, v, scale, interpret)
    o3, lse = _flash_fwd(q3, k3, v3, scale, bool(causal), int(block),
                         interpret, causal_offset=causal_offset)
    return from3(o3), lse[..., 0].reshape(b, h, t)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Fused attention over (B, T, H, D) tensors; differentiable.

    Drop-in for ``bigdl_tpu.parallel.ring_attention.attention`` with
    O(T) memory. ``block`` is the VMEM tile length (MXU-aligned multiple of
    128; ``None`` auto-sizes, see :func:`_auto_block`).
    ``interpret=None`` auto-selects Pallas interpreter mode off-TPU.
    """
    if block is None:
        block = _auto_block(max(q.shape[1], k.shape[1]))
    q3, k3, v3, scale, interpret, from3, _ = _bthd_plumbing(
        q, k, v, scale, interpret)
    return from3(_flash(q3, k3, v3, scale, bool(causal), int(block),
                        interpret))


def flash_attention_block_grads(q, k, v, o, lse, do,
                                scale: Optional[float] = None,
                                block: Optional[int] = None,
                                interpret: Optional[bool] = None,
                                causal: bool = False,
                                causal_offset=None):
    """Per-block backward against GLOBAL softmax statistics — the ring
    backward's building block.

    ``q/o/do``: (B, Tq, H, D); ``k/v``: (B, Tk, H, D); ``lse``: (B, H, Tq)
    — the log-sum-exp of the FULL (all-blocks) softmax, so the block's
    probabilities ``exp(s − lse)`` are the true global ones and block
    gradients sum exactly across blocks. Returns ``(dq, dk, dv)`` shaped
    like q/k/v.
    """
    _check_causal_offset(causal, causal_offset)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if block is None:
        block = _auto_block(max(tq, tk))
    q3, k3, v3, scale, interpret, from3, to3 = _bthd_plumbing(
        q, k, v, scale, interpret)
    o3, do3 = to3(o), to3(do)
    lse3 = lse.reshape(b * h, tq, 1)
    dq3, dk3, dv3 = _flash_bwd(q3, k3, v3, o3, lse3, do3, scale,
                               bool(causal), int(block), interpret,
                               causal_offset=causal_offset)
    dq = from3(dq3)
    dk = dk3.reshape(b, h, tk, d).transpose(0, 2, 1, 3)
    dv = dv3.reshape(b, h, tk, d).transpose(0, 2, 1, 3)
    return dq, dk, dv
