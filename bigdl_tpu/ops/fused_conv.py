"""Fused BN-apply → ReLU → 1×1-conv Pallas kernels (TPU lowering choice).

Reference (UNVERIFIED, SURVEY.md §0): the mkldnn engine precedent —
``.../bigdl/nn/mkldnn/SpatialConvolution.scala`` fuses ReLU/BN/sum into the
conv primitive when the engine is mkldnn (``setReLU``/``setSum`` fusion
flags); this module is the TPU-engine analog.

Why this exists (benchmarks/PERF_ANALYSIS_r2.md): in BN **training**, the
normalize+ReLU pass cannot fuse into the *producing* conv under XLA:TPU —
normalization needs the complete batch statistics, which only exist after
every output tile of the producer is done (the measured
``maximum_add_fusion`` passes at ~0.7 TFLOP/s / 83% HBM). But it CAN fuse
into the *consuming* conv's prologue: by the time the next conv runs, the
stats are a tiny (C,) vector. ResNet bottleneck 3×3→BN→ReLU→1×1 edges are
exactly this shape, with the 1×1 conv a plain matmul over M = N·H·W rows —
so the whole edge becomes one Pallas matmul with an elementwise prologue,
and the ReLU input tensor is never materialized in HBM.

Operand form: every big tensor is ``(G, R, C)`` — G row groups of R rows.
A channels-last activation ``(N, H, W, C)`` enters as ``(N·H, W, C)``,
which is a FREE view of the tiled NHWC layout (TPU tiling touches only the
last two dims); flattening all the way to ``(M, C)`` would physically
repack HBM (the measured 35 ms/step "data formatting" disaster of the
first integration attempt). The per-tile ``(bg·R, C)`` flatten happens in
VMEM, where relayout shuffles are ~free. Plain ``(M, C)`` operands are
accepted too and viewed as ``(M/bm, bm, C)``.

The op also emits ``sum(z)``/``sum(z²)`` per output channel from the matmul
epilogue (f32), so the *next* BN's batch stats need no extra pass over z —
mirroring XLA's conv-epilogue stats fusion (``multiply_reduce_fusion``).

Backward is the full BN-*train* backward (batch statistics are functions of
x): with p = x̂·γ + β (+ r), y = relu(p), z = y·W and incoming dz,

    dp = (dz @ Wᵀ) ⊙ 1[p > 0]          (+ any extra cotangent on y)
    dβ = Σ_M dp        dγ = Σ_M dp ⊙ x̂
    dx = (γ/σ) · (dp − dβ/M − x̂ · dγ/M)
    dW = yᵀ @ dz       dr = dp

The two reductions live in the dgrad kernel's epilogue; the (M,C)-sized
``dp`` is the only backward intermediate materialized (XLA materializes the
same-sized dy *and* runs a separate masked-scale pass). ``mean``/``var``
inputs are treated as *values* (their gradient contribution is the
−dβ/M − x̂·dγ/M correction above, i.e. already inside dx); callers must
pass stats computed from the same ``x``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _is_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


def _auto_interpret() -> bool:
    return not _is_tpu()


_VMEM_BUDGET = 10 * 1024 * 1024  # leave headroom under the 16M scoped limit


def _pick_div(n: int, target: int, unit: int = 1) -> int:
    """Largest divisor of n that is ≤ target and a multiple of ``unit``;
    falls back through smaller multiples, then to 1/n."""
    for k in range(target // unit, 0, -1):
        if n % (unit * k) == 0:
            return unit * k
    return n


def _pick_bk(k: int, target: int = 512) -> int:
    for cand in (target, 256, 128):
        if k % cand == 0:
            return cand
    return k


def _rows_cap(bytes_per_row: int, fixed_bytes: int, target: int) -> int:
    cap = max((_VMEM_BUDGET - fixed_bytes) // max(bytes_per_row, 1), 128)
    return min(target, cap)


def _as_grc(x, rows_target: int):
    """View x as (G, R, C) row groups: free for both 2-D (M, C) and 3-D
    (G0, R, C) inputs. Returns (x3, bg, n_groups_per_block_grid)."""
    if x.ndim == 3:
        g, r, c = x.shape
        bg = _pick_div(g, max(rows_target // r, 1))
        return x, bg
    m, c = x.shape
    bm = _pick_div(m, rows_target, unit=128)
    return x.reshape(m // bm, bm, c), 1


def _pack_factor(m: int, c: int) -> int:
    """Lane packing (2-D path only): C below the 128-lane width wastes half
    (or more) of every VMEM tile and DMA burst. Viewing (M, C) as
    (M/f, f·C) with a block-diagonal weight restores full lanes."""
    f = 128 // c if (c < 128 and 128 % c == 0) else 1
    while f > 1 and m % f:
        f //= 2
    return max(f, 1)


def _block_diag_w(w, f: int):
    """(C, K) → (f·C, f·K) with f copies of w on the diagonal."""
    c, k = w.shape
    eye = jnp.eye(f, dtype=w.dtype)
    return (eye[:, None, :, None] * w[None, :, None, :]).reshape(f * c, f * k)


def _tile_vec(v, f: int):
    return jnp.tile(v.reshape(1, -1), (f, 1)).reshape(-1)


def _esize(x) -> int:
    return 2 if x.dtype == jnp.bfloat16 else 4


def _flat(ref):
    """(bg, R, C) block → (bg·R, C) rows — a VMEM relayout, not HBM."""
    s = ref.shape
    return ref[...].reshape(-1, s[-1])


# ---------------------------------------------------------------------------
# forward: z = relu(x*scale + shift (+ r)) @ w, with per-channel z stats
# ---------------------------------------------------------------------------


def _fwd_kernel(x_ref, s_ref, b_ref, w_ref, r_ref, z_ref, zstat_ref, y_ref,
                y_s, stat_s, *, n_mt: int, with_residual: bool,
                want_y: bool):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        p = _flat(x_ref).astype(jnp.float32) * s_ref[0] + b_ref[0]
        if with_residual:
            p = p + _flat(r_ref).astype(jnp.float32)
        y = jnp.maximum(p, 0.0)
        y_s[...] = y.astype(y_s.dtype)
        if want_y:
            y_ref[...] = y.astype(y_ref.dtype).reshape(y_ref.shape)

    z32 = jnp.dot(y_s[...], w_ref[0], preferred_element_type=jnp.float32)
    z_ref[...] = z32.astype(z_ref.dtype).reshape(z_ref.shape)

    part = jnp.stack([jnp.sum(z32, axis=0), jnp.sum(z32 * z32, axis=0)])

    @pl.when(i == 0)
    def _():
        stat_s[j] = part

    @pl.when(i > 0)
    def _():
        stat_s[j] = stat_s[j] + part

    @pl.when(i == n_mt - 1)
    def _():
        zstat_ref[0] = stat_s[j]


def fused_scale_relu_matmul(x, scale, shift, w, residual=None,
                            want_y: bool = False,
                            out_dtype=None,
                            bk: Optional[int] = None,
                            interpret: Optional[bool] = None):
    """``z = relu(x·scale + shift (+ residual)) @ w`` in one HBM pass.

    x: (M, C) or (G, R, C); scale/shift: (C,) f32 (pre-folded BN: γ/σ and
    β − μγ/σ); w: (C, K). Returns ``(z, zstats[, y])`` with ``zstats``
    (2, K) f32 = per-channel ``[Σz, Σz²]`` from the matmul epilogue;
    z/y mirror x's rank. ``want_y`` additionally materializes the
    post-ReLU activation (for edges whose activation has a second
    consumer, e.g. the block-join feeding both the next conv and the next
    shortcut — the kernel then saves the re-read, not the write).
    """
    if interpret is None:
        interpret = _auto_interpret()
    c = x.shape[-1]
    k = w.shape[1]
    if x.ndim == 2:
        m = x.shape[0]
        f = _pack_factor(m, c)
        if f > 1:
            out = fused_scale_relu_matmul(
                x.reshape(m // f, f * c), _tile_vec(scale, f),
                _tile_vec(shift, f), _block_diag_w(w, f),
                residual=None if residual is None
                else residual.reshape(m // f, f * c),
                want_y=want_y, out_dtype=out_dtype, bk=bk,
                interpret=interpret)
            z = out[0].reshape(m, k)
            zstat = out[1].reshape(2, f, k).sum(1)
            if want_y:
                return z, zstat, out[2].reshape(m, c)
            return z, zstat
    bk = bk or _pick_bk(k)
    es = _esize(x)
    per_row = (es * c * (2 + 1
                         + (2 if residual is not None else 0)
                         + (2 if want_y else 0))
               + es * bk * 2)
    x3, bg = _as_grc(x, _rows_cap(per_row, 2 * es * c * bk, 1024))
    g, r, _ = x3.shape
    rows = bg * r
    n_mt, n_kt = g // bg, k // bk
    with_residual = residual is not None
    r3 = residual.reshape(x3.shape) if with_residual else \
        jnp.zeros((1, 1, c), x.dtype)
    out_dtype = out_dtype or x.dtype

    from jax.experimental.pallas import tpu as pltpu

    rspec = (pl.BlockSpec((bg, r, c), lambda i, j: (i, 0, 0))
             if with_residual else
             pl.BlockSpec((1, 1, c), lambda i, j: (0, 0, 0)))
    kernel = functools.partial(_fwd_kernel, n_mt=n_mt,
                               with_residual=with_residual, want_y=want_y)
    z, zstat, y = pl.pallas_call(
        kernel,
        grid=(n_mt, n_kt),
        in_specs=[
            pl.BlockSpec((bg, r, c), lambda i, j: (i, 0, 0)),   # x
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),          # scale
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),          # shift
            pl.BlockSpec((1, c, bk), lambda i, j: (0, 0, j)),   # w
            rspec,                                              # residual
        ],
        out_specs=[
            pl.BlockSpec((bg, r, bk), lambda i, j: (i, 0, j)),  # z
            pl.BlockSpec((1, 2, bk), lambda i, j: (0, 0, j)),   # zstats
            (pl.BlockSpec((bg, r, c), lambda i, j: (i, 0, 0))
             if want_y else
             pl.BlockSpec((1, 1, c), lambda i, j: (0, 0, 0))),  # y
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, r, k), out_dtype),
            jax.ShapeDtypeStruct((1, 2, k), jnp.float32),
            jax.ShapeDtypeStruct((g, r, c) if want_y else (1, 1, c),
                                 out_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((rows, c), jnp.bfloat16
                       if x.dtype == jnp.bfloat16 else jnp.float32),
            pltpu.VMEM((n_kt, 2, bk), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(x3, scale.reshape(1, c).astype(jnp.float32),
      shift.reshape(1, c).astype(jnp.float32), w[None], r3)
    zstat = zstat[0]
    if x.ndim == 2:
        z = z.reshape(x.shape[0], k)
        if want_y:
            return z, zstat, y.reshape(x.shape)
        return z, zstat
    if want_y:
        return z, zstat, y
    return z, zstat


# ---------------------------------------------------------------------------
# backward kernel 1 (dgrad): dp = (dz @ wᵀ) ⊙ relu-mask, plus q1/q2
# ---------------------------------------------------------------------------


def _dgrad_kernel(dz_ref, w_ref, x_ref, s_ref, b_ref, r_ref, g_ref,
                  mu_ref, is_ref, dp_ref, q_ref, q_s, *,
                  n_mt: int, with_residual: bool, with_extra: bool):
    i = pl.program_id(0)

    dy = jax.lax.dot_general(
        _flat(dz_ref), w_ref[0],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if with_extra:
        dy = dy + _flat(g_ref).astype(jnp.float32)
    x32 = _flat(x_ref).astype(jnp.float32)
    p = x32 * s_ref[0] + b_ref[0]
    if with_residual:
        p = p + _flat(r_ref).astype(jnp.float32)
    dp = jnp.where(p > 0.0, dy, 0.0)
    dp_ref[...] = dp.astype(dp_ref.dtype).reshape(dp_ref.shape)

    xhat = (x32 - mu_ref[0]) * is_ref[0]
    part = jnp.stack([jnp.sum(dp, axis=0), jnp.sum(dp * xhat, axis=0)])

    @pl.when(i == 0)
    def _():
        q_s[...] = part

    @pl.when(i > 0)
    def _():
        q_s[...] = q_s[...] + part

    @pl.when(i == n_mt - 1)
    def _():
        q_ref[0] = q_s[...]


def fused_dgrad(dz, w, x, scale, shift, mean, inv_std, residual=None,
                extra_dy=None, interpret: Optional[bool] = None):
    """``dp = (dz@wᵀ [+ extra_dy]) ⊙ 1[p>0]`` with epilogue reductions
    ``q = (Σ dp, Σ dp·x̂)`` — dβ/dγ and the BN-train dx correction terms,
    all in the one pass that reads dz. dz: (M, K)/(G, R, K); x & friends:
    (M, C)/(G, R, C); dp mirrors x's rank."""
    if interpret is None:
        interpret = _auto_interpret()
    k = dz.shape[-1]
    c = w.shape[0]
    if x.ndim == 2:
        m = x.shape[0]
        f = _pack_factor(m, c)
        if f > 1:
            dp, q = fused_dgrad(
                dz.reshape(m // f, f * k), _block_diag_w(w, f),
                x.reshape(m // f, f * c), _tile_vec(scale, f),
                _tile_vec(shift, f), _tile_vec(mean, f),
                _tile_vec(inv_std, f),
                residual=None if residual is None
                else residual.reshape(m // f, f * c),
                extra_dy=None if extra_dy is None
                else extra_dy.reshape(m // f, f * c),
                interpret=interpret)
            return dp.reshape(m, c), q.reshape(2, f, c).sum(1)
    es = _esize(x)
    per_row = es * (k * 2 + c * (2 + 2
                                 + (2 if residual is not None else 0)
                                 + (2 if extra_dy is not None else 0)))
    x3, bg = _as_grc(x, _rows_cap(per_row, 2 * es * c * k, 512))
    g, r, _ = x3.shape
    dz3 = dz.reshape(g, r, k)
    n_mt = g // bg
    with_residual = residual is not None
    with_extra = extra_dy is not None
    r3 = residual.reshape(g, r, c) if with_residual else \
        jnp.zeros((1, 1, c), x.dtype)
    g3 = extra_dy.reshape(g, r, c) if with_extra else \
        jnp.zeros((1, 1, c), x.dtype)

    from jax.experimental.pallas import tpu as pltpu

    big = lambda: pl.BlockSpec((bg, r, c), lambda i: (i, 0, 0))
    small = lambda: pl.BlockSpec((1, 1, c), lambda i: (0, 0, 0))
    vec = lambda: pl.BlockSpec((1, c), lambda i: (0, 0))
    kernel = functools.partial(_dgrad_kernel, n_mt=n_mt,
                               with_residual=with_residual,
                               with_extra=with_extra)
    dp, q = pl.pallas_call(
        kernel,
        grid=(n_mt,),
        in_specs=[
            pl.BlockSpec((bg, r, k), lambda i: (i, 0, 0)),      # dz
            pl.BlockSpec((1, c, k), lambda i: (0, 0, 0)),       # w
            big(),                                              # x
            vec(), vec(),                                       # scale, shift
            big() if with_residual else small(),                # residual
            big() if with_extra else small(),                   # extra_dy
            vec(), vec(),                                       # mean, inv_std
        ],
        out_specs=[
            pl.BlockSpec((bg, r, c), lambda i: (i, 0, 0)),      # dp
            pl.BlockSpec((1, 2, c), lambda i: (0, 0, 0)),       # q
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, r, c), x.dtype),
            jax.ShapeDtypeStruct((1, 2, c), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((2, c), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(dz3, w[None], x3,
      scale.reshape(1, c).astype(jnp.float32),
      shift.reshape(1, c).astype(jnp.float32),
      r3, g3,
      mean.reshape(1, c).astype(jnp.float32),
      inv_std.reshape(1, c).astype(jnp.float32))
    q = q[0]
    if x.ndim == 2:
        return dp.reshape(x.shape), q
    return dp, q


# ---------------------------------------------------------------------------
# backward kernel 2 (wgrad): dW = yᵀ @ dz with y recomputed in the prologue
# ---------------------------------------------------------------------------


def _wgrad_kernel(x_ref, s_ref, b_ref, r_ref, dz_ref, dw_ref, acc_s, *,
                  n_mt: int, with_residual: bool):
    i = pl.program_id(0)
    p = _flat(x_ref).astype(jnp.float32) * s_ref[0] + b_ref[0]
    if with_residual:
        p = p + _flat(r_ref).astype(jnp.float32)
    y = jnp.maximum(p, 0.0).astype(dz_ref.dtype)
    part = jax.lax.dot_general(
        y, _flat(dz_ref),
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == 0)
    def _():
        acc_s[...] = part

    @pl.when(i > 0)
    def _():
        acc_s[...] = acc_s[...] + part

    @pl.when(i == n_mt - 1)
    def _():
        dw_ref[0] = acc_s[...].astype(dw_ref.dtype)


def fused_wgrad(x, scale, shift, dz, residual=None, out_dtype=jnp.float32,
                interpret: Optional[bool] = None):
    """``dW = relu(x·scale+shift(+r))ᵀ @ dz`` — the activation is recomputed
    from x on the fly (never stored), so the forward needn't keep y."""
    if interpret is None:
        interpret = _auto_interpret()
    c = x.shape[-1]
    k = dz.shape[-1]
    if x.ndim == 2:
        m = x.shape[0]
        f = _pack_factor(m, c)
        if f > 1:
            dw2 = fused_wgrad(
                x.reshape(m // f, f * c), _tile_vec(scale, f),
                _tile_vec(shift, f), dz.reshape(m // f, f * k),
                residual=None if residual is None
                else residual.reshape(m // f, f * c),
                out_dtype=out_dtype, interpret=interpret)
            # true dW is the sum of the diagonal (C, K) blocks
            dw4 = dw2.reshape(f, c, f, k)
            idx = jnp.arange(f)
            return dw4[idx, :, idx, :].sum(0)
    es = _esize(x)
    per_row = es * (k * 2 + c * (2
                                 + (2 if residual is not None else 0)))
    x3, bg = _as_grc(x, _rows_cap(per_row, 4 * c * k, 512))
    g, r, _ = x3.shape
    dz3 = dz.reshape(g, r, k)
    n_mt = g // bg
    with_residual = residual is not None
    r3 = residual.reshape(g, r, c) if with_residual else \
        jnp.zeros((1, 1, c), x.dtype)

    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_wgrad_kernel, n_mt=n_mt,
                               with_residual=with_residual)
    dw = pl.pallas_call(
        kernel,
        grid=(n_mt,),
        in_specs=[
            pl.BlockSpec((bg, r, c), lambda i: (i, 0, 0)),      # x
            pl.BlockSpec((1, c), lambda i: (0, 0)),             # scale
            pl.BlockSpec((1, c), lambda i: (0, 0)),             # shift
            (pl.BlockSpec((bg, r, c), lambda i: (i, 0, 0))
             if with_residual else
             pl.BlockSpec((1, 1, c), lambda i: (0, 0, 0))),     # residual
            pl.BlockSpec((bg, r, k), lambda i: (i, 0, 0)),      # dz
        ],
        out_specs=pl.BlockSpec((1, c, k), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, c, k), out_dtype),
        scratch_shapes=[pltpu.VMEM((c, k), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x3, scale.reshape(1, c).astype(jnp.float32),
      shift.reshape(1, c).astype(jnp.float32), r3, dz3)
    return dw[0]


# ---------------------------------------------------------------------------
# the differentiable op: BN(train, batch stats) → ReLU → 1×1 conv
# ---------------------------------------------------------------------------


def bn_relu_conv1x1(x, gamma, beta, mean, var, w, residual=None,
                    eps: float = 1e-5, want_y: bool = False):
    """Differentiable fused edge over channels-last views.

    x: (M, C) or (G, R, C) pre-BN activations (pass an NHWC activation as
    ``x4.reshape(N·H, W, C)`` — a free view; a full 2-D flatten physically
    repacks the tiled layout); mean/var: the *batch* stats of x over all
    rows (pass running stats at inference); w: (C, K); residual: shaped
    like x or None. Returns ``(z, zstats)`` or ``(z, zstats, y)`` — see
    :func:`fused_scale_relu_matmul`. Gradients implement the full BN-train
    backward (mean/var receive zeros; their chain-rule contribution is the
    q1/q2 correction inside dx — callers MUST pass stats of this same x).

    ``zstats`` is returned under ``stop_gradient``: it exists so the NEXT
    fused edge can form its batch stats without re-reading z, and that edge
    owns the stats' chain-rule contribution (its own q1/q2 correction on
    dz) — so no gradient may also flow through zstats, or it would be
    double-counted.
    """
    out = _bn_relu_conv1x1_vjp(x, gamma, beta, mean, var, w, residual,
                               eps, want_y)
    return (out[0], jax.lax.stop_gradient(out[1]), *out[2:])


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _bn_relu_conv1x1_vjp(x, gamma, beta, mean, var, w, residual,
                         eps: float = 1e-5, want_y: bool = False):
    return _fwd(x, gamma, beta, mean, var, w, residual, eps, want_y)


def _fold(gamma, beta, mean, var, eps):
    inv_std = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = gamma.astype(jnp.float32) * inv_std
    shift = beta.astype(jnp.float32) - mean.astype(jnp.float32) * scale
    return scale, shift, inv_std


def _fwd(x, gamma, beta, mean, var, w, residual, eps, want_y):
    scale, shift, _ = _fold(gamma, beta, mean, var, eps)
    return fused_scale_relu_matmul(x, scale, shift, w, residual,
                                   want_y=want_y)


def _fwd_rule(x, gamma, beta, mean, var, w, residual, eps, want_y):
    out = _fwd(x, gamma, beta, mean, var, w, residual, eps, want_y)
    return out, (x, gamma, beta, mean, var, w, residual)


def _bwd_rule(eps, want_y, res, cts):
    x, gamma, beta, mean, var, w, residual = res
    if want_y:
        dz, _dzstat, dy_extra = cts
    else:
        dz, _dzstat = cts
        dy_extra = None
    scale, shift, inv_std = _fold(gamma, beta, mean, var, eps)
    c = x.shape[-1]
    m = x.size // c

    dp, q = fused_dgrad(dz.astype(x.dtype), w, x, scale, shift,
                        mean, inv_std, residual=residual,
                        extra_dy=dy_extra)
    dbeta, dgamma = q[0], q[1]
    # BN-train dx: (γ/σ)(dp − dβ/M − x̂·dγ/M) — one XLA elementwise pass
    # (fusable with neighbors); x̂ recomputed from x. The per-channel
    # factors downcast to the data dtype (module-BN discipline: f32
    # intermediates would double this pass's HBM bytes).
    xhat = (x - mean.astype(x.dtype)) * inv_std.astype(x.dtype)
    dx = (scale.astype(x.dtype)
          * (dp - (dbeta / m).astype(x.dtype)
             - xhat * (dgamma / m).astype(x.dtype)))
    dw = fused_wgrad(x, scale, shift, dz.astype(x.dtype), residual=residual,
                     out_dtype=w.dtype)
    dresidual = dp if residual is not None else None
    zeros = lambda a: jnp.zeros_like(a)
    return (dx, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype),
            zeros(mean), zeros(var), dw, dresidual)


_bn_relu_conv1x1_vjp.defvjp(_fwd_rule, _bwd_rule)
