"""Pooled decode attention as a Pallas TPU kernel (+ jnp reference).

The serving engine's decode step is memory-bandwidth-bound: every token
re-reads the whole pooled KV cache ``(n_slots, max_len, heads, head_dim)``
to score ONE query per row. This module owns that inner loop:

* :func:`decode_attention_reference` — the jnp spelling (the math the
  engine's inline decode path computes): masked single-query attention
  over each row's own cache prefix ``0..pos[r]``, fp32 score/softmax
  accumulation;
* :func:`pooled_decode_attention` — the Pallas kernel (grid
  ``(n_rows, heads, kv_blocks)``, online softmax in VMEM scratch, one
  ``(block_l, head_dim)`` K/V tile resident per step) with the same
  ``interpret``-mode CPU fallback pattern as ``ops.flash_attention``
  (the dispatch probe is shared: ``utils.compat.auto_interpret``).

Quantized KV (the int8 serving path — see docs/serving.md "Quantized KV
cache"): K/V arrive as int8 with ONE fp32 scale per (row, head)
(``k_scale``/``v_scale``, shape ``(N, H)``). Because the scale is
constant over the positions and lanes being contracted, dequantization
FACTORS OUT of both matmuls exactly —

    scores[n,h,l] = (q . k_int8) * (qk_scale * k_scale[n,h])
    out[n,h,d]    = (p . v_int8) * v_scale[n,h]

so the kernel's K/V loads stay int8 end-to-end (half the HBM traffic of
bf16) and the dequant costs two scalar multiplies per (row, head), not
an elementwise pass over the cache. The reference computes the
identically-factored expression, so interpret-mode numerics match to
float round-off (pinned by tests/test_decode_attention.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from bigdl_tpu.ops.flash_attention import _out_struct

_NEG_INF = -1e30  # finite sentinel, same convention as flash/decode steps


def _auto_interpret() -> bool:
    from bigdl_tpu.utils.compat import auto_interpret

    return auto_interpret()


def _check_qkv(q, k, v, k_scale, v_scale):
    if q.ndim != 3 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(
            f"expected q (N, H, D) and k/v (N, L, H, D), got "
            f"{q.shape} / {k.shape} / {v.shape}")
    n, h, d = q.shape
    if k.shape != v.shape or k.shape[0] != n or k.shape[2:] != (h, d):
        raise ValueError(
            f"k/v {k.shape}/{v.shape} do not match q {q.shape}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "quantized KV needs BOTH k_scale and v_scale (or neither)")
    if k_scale is not None:
        if k_scale.shape != (n, h) or v_scale.shape != (n, h):
            raise ValueError(
                f"per-(row, head) scales must be ({n}, {h}), got "
                f"{k_scale.shape} / {v_scale.shape}")
        if k.dtype != jnp.int8 or v.dtype != jnp.int8:
            raise ValueError(
                f"scaled K/V must be int8, got {k.dtype}/{v.dtype}")


# --------------------------------------------------------------- reference


def decode_attention_reference(q, k, v, pos, k_scale=None, v_scale=None,
                               scale: Optional[float] = None,
                               out_dtype=None):
    """Masked single-query pooled attention, plain jnp — the numerics
    contract the kernel is tested against AND the CPU serving path.

    ``q``: (N, H, D) one query per pooled row; ``k``/``v``:
    (N, L, H, D) per-row caches (float, or int8 with (N, H) fp32
    ``k_scale``/``v_scale``); ``pos``: (N,) int32 — row ``r`` attends
    over its own cache columns ``0..pos[r]`` INCLUSIVE (the decode
    step's ``wpos``, where the new K/V was just written). Scores and
    softmax accumulate fp32 regardless of input dtype; the int8 path
    runs the q.k and p.v contractions on the RAW int8 values (cast to
    f32) and applies the per-(row, head) scales as factored-out scalar
    multiplies — exactly the kernel's fused-dequant math. Returns
    (N, H, D) in ``out_dtype`` (default: q's dtype)."""
    _check_qkv(q, k, v, k_scale, v_scale)
    n, h, d = q.shape
    L = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    if out_dtype is None:
        out_dtype = q.dtype
    valid = jnp.arange(L)[None, None, :] <= \
        jnp.asarray(pos, jnp.int32)[:, None, None]
    if k_scale is not None:
        s = jnp.einsum("nhd,nlhd->nhl", q.astype(jnp.float32),
                       k.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        s = s * (scale * k_scale.astype(jnp.float32))[:, :, None]
        p = jax.nn.softmax(jnp.where(valid, s, _NEG_INF), axis=-1)
        ctx = jnp.einsum("nhl,nlhd->nhd", p, v.astype(jnp.float32),
                         preferred_element_type=jnp.float32)
        ctx = ctx * v_scale.astype(jnp.float32)[:, :, None]
    else:
        # dots run on the cache dtype (bf16 stays on the fast MXU path)
        # with f32 accumulation — the flash-kernel convention
        s = jnp.einsum("nhd,nlhd->nhl", q.astype(k.dtype), k,
                       preferred_element_type=jnp.float32) * scale
        p = jax.nn.softmax(jnp.where(valid, s, _NEG_INF), axis=-1)
        ctx = jnp.einsum("nhl,nlhd->nhd", p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
    return ctx.astype(out_dtype)


# ------------------------------------------------------------------ kernel


def _decode_kernel(*refs, scale, quantized, skip):
    """Grid (N, H, n_l) — the KV-position axis is the INNER grid
    dimension, so one (block_l, D) K tile and one V tile are
    VMEM-resident per step and the online-softmax state carries across
    the position blocks in scratch (the flash-forward recipe, with a
    single query row per (n, h) program).

    Quantized layout: int8 K/V tiles are loaded RAW; the (row, head)
    scales enter as scalar factors — k_scale folds into the score
    scaling, v_scale multiplies the accumulated context once at the
    end (exact: both are constant over the contracted axes)."""
    if quantized:
        (q_ref, k_ref, v_ref, pos_ref, ks_ref, vs_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, pos_ref, o_ref,
         m_scr, l_scr, acc_scr) = refs
    j = pl.program_id(2)
    n_l = pl.num_programs(2)
    bl = k_ref.shape[1]
    pos = jnp.reshape(pos_ref[...], ())

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _step():
        q = q_ref[0]                                    # (1, D)
        k = k_ref[0, :, 0, :]                           # (BL, D)
        v = v_ref[0, :, 0, :]
        if quantized:
            ks = jnp.reshape(ks_ref[...], ())
            s = jax.lax.dot_general(
                q.astype(jnp.float32), k.astype(jnp.float32),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * (scale * ks)
        else:
            s = jax.lax.dot_general(
                q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
        cols = j * bl + jax.lax.broadcasted_iota(jnp.int32, (1, bl), 1)
        s = jnp.where(cols <= pos, s, _NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                          # (1, BL) f32
        alpha = jnp.exp(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        if quantized:
            pv = jnp.dot(p, v.astype(jnp.float32),
                         preferred_element_type=jnp.float32)
        else:
            pv = jnp.dot(p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
        acc_scr[...] = acc_scr[...] * alpha + pv

    if skip:
        # compiled path: key blocks entirely past the row's pos
        # contribute nothing — skip their gemms (most of the grid when
        # the pool is young). Interpret mode runs unconditionally: a
        # traced pl.when predicate is rejected there under shard_map
        # (same constraint the flash kernel documents).
        pl.when(j * bl <= pos)(_step)
    else:
        _step()

    @pl.when(j == n_l - 1)
    def _finish():
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        out = acc_scr[...] / l_safe
        if quantized:
            out = out * jnp.reshape(vs_ref[...], ())
        o_ref[0] = out.astype(o_ref.dtype)


def _auto_block_l(L: int) -> int:
    """KV-position tile length: the LARGEST of 512/384/256/128 that
    divides the 128-padded cache window (VMEM holds 2 int8/bf16
    (block, D) tiles + the (1, block) f32 score row — far under budget;
    bigger tiles amortize grid-step overhead on the short-query decode
    grid). Divisibility is the load-bearing part: a non-dividing block
    forces :func:`pooled_decode_attention` to ``jnp.pad`` the K/V
    operands, and on the per-step decode hot path that pad is a full
    copy of the entire pooled cache — the exact HBM traffic this kernel
    exists to avoid. Any 128-multiple window (every real serving
    ``max_len``) gets pad 0 here; only sub-128 or ragged windows pay
    the (small-cache) pad."""
    padded = ((max(L, 1) + 127) // 128) * 128
    for b in (512, 384, 256, 128):
        if padded % b == 0:
            return b
    return 128


def pooled_decode_attention(q, k, v, pos, k_scale=None, v_scale=None,
                            scale: Optional[float] = None,
                            block: Optional[int] = None,
                            interpret: Optional[bool] = None,
                            out_dtype=None):
    """Pallas pooled decode attention over slot-indexed KV.

    Same contract as :func:`decode_attention_reference` (q ``(N, H, D)``,
    k/v ``(N, L, H, D)`` float or int8-with-``(N, H)``-scales, per-row
    inclusive ``pos``), computed by the tiled online-softmax kernel.
    ``block`` is the KV-position tile length (None = auto);
    ``interpret=None`` auto-selects Pallas interpreter mode off-TPU via
    the shared ``utils.compat.auto_interpret`` probe. The cache window
    is right-padded to a block multiple when needed — padded columns
    sit beyond every row's ``pos`` and are masked like any other
    out-of-window position."""
    from jax.experimental.pallas import tpu as pltpu

    from bigdl_tpu.utils.compat import pallas_tpu_compiler_params

    _check_qkv(q, k, v, k_scale, v_scale)
    n, h, d = q.shape
    L = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    if out_dtype is None:
        out_dtype = q.dtype
    if interpret is None:
        interpret = _auto_interpret()
    if block is None:
        block = _auto_block_l(L)
    quantized = k_scale is not None
    pad = (-L) % block
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    lp = L + pad
    pos2 = jnp.asarray(pos, jnp.int32).reshape(n, 1)
    grid = (n, h, lp // block)
    qblk = pl.BlockSpec((1, 1, d), lambda n_, h_, j: (n_, h_, 0))
    kblk = pl.BlockSpec((1, block, 1, d), lambda n_, h_, j: (n_, j, h_, 0))
    posblk = pl.BlockSpec((1, 1), lambda n_, h_, j: (n_, 0))
    sblk = pl.BlockSpec((1, 1), lambda n_, h_, j: (n_, h_))
    operands = [q, k, v, pos2]
    in_specs = [qblk, kblk, kblk, posblk]
    if quantized:
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
        in_specs += [sblk, sblk]
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale),
                          quantized=quantized, skip=not interpret),
        grid=grid,
        in_specs=in_specs,
        out_specs=qblk,
        out_shape=_out_struct((n, h, d), out_dtype, *operands),
        scratch_shapes=[
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, d), jnp.float32),
        ],
        compiler_params=None if interpret else pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)
    return out


def decode_attention(q, k, v, pos, k_scale=None, v_scale=None,
                     scale: Optional[float] = None,
                     block: Optional[int] = None,
                     interpret: Optional[bool] = None,
                     impl: str = "auto", out_dtype=None):
    """The serving steps' dispatch point: ``impl="auto"`` runs the
    compiled Pallas kernel on TPU and the jnp reference elsewhere
    (interpret-mode Pallas is an emulator — correct but far too slow
    for the CPU CI serving loop); ``"kernel"``/``"reference"`` force a
    path (tests pin kernel-vs-reference numerics with
    ``impl="kernel", interpret=True``)."""
    if impl not in ("auto", "kernel", "reference"):
        raise ValueError(f"unknown impl {impl!r}")
    if impl == "auto":
        impl = "reference" if _auto_interpret() else "kernel"
    if impl == "reference":
        return decode_attention_reference(
            q, k, v, pos, k_scale=k_scale, v_scale=v_scale, scale=scale,
            out_dtype=out_dtype)
    return pooled_decode_attention(
        q, k, v, pos, k_scale=k_scale, v_scale=v_scale, scale=scale,
        block=block, interpret=interpret, out_dtype=out_dtype)
