"""Pallas (Mosaic) TPU kernels — the hand-written hot-op layer.

Reference role (UNVERIFIED, SURVEY.md §0/§2.1): the reference's native math
backends (MKL/MKL-DNN JNI) provide fast kernels under the generic layer
API. On TPU, XLA covers that role for gemms/convs; this package holds the
Pallas kernels for the ops XLA doesn't schedule optimally — flash
attention (fused online-softmax attention, linear memory in sequence
length), pooled decode attention (the serving engine's memory-bound
single-query inner loop, with fused int8-KV dequantization), and the
fused BN→ReLU→1×1-conv training edge (prologue fusion XLA cannot do
across a batch-stats barrier).
"""

from bigdl_tpu.ops.decode_attention import (
    decode_attention, decode_attention_reference, pooled_decode_attention,
)
from bigdl_tpu.ops.flash_attention import flash_attention
from bigdl_tpu.ops.fused_conv import bn_relu_conv1x1

__all__ = ["flash_attention", "bn_relu_conv1x1", "decode_attention",
           "decode_attention_reference", "pooled_decode_attention"]
