"""Distributed pod-training example: the full resilience surface in one
script — data-parallel DistriOptimizer over a mesh, compressed gradient
exchange, sharded in-training validation, async checkpoints, preemption
handling, and (optionally) the BlockManager-analog blockstore mode with
straggler gradient-drop.

Reference (UNVERIFIED, SURVEY.md §0): the shape of
``models/resnet/TrainImageNet.scala`` / ``models/lenet/Train.scala`` mains
(scopt option parser + Engine.init + Optimizer wiring), re-targeted at a
TPU pod.

Single host (1 process, all local chips):

    python -m bigdl_tpu.examples.distributed_pod -b 64 --maxIteration 20

Pod (one process per host; scheduler SIGTERMs are survived via
handle_preemption + resume):

    python -m bigdl_tpu.examples.distributed_pod \
        --coordinator host0:9999 --nProcs 4 --procId $RANK \
        -b 1024 --checkpoint /ckpt --resume

Straggler-tolerant DCN mode (the reference's dropPercentage):

    ... --parameterMode blockstore --dropPercentage 0.05
"""

from __future__ import annotations

import argparse


def main(argv=None):
    import numpy as np

    p = argparse.ArgumentParser(description="pod training example")
    p.add_argument("--coordinator", default=None,
                   help="jax.distributed coordinator host:port")
    p.add_argument("--nProcs", type=int, default=1)
    p.add_argument("--procId", type=int, default=0)
    p.add_argument("-b", "--batchSize", type=int, default=64,
                   help="GLOBAL batch size (reference semantics)")
    p.add_argument("--learningRate", type=float, default=0.05)
    p.add_argument("--maxIteration", type=int, default=20)
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--parameterMode", default="partitioned",
                   choices=["partitioned", "allreduce", "blockstore"])
    p.add_argument("--compress", default=None,
                   choices=[None, "bf16", "fp16"])
    p.add_argument("--dropPercentage", type=float, default=0.0)
    p.add_argument("--nSamples", type=int, default=512)
    args = p.parse_args(argv)

    import jax

    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import (
        Optimizer, SGD, Top1Accuracy, TrainingPreempted, Trigger,
    )
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random_gen import RNG

    if args.coordinator:
        Engine.init_distributed(coordinator_address=args.coordinator,
                                num_processes=args.nProcs,
                                process_id=args.procId)

    RNG.set_seed(42)
    rs = np.random.RandomState(0)
    samples = [Sample(rs.rand(1, 28, 28).astype(np.float32),
                      np.float32(i % 10 + 1))
               for i in range(args.nSamples)]
    train_ds = DataSet.distributed(samples)
    val_ds = DataSet.distributed(
        [Sample(rs.rand(1, 28, 28).astype(np.float32),
                np.float32(i % 10 + 1)) for i in range(128)])

    kw = {}
    if args.parameterMode != "blockstore":
        from jax.sharding import Mesh

        kw["mesh"] = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
    opt = Optimizer(
        model=LeNet5(10), dataset=train_ds,
        criterion=ClassNLLCriterion(), batch_size=args.batchSize,
        end_trigger=Trigger.max_iteration(args.maxIteration),
        parameter_mode=args.parameterMode, compress=args.compress,
        **kw)
    opt.set_optim_method(SGD(learning_rate=args.learningRate,
                             momentum=0.9))
    opt.set_validation(Trigger.several_iteration(10), val_ds,
                       [Top1Accuracy()], batch_size=args.batchSize)
    if args.dropPercentage > 0:
        opt.set_drop_module_property(args.dropPercentage)
    if args.checkpoint:
        # every rank may be given the SAME durable path (preemption
        # survival needs shared storage — a preempted VM's local disk is
        # gone): the Optimizer suffixes it per-rank (proc_<rank>), so
        # per-rank opt_state shards never race on one orbax target nor
        # silently restore another rank's same-shaped slice
        opt.set_checkpoint(args.checkpoint, Trigger.several_iteration(5),
                           backend="orbax_async")
        opt.handle_preemption()

    try:
        trained = opt.optimize(resume=args.resume)
    except TrainingPreempted as e:
        print(f"evicted cleanly: {e} — restart with --resume")
        return None
    ws, _ = trained.parameters()
    n = sum(int(np.asarray(w).size) for w in ws)
    print(f"done: {n} parameters trained, last loss recorded in metrics")
    return trained


if __name__ == "__main__":
    main()
