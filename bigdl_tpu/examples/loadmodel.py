"""Load-model example: run inference with a model from ANY supported format.

Reference (UNVERIFIED, SURVEY.md §0): ``example/loadmodel`` — loads a
BigDL / Caffe / TensorFlow model and evaluates it.

    python -m bigdl_tpu.examples.loadmodel --modelType bigdl --model m.bigdl
    python -m bigdl_tpu.examples.loadmodel --modelType caffe \
        --caffeDefPath deploy.prototxt --model weights.caffemodel
    python -m bigdl_tpu.examples.loadmodel --modelType tf \
        --model frozen.pb --tfInputs x --tfOutputs prob
"""

from __future__ import annotations

import argparse

import numpy as np


def load_any(args):
    if args.modelType == "bigdl":
        import zipfile

        from bigdl_tpu.nn.module import AbstractModule

        # structured snapshots are zips; legacy Module.save blobs are pickle
        if zipfile.is_zipfile(args.model):
            return AbstractModule.load_module(args.model)
        return AbstractModule.load(args.model)
    if args.modelType == "caffe":
        from bigdl_tpu.utils.caffe_loader import CaffeLoader

        if not args.caffeDefPath:
            raise SystemExit(
                "--caffeDefPath (deploy prototxt) is required with "
                "--modelType caffe")
        return CaffeLoader.load(args.caffeDefPath, args.model)
    if args.modelType == "tf":
        from bigdl_tpu.utils.tf_loader import TensorflowLoader

        return TensorflowLoader.load(
            args.model, args.tfInputs.split(","), args.tfOutputs.split(","))
    raise ValueError(f"unknown modelType {args.modelType}")


def main(argv=None):
    p = argparse.ArgumentParser(description="load + predict with any model")
    p.add_argument("--modelType", required=True,
                   choices=["bigdl", "caffe", "tf"])
    p.add_argument("--model", required=True)
    p.add_argument("--caffeDefPath", default=None)
    p.add_argument("--tfInputs", default="input")
    p.add_argument("--tfOutputs", default="output")
    p.add_argument("--inputShape", default="3,224,224",
                   help="comma-separated, batch excluded")
    p.add_argument("-b", "--batchSize", type=int, default=4)
    args = p.parse_args(argv)

    model = load_any(args)
    shape = tuple(int(s) for s in args.inputShape.split(","))
    x = np.random.rand(args.batchSize, *shape).astype(np.float32)
    out = model.evaluate().predict(x, batch_size=args.batchSize)
    out = np.asarray(out)
    print(f"model loaded: {type(model).__name__}; output shape {out.shape}; "
          f"top-1 ids {out.reshape(out.shape[0], -1).argmax(-1) + 1}")
    return model


if __name__ == "__main__":
    main()
