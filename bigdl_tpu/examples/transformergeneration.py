"""Transformer LM example: train, then generate (greedy/sampled + beam).

The decode side runs on the KV-cached incremental decoder
(``models.transformer.make_decode_step``): O(1) new compute per token, and
``beam_generate`` drives ``SequenceBeamSearch`` over the same cache.

    python -m bigdl_tpu.examples.transformergeneration \
        --synthetic 128 --maxEpoch 1 --beam 4 --genLen 16
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    from bigdl_tpu.models.transformer import (
        beam_generate, generate, train_main,
    )

    p = argparse.ArgumentParser(description="transformer train + generate")
    p.add_argument("--beam", type=int, default=4)
    p.add_argument("--genLen", type=int, default=16)
    p.add_argument("--topK", type=int, default=8)
    known, rest = p.parse_known_args(argv)

    model = train_main(rest)
    model.evaluate()

    prompt = [1, 2, 3]
    greedy = generate(model, prompt, length=known.genLen, temperature=0.0)
    sampled = generate(model, prompt, length=known.genLen, temperature=0.9,
                       top_k=known.topK, seed=7)
    print("greedy :", " ".join(map(str, greedy)))
    print("sampled:", " ".join(map(str, sampled)))

    seqs, scores = beam_generate(model, prompt, beam_size=known.beam,
                                 decode_length=known.genLen)
    for b in range(known.beam):
        ids = " ".join(str(int(t)) for t in seqs[b])
        print(f"beam {b}  score {scores[b]:8.3f}  {ids}")
    return model


if __name__ == "__main__":
    main()
