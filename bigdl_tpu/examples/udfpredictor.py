"""UDF-predictor example: wrap a trained text classifier as a reusable
predict function applied over a stream of documents.

Reference (UNVERIFIED, SURVEY.md §0): ``example/udfpredictor`` — registers a
BigDL model as a Spark SQL UDF and applies it to a DataFrame of texts. The
Spark-SQL surface becomes a plain Python callable (the TPU-era "UDF"):
``make_udf(model, dictionary, seq_len)`` returns ``predict(texts) -> labels``
backed by ONE compiled eval step.

    python -m bigdl_tpu.examples.udfpredictor          # self-contained demo
"""

from __future__ import annotations

from typing import Callable, List, Sequence

import numpy as np


def make_udf(model, dictionary, seq_len: int) -> Callable:
    """Build the predict-UDF: tokenizes, pads, batches, argmaxes."""
    from bigdl_tpu.dataset.text import simple_tokenize
    from bigdl_tpu.optim.evaluator import Predictor

    predictor = Predictor(model.evaluate())

    def predict(texts: Sequence[str]) -> List[int]:
        rows = []
        for t in texts:
            ids = [dictionary.get_index(w) + 1 for w in simple_tokenize(t)]
            ids = (ids[:seq_len] + [0] * (seq_len - len(ids)))[:seq_len]
            rows.append(np.asarray(ids, np.float32))
        scores = np.asarray(predictor.predict(np.stack(rows),
                                              batch_size=len(rows)))
        return list(scores.argmax(-1) + 1)

    return predict


def main(argv=None):
    """Self-contained demo: train a tiny classifier, serve it as a UDF."""
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.dataset.text import Dictionary, simple_tokenize
    from bigdl_tpu.models.textclassifier import TextClassifier
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim import Adagrad, Optimizer, Trigger
    from bigdl_tpu.utils.random_gen import RNG

    RNG.set_seed(7)

    corpus = {
        1: ["the market rallied as stocks rose", "shares gained on earnings",
            "the index closed higher on trade news"] * 4,
        2: ["the team won the final game", "a late goal sealed the match",
            "the players celebrated the championship"] * 4,
    }
    docs = [(t, c) for c, ts in corpus.items() for t in ts]
    d = Dictionary([simple_tokenize(t) for t, _ in docs])
    seq_len = 8
    samples = []
    for t, c in docs:
        ids = [d.get_index(w) + 1 for w in simple_tokenize(t)]
        ids = (ids[:seq_len] + [0] * (seq_len - len(ids)))[:seq_len]
        samples.append(Sample(np.asarray(ids, np.float32), np.int32(c)))

    model = TextClassifier(2, embedding_dim=16, vocab_size=d.vocab_size(),
                           embedding_input=False)
    opt = Optimizer(model=model, dataset=samples,
                    criterion=ClassNLLCriterion(), batch_size=8)
    opt.set_optim_method(Adagrad(learning_rate=0.3))
    opt.set_end_when(Trigger.max_epoch(40))
    opt.optimize()

    predict = make_udf(model, d, seq_len)
    queries = ["stocks rose sharply on market gains",
               "a late goal sealed the championship for the players"]
    labels = predict(queries)
    for q, l in zip(queries, labels):
        print(f"[class {l}] {q}")
    return labels


if __name__ == "__main__":
    main()
