"""ML-pipeline example: DLClassifier inside a feature pipeline.

Reference (UNVERIFIED, SURVEY.md §0): ``example/MLPipeline`` /
``dlframes`` — wraps a BigDL model as a Spark-ML estimator
(``DLClassifier``) so it composes with feature transformers and a
train/evaluate pipeline. Same story here with the sklearn-style
``dlframes`` API: standardize → DLClassifier(MLP) → accuracy.

    python -m bigdl_tpu.examples.mlpipeline --samples 512 --maxEpoch 4
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    from bigdl_tpu.dlframes import DLClassifier
    from bigdl_tpu.nn import ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential

    p = argparse.ArgumentParser(description="DLClassifier pipeline example")
    p.add_argument("--samples", type=int, default=512)
    p.add_argument("--features", type=int, default=20)
    p.add_argument("--classes", type=int, default=3)
    p.add_argument("--maxEpoch", type=int, default=4)
    p.add_argument("--batchSize", type=int, default=64)
    args = p.parse_args(argv)

    # synthetic blobs: class c centered at c-dependent offset
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((args.classes, args.features)) * 2.0
    y = rng.integers(1, args.classes + 1, size=args.samples)  # 1-based
    X = centers[y - 1] + rng.standard_normal(
        (args.samples, args.features)).astype(np.float32)

    # pipeline stage 1: standardize (host feature transformer)
    mu, sd = X.mean(0), X.std(0) + 1e-6
    Xs = ((X - mu) / sd).astype(np.float32)

    model = (Sequential()
             .add(Linear(args.features, 32)).add(ReLU())
             .add(Linear(32, args.classes)).add(LogSoftMax()))
    clf = (DLClassifier(model, ClassNLLCriterion(), [args.features])
           .set_batch_size(args.batchSize)
           .set_max_epoch(args.maxEpoch)
           .set_learning_rate(0.05))
    fitted = clf.fit(Xs, y.astype(np.int32))
    pred = fitted.transform(Xs)
    acc = float((pred == y).mean())
    print(f"pipeline accuracy: {acc:.3f} over {args.samples} samples")
    return acc


if __name__ == "__main__":
    main()
