"""Image-classification example: classify an image folder with a zoo model.

Reference (UNVERIFIED, SURVEY.md §0): ``example/imageclassification`` —
loads a trained model and predicts over an image directory.

    python -m bigdl_tpu.examples.imageclassification \
        --model ck/model --folder ./images -b 32
"""

from __future__ import annotations

import argparse

import numpy as np


def main(argv=None):
    from bigdl_tpu.examples.loadmodel import load_any

    p = argparse.ArgumentParser(description="classify an image folder")
    p.add_argument("--model", required=True, help="model snapshot path")
    p.add_argument("--modelType", default="bigdl",
                   choices=["bigdl", "caffe", "tf"])
    p.add_argument("--caffeDefPath", default=None)
    p.add_argument("--tfInputs", default="input")
    p.add_argument("--tfOutputs", default="output")
    p.add_argument("-f", "--folder", required=True,
                   help="class-per-subdir image directory")
    p.add_argument("--imageSize", type=int, default=224)
    p.add_argument("-b", "--batchSize", type=int, default=32)
    args = p.parse_args(argv)

    from bigdl_tpu.dataset.image import image_folder_samples

    model = load_any(args)
    samples = image_folder_samples(args.folder, image_size=args.imageSize)
    X = np.stack([np.asarray(s.features[0]) for s in samples])
    # the canonical serving API (handles eval-mode switching internally)
    preds = model.predict_class(X, batch_size=args.batchSize)
    for s, c in zip(samples, preds):
        print(f"class {int(c)}  (true label {int(np.asarray(s.labels[0]))})")
    return preds


if __name__ == "__main__":
    main()
