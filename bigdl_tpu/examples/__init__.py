"""Example programs (reference ``.../bigdl/example/*`` — SURVEY.md §2.8)."""
