"""Language-model example: train the PTB RNN LM, then generate with beam
search.

Reference (UNVERIFIED, SURVEY.md §0): ``example/languagemodel`` — trains the
``models/rnn`` PTB model on a tokenized corpus. This example adds the
decode-side story: after training, the LM drives ``SequenceBeamSearch``
(one compiled ``lax.scan``) to generate continuations.

    python -m bigdl_tpu.examples.languagemodel --synthetic 256 --maxEpoch 1 \
        --beam 4 --genLen 12
"""

from __future__ import annotations

import numpy as np


def lm_step_fn(model):
    """Build ``symbols_to_logits(params_ignored, tokens, carry)`` from a
    trained ``PTBModel``-shaped Sequential (LookupTable → N×Recurrent(cell)
    → TimeDistributed(Linear) → LogSoftMax), any ``num_layers``."""
    import jax.numpy as jnp

    from bigdl_tpu.nn import LookupTable, MultiRNNCell, Recurrent, TimeDistributed

    lookup = model.modules[0]
    assert isinstance(lookup, LookupTable), "PTBModel-shaped model expected"
    recs = [(i, m) for i, m in enumerate(model.modules)
            if isinstance(m, Recurrent)]
    td_i, td = next((i, m) for i, m in enumerate(model.modules)
                    if isinstance(m, TimeDistributed))

    p = model.params
    lookup_p = p[model._child_key(0)]
    cell_ps = [p[model._child_key(i)][m._key()] for i, m in recs]
    lin_p = p[model._child_key(td_i)][td._key()]

    # drive the whole stack as one cell (params re-keyed to the stack's
    # naming so MultiRNNCell.step can dispatch)
    stack = MultiRNNCell([m.cell for _, m in recs])
    stack_p = {stack._key(i, c): cp
               for i, (c, cp) in enumerate(zip(stack.cells, cell_ps))}

    def step(params, tokens, carry):
        # beam tokens are 0-based class indices; word id = token + 1, and the
        # embedding row for word id w is w - 1 — so the row IS the token
        emb = jnp.take(lookup_p["weight"], tokens, axis=0)
        out, new_carry = stack.step(stack_p, emb, carry)
        logits = jnp.matmul(out, lin_p["weight"].T) + lin_p["bias"]
        return logits, new_carry

    return step, stack


def main(argv=None):
    import jax

    from bigdl_tpu.models.rnn import train_main
    from bigdl_tpu.nn.beam_search import beam_search

    import argparse

    p = argparse.ArgumentParser(description="LM train + beam-search generate")
    p.add_argument("--beam", type=int, default=4)
    p.add_argument("--genLen", type=int, default=12)
    p.add_argument("--sos", type=int, default=1)
    known, rest = p.parse_known_args(argv)

    model = train_main(rest)
    step, cell = lm_step_fn(model)
    vocab = model.modules[0].n_index

    K = known.beam
    carry0 = jax.tree_util.tree_map(
        lambda x: np.tile(np.asarray(x), (K,) + (1,) * (np.asarray(x).ndim - 1)),
        cell.init_carry(1))
    seqs, scores = beam_search(
        step, None, carry0, 1, K, vocab, known.genLen,
        sos_id=known.sos - 1, eos_id=vocab + 7, alpha=0.6)  # eos unreachable
    for k in range(K):
        # report 1-based word ids (class index + 1)
        ids = " ".join(str(int(t) + 1) for t in np.asarray(seqs)[0, k])
        print(f"beam {k}  score {float(np.asarray(scores)[0, k]):8.3f}  {ids}")
    return model


if __name__ == "__main__":
    main()
