"""Batched, length-bucketed admission for the serving engine.

PR 1 admitted requests ONE AT A TIME: each admission ran a private B=1
``make_prefill_step`` call, and every novel prompt length triggered a
fresh XLA trace MID-ADMISSION, stalling all in-flight rows for the
compile (the docs/serving.md operational caveat). The reference's core
scheduling lesson (SoCC'19: schedule work onto fixed, pre-compiled
executors instead of spawning per-job state) applies to prompt ingestion
just as much as to decode — and MLPerf-scale TPU practice shows bounding
the compiled-program set is what keeps admission latency flat under
ragged traffic.

:class:`AdmissionController` turns admission into a pooled,
shape-stable pipeline:

* waiting requests are grouped into POWER-OF-TWO length buckets
  (clamped at ``max_len``) — a bounded bucket set, so the set of
  compiled prefill programs is bounded by ``O(log max_len)`` buckets
  regardless of how many distinct prompt lengths traffic brings;
* each bucket prefills in ONE :func:`make_batch_prefill_step` call over
  a ``(B, L_bucket)`` right-padded token block with a per-row
  ``lengths`` vector. The row count B is FIXED (``prefill_rows``,
  default ``n_slots`` — an admission round never has more rows to
  fill; unfilled rows are zero-length ballast), so the
  compiled-program set is exactly ONE
  program per length bucket no matter how arrival timing groups the
  requests — admission never compiles mid-flight after the buckets are
  warm. (Ballast rows cost padding FLOPs; on the MXU a small fixed B
  is the cheap side of that trade, and shape stability is the point —
  it is also what keeps a future SHARDED prefill program reusable.);
* every produced row is scattered into its :class:`KVPool` slot through
  the existing donated scatter (``write_prefill(..., row=j)``);
* with a :class:`bigdl_tpu.serving.prefix_cache.PrefixCache` attached,
  each prompt first takes the longest-cached-prefix path: a FULL hit
  clones the cached carry straight into the pool (zero prefill work), a
  PARTIAL hit clones it and prefills only the suffix (the batch
  prefill's nonzero per-row start offsets), and finished prefills are
  inserted back so later requests hit.

The zero input carries (one per row bucket) are built once and reused
for every admission — jax arrays are immutable, so sharing them is free
(the same trick as the engine's old ``_zero_carry1``, per shape).

On a SHARDED engine (``serving/sharded.py``) this controller runs
unchanged: ``pool.alloc()`` is the balanced cross-shard allocator, and
every ``write_prefill(..., row=j)`` routes the prefilled row to the
slot's OWNING shard through the pool's mesh-pinned scatter
(slot → (shard, row) is ``pool.slot_shard``) — admission never needs to
know the mesh exists, which is what keeps the bucketed prefill programs
reusable across mesh shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.serving.prefix_cache import PrefixCache


@dataclass(frozen=True)
class Degrade:
    """A request's graceful-degradation knobs, applied AT ADMISSION when
    the engine is under pressure (queue depth ≥ the engine's
    ``degrade_at`` — see ``ServingEngine``): ``max_new_tokens`` caps the
    request's token budget (never raises it), ``draft_tokens`` replaces
    its speculative budget (``0`` disables speculation for the request —
    on a loaded engine the draft dispatches are pure added latency for
    everyone else in the batch). Both are per-row RUNTIME data of the
    already-compiled programs, so degrading traffic never recompiles —
    the same shape-stability rule every serving knob follows. ``None``
    fields leave the request untouched; a request with no ``degrade``
    attached is never degraded.

    The clamp is REVERTIBLE (PR 19): the engine's one degrade writer
    records the request's original limits, and when pressure drops
    while the row still WAITS (the static ``degrade_at`` path, or the
    autopilot's ``restore_waiting`` actuator) the originals come back
    — a burst's degrade must not outlive the burst."""

    max_new_tokens: Optional[int] = None
    draft_tokens: Optional[int] = None

    def __post_init__(self):
        if self.max_new_tokens is not None and self.max_new_tokens <= 0:
            raise ValueError(
                f"max_new_tokens must be positive, got "
                f"{self.max_new_tokens}")
        if self.draft_tokens is not None and self.draft_tokens < 0:
            raise ValueError(
                f"draft_tokens must be >= 0, got {self.draft_tokens}")


def bucket_len(n: int, cap: int) -> int:
    """The power-of-two length bucket for ``n`` tokens, clamped to
    ``cap`` (= max_len): 1, 2, 4, ... cap. Bucketing bounds the set of
    compiled prefill programs; the clamp keeps the block no wider than
    the cache (pad columns beyond a row's length are masked anyway)."""
    if n <= 0:
        raise ValueError(f"need a positive length, got {n}")
    b = 1
    while b < n:
        b <<= 1
    return min(b, cap)


class AdmissionController:
    """Groups admissions into bucketed batch-prefill calls (see module
    docstring). Owned by :class:`ServingEngine`; reads the engine's
    pool/scheduler/metrics and its cached batch-prefill step."""

    def __init__(self, engine, prefix_cache: Optional[PrefixCache] = None,
                 prefill_rows: int = 0) -> None:
        # engine is the owning ServingEngine (pool, scheduler, metrics,
        # params, jitted steps); the controller is its admission policy,
        # split out so the pieces stay independently testable
        self.engine = engine
        self.prefix_cache = prefix_cache
        # FIXED batch-prefill row count (module docstring): one compiled
        # shape per length bucket, independent of arrival grouping (an
        # admission round never has more than n_slots rows to fill)
        self.prefill_rows = int(prefill_rows) or engine.pool.n_slots
        # ONE shared fresh zero carry, built lazily and reused for every
        # admission (prefill never donates its carry and jax arrays are
        # immutable, so sharing the zero input is free)
        self._zero_carry_cache: Optional[dict] = None
        # (B, L) shapes routed through THIS controller — the bounded
        # compiled-program set this subsystem exists to enforce. The
        # serving/prefill_bucket_compiles counter instead counts shapes
        # new to the SHARED jitted step (cached per model/dtype), so a
        # second engine over a warm model reports zero compiles.
        self.traced_shapes: set = set()

    # -- streaming hooks (overridden by ChunkedAdmissionController) --------

    def pump(self) -> None:
        """Per-super-step streaming hook: batched admission does all
        its prefill work inside :meth:`admit`, so this is a no-op —
        the chunked controller (``serving/chunked.py``) overrides it to
        feed one budget of prompt chunks before the decode step."""

    def drop(self, slot: int) -> None:
        """Forget any per-slot streaming state (no-op here; the chunked
        controller drops the slot's chunk plan). Called by the engine
        whenever a slot is torn down mid-admission (cancel, fault
        eviction, preemption)."""

    # -- helpers -----------------------------------------------------------

    def _bind_next(self, partial: bool = False):
        """THE admission prologue, shared by the batched and chunked
        controllers so the loss-free-readmission invariants have one
        spelling: allocate a slot, bind the best waiting request
        (``partial=True`` binds mid-prefill — chunked), and handle the
        two zero-ingestion fast paths — an empty prefill list (1-token
        prompts start decoding at pos 0) and a PREEMPTED row's
        byte-exact ``resume_carry`` scatter. Returns ``(slot, req,
        pf)`` with ``pf`` None when the row needs no prompt
        ingestion."""
        eng = self.engine
        slot = eng.pool.alloc()
        assert slot is not None                # admissible() checked
        req = eng.scheduler.admit(slot, partial=partial)
        # the last fed token is the first decode input — exactly
        # generate()'s convention, so outputs match token-for-token.
        # Called BEFORE the resume check on purpose: its side effects
        # (req.next_token, the degrade knob) are required on the
        # restored path too, even though pf itself goes unused there
        pf = eng._admitted_prefill_tokens(req)
        payload = eng._resume_payload(req)
        if payload is not None:
            # byte-exact resume: the stashed/spilled row_state payload
            # (preemption stash, host tier, or disaggregated handoff)
            # restores whole — KV + scales + lanes + mirrors + draft —
            # and the slot skips _configure_slot's device reseeding
            eng.pool.restore_row(slot, payload)
            req.resume_carry = None
            eng._restored.add(slot)
            return slot, req, None
        if not pf:
            eng.pool.set_pos(slot, 0)
            return slot, req, None
        return slot, req, pf

    def _zero_carry(self) -> dict:
        if self._zero_carry_cache is None:
            self._zero_carry_cache = self.engine._pool_init(self.prefill_rows)
        return self._zero_carry_cache

    def _note_shape(self, B: int, L: int) -> None:
        self.traced_shapes.add((B, L))
        fn = self.engine._batch_prefill_fn
        seen = getattr(fn, "_traced_shapes", None)
        if seen is None:
            seen = fn._traced_shapes = set()
        if (B, L) not in seen:
            seen.add((B, L))
            self.engine.metrics.on_bucket_compile()

    @staticmethod
    def _carry_row(carry: dict, row: int) -> dict:
        """Row ``row`` of a multi-row carry as a B=1 carry (a device
        slice per leaf — what PrefixCache stores)."""
        return {k: v[row:row + 1] for k, v in carry.items()}

    # -- the admission pipeline --------------------------------------------

    def admit(self, n: int) -> None:
        """Admit ``n`` scheduler-approved requests: allocate slots,
        route each prompt through the prefix cache, then prefill the
        misses bucket-by-bucket.

        Admission covers READMISSION too: a preempted or fault-evicted
        request re-enters here with its emitted tokens in
        ``req.output``, so its "prompt" for prefill purposes is
        ``prompt + output`` (``eng._admitted_prefill_tokens``) — the
        replay contract that makes eviction loss-free. A PREEMPTED row
        carries its stashed KV slice (``req.resume_carry``) and
        scatters it straight back (zero prefill work, byte-exact);
        fault-evicted rows replay through the normal prefill pipeline
        (their carry was never trusted). A prefill dispatch that FAULTS
        (injected or real — serving/faults.py) requeues exactly its own
        rows and frees their slots; other buckets in the round admit
        normally."""
        from bigdl_tpu.serving.faults import FaultError

        eng = self.engine
        groups: Dict[int, List[Tuple]] = {}    # L_bucket -> (req, slot, pf)
        for _ in range(n):
            slot, req, pf = self._bind_next()
            if pf is None:
                continue
            if self.prefix_cache is not None:
                try:
                    if self._try_prefix(slot, req, pf):
                        continue
                except FaultError:
                    eng._recover_admission([(slot, req)])
                    continue
            groups.setdefault(bucket_len(len(pf), eng.max_len),
                              []).append((req, slot, pf))
        for L in sorted(groups):
            rows = groups[L]
            # a bucket larger than the row block prefills in chunks
            for lo in range(0, len(rows), self.prefill_rows):
                chunk = rows[lo:lo + self.prefill_rows]
                try:
                    self._prefill_bucket(L, chunk)
                except FaultError:
                    eng._recover_admission(
                        [(slot, req) for req, slot, _ in chunk])

    def _try_prefix(self, slot: int, req, pf: List[int]) -> bool:
        """The prefix-cache path: full hit → clone into the pool;
        partial hit → clone + prefill only the suffix. Returns False on
        a miss (the caller buckets the prompt normally). Lookups and
        inserts are NAMESPACED by the request's adapter id — K/V
        computed under one tenant's factors must never splice into
        another tenant's row (null-adapter traffic keeps today's shared
        namespace and hit rate)."""
        import jax.numpy as jnp
        import numpy as np

        eng = self.engine
        carry, matched, lease = self.prefix_cache.acquire(
            pf, adapter_id=req.adapter_id)
        eng.metrics.on_prefix_lookup(matched, len(pf))
        if matched == 0:
            return False
        try:
            if matched == len(pf):             # full hit: zero prefill work
                eng.pool.write_prefill(slot, carry, len(pf))
                return True
            S = len(pf) - matched
            L = bucket_len(S, eng.max_len)
            toks = np.zeros((1, L), np.int32)
            toks[0, :S] = pf[matched:]
            self._note_shape(1, L)
            # the cached carry's pos IS the start offset: the batch
            # prefill continues over the cached prefix, writing only
            # positions matched..len(pf)-1. NO completion fence (and no
            # phase timer — it would measure the launch, the ASY305
            # lie): the suffix prefill overlaps the decode step under
            # async dispatch, and the step's decode fence absorbs its
            # completion (docs/async_readiness.md cashed-in entry).
            _, out = eng._dispatch(
                "prefill", eng._batch_prefill_fn, eng.params,
                jnp.asarray(toks), np.asarray([S], np.int32), carry,
                *eng._prefill_adapter_args([req.adapter_id]))
            eng.metrics.on_prefill_batch(1, 1)
            eng.pool.write_prefill(slot, out, len(pf))
            self.prefix_cache.insert(pf, out, adapter_id=req.adapter_id)
            return True
        finally:
            self.prefix_cache.release(lease)

    def _prefill_bucket(self, L: int, rows: List[Tuple]) -> None:
        """ONE masked multi-row prefill for every miss in an L-bucket,
        then per-row scatter into the pool."""
        import jax.numpy as jnp
        import numpy as np

        eng = self.engine
        k = len(rows)
        B = self.prefill_rows
        toks = np.zeros((B, L), np.int32)
        lengths = np.zeros((B,), np.int32)     # pad rows stay ballast (0)
        aids = np.zeros((B,), np.int32)        # pad rows: null adapter
        for j, (req, _, pf) in enumerate(rows):
            toks[j, :len(pf)] = pf
            lengths[j] = len(pf)
            aids[j] = req.adapter_id
        self._note_shape(B, L)
        # NO completion fence, no phase timer: the bucket prefill is
        # the work async dispatch-ahead overlaps with the decode step —
        # the step's decode fence absorbs its completion, and a timer
        # here would measure only the launch (the ASY305 lie). The
        # PR 12 worksheet marked this site deletable
        # (docs/async_readiness.md).
        _, out = eng._dispatch("prefill", eng._batch_prefill_fn,
                               eng.params, jnp.asarray(toks), lengths,
                               self._zero_carry(),
                               *eng._prefill_adapter_args(aids))
        eng.metrics.on_prefill_batch(k, B)
        for j, (req, slot, pf) in enumerate(rows):
            eng.pool.write_prefill(slot, out, len(pf), row=j)
            if self.prefix_cache is not None:
                self.prefix_cache.insert(pf, self._carry_row(out, j),
                                         adapter_id=req.adapter_id)
