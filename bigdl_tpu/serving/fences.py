"""Declared device→host synchronization points for the serving plane.

The async dispatch-ahead refactor (ROADMAP "raw speed" item) lives or
dies on ONE discipline: the super-step loop must never force a device
sync it did not declare. jax dispatches asynchronously — the host is
free to queue the next chunk prefill or draft chain while the decode
step runs on device — until something reads a device value back
(``np.asarray``, ``float()``, ``.item()``, a Python branch on an
array), at which point the host silently stalls on the whole pending
pipeline. Those implicit syncs are exactly what the ASY3xx analyzer
rules inventory (docs/analysis.md); this module is the other half of
the contract — the ONE idiom a deliberate sync is allowed to wear, so
every host-crossing in the hot path is named, machine-checked, and
enumerable (``python -m bigdl_tpu.analysis --report sync-points``).

Two idioms, both over a CLOSED site vocabulary (:data:`FENCE_SITES`,
the ``FINISH_REASONS`` pattern — an unknown site raises here and the
analyzer's ASY302 flags it statically):

* :func:`fence` — the READBACK fence: one batched ``jax.device_get``
  of several small values (the per-step token/logprob/emit-count
  readback). Batching matters: N separate ``np.asarray`` calls are N
  host round-trips; one ``device_get`` of the tuple is one. The
  returned values are host ``np.ndarray``s — everything downstream is
  plain Python and never syncs again.
* :func:`fence_wait` — the COMPLETION fence: ``jax.block_until_ready``
  on a tree, no copy. This is what a *timer* needs — a phase timing
  read off the clock before the dispatched work finished measures
  launch latency, not work (the lie ASY305 flags) — and the designated
  home of ``block_until_ready`` (ASY302 flags the raw spelling on any
  hot-path-reachable function outside this module).

The async refactor's job is then mechanical: every ``fence``/
``fence_wait`` site in the sync-point inventory is a place the loop
currently stops; moving one later (a delayed consumer) or deleting one
(batched host bookkeeping) is a reviewable one-line diff the analyzer
keeps honest.
"""

from __future__ import annotations

#: THE closed fence-site vocabulary. Every deliberate device→host sync
#: in the serving plane names one of these; the analyzer extracts this
#: frozenset (cross-module) and ASY302 flags both unknown site strings
#: and ``block_until_ready`` spelled outside this module.
FENCE_SITES = frozenset({
    "decode",    # the per-step token/logprob readback — consumed by the
                 # engine's DELAYED consumer (the dispatch-ahead window;
                 # see DELAYED_CONSUMER_SITES below)
    "verify",    # the speculative super-step's verify readback
    "draft",     # completion of the chained draft dispatches (timing)
    "prefill",   # vocabulary-reserved: the prefill completion fences
                 # were DELETED in PR 15 (prefill dispatches overlap
                 # the decode step — docs/async_readiness.md's
                 # cashed-in entries), so no shipped site spells this
                 # today; the name stays legal for a deliberate
                 # prefill wait (e.g. a debugging pin) so re-adding
                 # one is a diff, not a vocabulary change
    "transfer",  # KV-row handoff serialization (disagg.pack_payload):
                 # one batched readback of every payload leaf
})


#: THE closed dispatch-ahead vocabulary, the FENCE_SITES pattern lifted
#: to the multi-step window (PR 20 — the cashed-in async refactor).
#:
#: ``WINDOW_KNOBS`` names the engine knobs a dispatch-ahead window may
#: be bounded by: the analyzer's ASY308 demands every window-depth
#: guard (a ``len(<window>)`` comparison controlling dispatch or
#: consumption) reference one of these attributes — a bare loop
#: counter or a literal depth is vocabulary drift, exactly like an
#: unknown fence site string.
WINDOW_KNOBS = frozenset({
    "dispatch_ahead",   # ServingEngine(dispatch_ahead=W): in-flight
                        # decode dispatches beyond the one being
                        # consumed (W=0 = consume-immediately, the
                        # pre-window engine)
})

#: ``DELAYED_CONSUMER_SITES`` names the fence sites whose readback is
#: allowed to sit BEHIND the window — consumed by the delayed consumer
#: one-or-more dispatches after it was issued. Exactly the sites here
#: may appear in a window-consuming unit; any other fence reachable
#: from a window-DISPATCHING unit re-serializes the window by accident
#: and ASY309 flags it. The census in tests/test_serving_async.py
#: proves the serving tree has exactly ONE such site.
DELAYED_CONSUMER_SITES = frozenset({
    "decode",   # the engine's per-step token/logprob readback — THE
                # delayed-consumer site (ServingEngine._consume_window
                # fences the OLDEST in-flight dispatch while newer
                # ones keep the device fed). The speculative plane's
                # "verify" site stays an immediate consumer: each
                # super-step's draft budgets are a host decision made
                # from the previous verify readback, so its window
                # depth is structurally 0 (docs/serving.md).
})


def _check_site(site: str) -> None:
    if site not in FENCE_SITES:
        raise ValueError(
            f"unknown fence site {site!r} — add it to "
            f"fences.FENCE_SITES first; known: {sorted(FENCE_SITES)}")


def fence(site: str, *values):
    """THE declared readback: one batched ``jax.device_get`` of
    ``values``, returning host ``np.ndarray``s (a single value comes
    back bare, several as a tuple). The one place per super-step the
    host is ALLOWED to wait on the device — downstream bookkeeping
    runs on the returned host arrays and never syncs again."""
    import jax

    _check_site(site)
    out = jax.device_get(tuple(values))
    return out[0] if len(out) == 1 else out


def fence_wait(site: str, tree):
    """THE declared completion wait: ``jax.block_until_ready`` on
    ``tree`` (returned unchanged, still on device — no copy). Timers
    bracket device work with this so the elapsed time measures the
    work, not the launch."""
    import jax

    _check_site(site)
    return jax.block_until_ready(tree)
