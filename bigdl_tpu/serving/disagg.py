"""Disaggregated serving: a prefill pool and a decode pool with KV-row
handoff (the DistServe/Splitwise pattern, PAPERS.md).

One engine interleaves prompt ingestion and decode on one device, so a
burst of long prompts steals decode steps from every in-flight row —
the interference chunked admission measures (``serving/decode_gap_s``)
and bounds, but cannot eliminate: the bound is still paid from the
decode budget. Past one host the fix is structural: run admission
(prefill + prefix cache) on a PREFILL POOL, run the decode/sample/
verify super-step on a DECODE POOL, and hand each finished KV row
across. Decode rows then never wait on anyone's prompt, and each pool
scales on its own axis (prefill is MXU-bound, decode weight-read-bound
— ``benchmarks/pod_projection.py`` prices the split).

The pieces were already lying around, which is why this module is thin:

* ``KVPool.row_state()`` serializes EVERYTHING a row carries (K/V +
  int8 scales + ``pos``, RNG lane, penalty counts, prompt mask, the
  ``chunk_done``/``chunk_target`` host mirrors, the draft-carry slice)
  and ``restore_row()`` is its byte-identical inverse — the SAME API
  the engine's loss-free preemption stash speaks, so stash and handoff
  can never drift apart field by field;
* ``Request.resume_carry`` is the engine's existing "this row arrives
  with its state attached" handle — a handed-off request is admitted
  into the decode pool exactly like a preempted row resuming;
* ``block_store`` is a working cross-process byte-transfer layer — the
  production-shaped :class:`BlockStoreTransfer` backend rides it, and
  :class:`InProcessTransfer` serializes through the same codec so the
  in-process tests exercise the real wire format.

Every engine contract is preserved (pinned by
tests/test_serving_disagg.py and ``serving_bench --scenario disagg``):

* **token identity** — per-row streams depend only on the row's own
  carry + params, so splitting admission and decode across pools
  changes WHERE state lives, never what any row computes: greedy and
  fixed-seed sampled outputs are token-identical to the monolithic
  :class:`~bigdl_tpu.serving.engine.ServingEngine`, through prefix
  hits, evict/readmit inside the decode pool, and fault recovery.
  Sampling lanes ride the payload (seeded by the prefill worker from
  the GLOBAL request id), so a decode worker reproduces the stream
  without knowing the request's seed;
* **zero extra compiles per pool** — every worker wraps a stock
  ``ServingEngine`` over the same model, and the per-(model, dtype)
  step caches are process-wide: N decode pools share ONE compiled
  decode (or verify) program, and the prefill pool shares the bucketed
  prefill set;
* **closed accounting** — shed/deadline/infeasible dispositions land
  at the prefill door, eos/stop/length/error at the decode pool, and
  the front end's ledger union keeps every ``finish_<reason>`` counter
  summing to the submitted total. New handoff observability:
  ``serving/handoffs``, ``serving/transfer_bytes``,
  ``serving/transfer_s``, and per-pool occupancies.

The wire payload is a CLOSED schema (:data:`ROW_PAYLOAD_KEYS`) checked
statically: the analyzer's SRV202 rule reads this declaration
(cross-module, like the carry-key schema it extends) and flags any
subscript on a ``payload``-named dict whose key is not in it — a
typo'd transfer key is machine-caught before it ships a row that
restores wrong.

    from bigdl_tpu.serving import DisaggregatedEngine

    eng = DisaggregatedEngine(lm, prefill_slots=8, decode_slots=8,
                              decode_pools=2, prefix_cache=True)
    rid = eng.submit([3, 7, 2], max_new_tokens=32)
    outs = eng.drain()                  # {rid: 1-based token ids}
    eng.metrics.summary()["serving/handoffs"]
"""

from __future__ import annotations

import json
import struct
from collections import deque
from dataclasses import asdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.parallel.block_store import (
    BlockStore, decode_array, encode_array,
)
from bigdl_tpu.serving.engine import ServingEngine
from bigdl_tpu.serving.faults import FaultError, default_clock
from bigdl_tpu.serving.fences import fence
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.sampling import SamplingParams
from bigdl_tpu.serving.scheduler import FINISHED, Request

#: THE serialized row-payload schema — every top-level key a handoff
#: payload may carry. ``carry`` is the B=1 target-carry slice (its own
#: keys are the SRV202 carry schema), ``draft`` the optional draft-carry
#: slice, ``chunk_done``/``chunk_target`` the host chunk mirrors, and
#: ``request`` the wire header's request metadata. Closed like
#: ``ServingMetrics.FINISH_REASONS``: the static analyzer (SRV202)
#: reads this declaration and flags any payload subscript outside it,
#: so a typo'd transfer key cannot silently drop a field on the floor.
ROW_PAYLOAD_KEYS = ("request", "carry", "draft", "chunk_done",
                    "chunk_target")

_WIRE_MAGIC = b"BDRH"                  # row-handoff wire format v1


# -- request metadata <-> wire header ---------------------------------------

def request_meta(req: Request) -> Dict:
    """The JSON-serializable request half of a handoff payload: enough
    to reconstruct the request at the decode pool with its GLOBAL id
    (the RNG-lane key is a function of (engine seed, req_id), so the
    id must survive the wire), its post-degrade budgets, and its
    stream-so-far (empty for the normal prefill-complete handoff; the
    general mid-stream form keeps the codec future-proof)."""
    return {
        "req_id": int(req.req_id),
        "prompt": [int(t) for t in req.prompt],
        "output": [int(t) for t in req.output],
        "logprobs": [float(v) for v in req.logprobs],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_id": int(req.eos_id),
        "sampling": asdict(req.sampling if req.sampling is not None
                           else SamplingParams()),
        "draft_tokens": req.draft_tokens,
        "priority": int(req.priority),
        "deadline_s": req.deadline_s,
        "submit_time": float(req.submit_time),
        "first_token_time": req.first_token_time,
    }


def request_from_meta(meta: Dict) -> Request:
    """Reconstruct a :class:`Request` from its wire header (the decode
    side of :func:`request_meta`). ``seq`` stays unset — the receiving
    scheduler assigns its own arrival order, which is handoff order."""
    sp = dict(meta["sampling"])
    req = Request(
        req_id=int(meta["req_id"]),
        prompt=[int(t) for t in meta["prompt"]],
        max_new_tokens=int(meta["max_new_tokens"]),
        eos_id=int(meta["eos_id"]),
        sampling=SamplingParams(**sp),
        draft_tokens=meta.get("draft_tokens"),
        priority=int(meta.get("priority", 0)),
        deadline_s=meta.get("deadline_s"),
        submit_time=float(meta.get("submit_time", 0.0)))
    req.output = [int(t) for t in meta.get("output", ())]
    req.logprobs = [float(v) for v in meta.get("logprobs", ())]
    req.first_token_time = meta.get("first_token_time")
    return req


# -- the wire codec ---------------------------------------------------------

def pack_payload(meta: Dict, payload: Dict) -> bytes:
    """Serialize one handoff — request header + ``KVPool.row_state``
    payload — to bytes: a JSON header (request metadata, chunk mirrors,
    and the ORDERED carry/draft key lists) followed by one
    length-prefixed :func:`~bigdl_tpu.parallel.block_store.encode_array`
    blob per leaf. Every leaf rides the self-describing array codec, so
    the receiver needs no out-of-band dtype/shape agreement (bf16 and
    int8 carries round-trip bitwise)."""
    carry = payload["carry"]
    draft = payload.get("draft")
    head = {
        "request": meta,
        "chunk_done": int(payload["chunk_done"]),
        "chunk_target": int(payload["chunk_target"]),
        "carry_keys": sorted(carry),
        "draft_keys": None if draft is None else sorted(draft),
    }
    hj = json.dumps(head).encode()
    parts = [_WIRE_MAGIC, struct.pack("<q", len(hj)), hj]
    # serialization IS a device→host crossing, so it wears the declared
    # fence idiom (serving/fences.py): ONE batched device_get of every
    # payload leaf instead of a hidden sync per array (ASY301)
    ordered = [carry[k] for k in head["carry_keys"]]
    if draft is not None:
        ordered += [draft[k] for k in head["draft_keys"]]
    host = fence("transfer", *ordered)
    if len(ordered) == 1:
        host = (host,)
    for arr in host:
        blob = encode_array(arr)
        parts.append(struct.pack("<q", len(blob)))
        parts.append(blob)
    return b"".join(parts)


def unpack_payload(blob: bytes) -> Tuple[Dict, Dict]:
    """Inverse of :func:`pack_payload`: ``(request metadata, row_state
    payload)`` with numpy leaves — exactly what ``KVPool.restore_row``
    accepts."""
    if blob[:4] != _WIRE_MAGIC:
        raise ValueError("not a row-handoff payload")
    off = 4
    (nh,) = struct.unpack_from("<q", blob, off)
    off += 8
    head = json.loads(blob[off:off + nh].decode())
    off += nh

    def _arrays(keys):
        nonlocal off
        out = {}
        for k in keys:
            (nb,) = struct.unpack_from("<q", blob, off)
            off += 8
            out[k] = decode_array(blob[off:off + nb])
            off += nb
        return {k: v[None] if v.ndim == 0 else v for k, v in out.items()}

    payload = {
        "carry": _arrays(head["carry_keys"]),
        "draft": (None if head["draft_keys"] is None
                  else _arrays(head["draft_keys"])),
        "chunk_done": int(head["chunk_done"]),
        "chunk_target": int(head["chunk_target"]),
    }
    return head["request"], payload


# -- transfer backends ------------------------------------------------------

class KVTransfer:
    """One ordered byte channel from the prefill pool to ONE decode
    worker. ``send`` publishes a packed handoff; ``recv`` returns the
    next pending payload or None when the channel is empty (never
    blocks — the decode loop polls between steps). Backends:
    :class:`InProcessTransfer` (a deque, for tests and the in-process
    engine) and :class:`BlockStoreTransfer` (any
    :class:`~bigdl_tpu.parallel.block_store.BlockStore` — the
    cross-process production shape). Both carry the SAME packed bytes,
    so the in-process tests exercise the real wire format."""

    def send(self, blob: bytes) -> None:
        raise NotImplementedError

    def recv(self) -> Optional[bytes]:
        raise NotImplementedError

    def pending(self) -> int:
        """Sent-but-not-received payloads (drain/idle bookkeeping)."""
        raise NotImplementedError


class InProcessTransfer(KVTransfer):
    """Same-process queue backend: a deque of packed payloads."""

    def __init__(self) -> None:
        self._q: deque = deque()

    def send(self, blob: bytes) -> None:
        self._q.append(bytes(blob))

    def recv(self) -> Optional[bytes]:
        return self._q.popleft() if self._q else None

    def pending(self) -> int:
        return len(self._q)


class BlockStoreTransfer(KVTransfer):
    """Cross-process backend over a :class:`BlockStore`: sender and
    receiver each track their own monotone sequence number, so the
    channel is ordered with no coordination beyond the store itself
    (``FsBlockStore`` for same-host processes,
    ``CoordServiceBlockStore`` for a jax.distributed pod — the same
    backends the gradient exchange already runs on). Received keys are
    deleted, so the store never grows past the in-flight window.
    ``pending()`` probes the receiver's NEXT key only — cheap, and
    sufficient for the drain loop's "anything left?" question."""

    def __init__(self, store: BlockStore, channel: str = "disagg") -> None:
        self.store = store
        self.channel = str(channel)
        self._sent = 0
        self._received = 0

    def _key(self, n: int) -> str:
        return f"{self.channel}/row_{n:08d}"

    def send(self, blob: bytes) -> None:
        self.store.put(self._key(self._sent), blob)
        self._sent += 1

    def recv(self) -> Optional[bytes]:
        blob = self.store.try_get(self._key(self._received))
        if blob is None:
            return None
        self.store.delete(self._key(self._received))
        self._received += 1
        return blob

    def pending(self) -> int:
        # when sender and receiver share this object (the in-process
        # engine), the counters give the EXACT in-flight depth — the
        # least-loaded router needs the real number, or a same-step
        # burst all lands on whichever worker tied at "1". A pure
        # receiver (its own process; _sent == 0) falls back to a cheap
        # existence probe of its next key — never a payload fetch
        n = self._sent - self._received
        if n > 0:
            return n
        return 1 if self.store.contains(self._key(self._received)) else 0


# -- the prefill pool -------------------------------------------------------

class PrefillWorker:
    """Owns ADMISSION: the waiting queue, batched or chunked prompt
    ingestion, the prefix cache, sampling-lane seeding, and — on
    speculative configs — the draft-cache prefill. Produces COMPLETED
    KV rows: every pump, rows whose prompts are fully resident are
    serialized via ``pool.row_state()`` and released (slot freed for
    the next admission wave), never decoded here.

    Wraps a stock :class:`ServingEngine`, so every admission behavior —
    bucketed compile-bounded prefill, chunked streaming, prefix-cache
    reuse, backpressure/deadline shedding at the door, admission-side
    fault recovery — is the SAME code the monolithic engine runs, and
    the compiled prefill programs are shared through the per-(model,
    dtype) step caches.

    ``transfer`` is optional: with one attached (the standalone
    cross-process shape), :meth:`pump` packs and sends each finished
    row itself, requeueing loss-free on a failed send; without one (the
    in-process :class:`DisaggregatedEngine` shape) it returns
    ``(request, payload)`` pairs and the front end routes them."""

    def __init__(self, model, n_slots: int = 8,
                 transfer: Optional[KVTransfer] = None,
                 **engine_kw) -> None:
        self.engine = ServingEngine(model, n_slots=n_slots, **engine_kw)
        self.transfer = transfer
        self._peak_occupancy = 0.0

    def submit(self, *args, **kwargs) -> int:
        """Queue one request (the :meth:`ServingEngine.submit`
        surface, including backpressure shedding at the door)."""
        return self.engine.submit(*args, **kwargs)

    def _release(self, slot: int, req: Request) -> None:
        # the row leaves this pool entirely: its lifecycle continues at
        # a decode worker, so it is popped (not finished) and its slot
        # returns to the free list for the next admission wave
        del self.engine.scheduler.running[slot]
        req.slot = None
        self.engine.pool.free(slot)
        self.engine._configured.discard(slot)
        self.engine._restored.discard(slot)

    def requeue(self, req: Request, payload: Dict) -> None:
        """Loss-free return of a handoff that could not be delivered
        (fault during pack or transfer): the payload goes back on the
        request and it re-enters the queue at its ORIGINAL arrival
        key — at the next pump it restores byte-identically (no
        prefill replay) and hands off again. BOUNDED by the engine
        watchdog's ``max_retries`` (the step-recovery budget): a
        persistently failing fabric fails the REQUEST with
        ``finish_reason='error'`` instead of wedging ``drain()`` in a
        restore→pack→send loop forever — the same liveness contract
        the step watchdog enforces."""
        eng = self.engine
        req.retries += 1
        mr = eng.watchdog.max_retries
        if mr is not None and req.retries > mr:
            eng._ledger_finish(req, "error", eng._clock())
            return
        req.resume_carry = payload
        eng.scheduler.submit(req)
        eng.metrics.on_retry()

    def pump(self) -> List[Tuple[Request, Dict]]:
        """One admission super-step: deadline/feasibility drops, slot
        binding, bucketed (or chunked) prefill, then serialize-and-
        release every prompt-complete row. Returns the finished
        ``(request, row_state payload)`` pairs (empty when a transfer
        is attached — those were sent)."""
        eng = self.engine
        eng._admit()
        if eng.admitter is not None:
            eng.admitter.pump()
        # sample occupancy at its per-pump PEAK — after admission,
        # BEFORE the completed rows release their slots (post-release
        # the batched pool is empty by construction, and a pool-sizing
        # signal that always reads 0 can never fire)
        self._peak_occupancy = eng.pool.occupancy()
        out: List[Tuple[Request, Dict]] = []
        for slot, req in list(eng.scheduler.running.items()):
            if slot not in eng._configured:
                try:
                    # seeds the row's RNG lane/penalty counts (and the
                    # draft cache) so the payload carries them — the
                    # decode pool restores, never reseeds
                    eng._configure_slot(slot, req)
                except FaultError:
                    eng._recover_admission([(slot, req)])
                    continue
            payload = eng.pool.row_state(slot)
            self._release(slot, req)
            if self.transfer is None:
                out.append((req, payload))
                continue
            t0 = eng._clock()
            try:
                # pack INSIDE the recovery scope: the row already left
                # every scheduler table, so a serialization failure
                # (the transfer fence's device_get can surface real
                # device errors) must requeue it, not lose it
                blob = pack_payload(request_meta(req), payload)
                self.transfer.send(blob)
            except Exception:
                self.requeue(req, payload)
                continue
            eng.metrics.on_handoff(len(blob), eng._clock() - t0)
        return out

    def idle(self) -> bool:
        return self.engine.scheduler.idle()

    @property
    def occupancy(self) -> float:
        """The last pump's PEAK slot occupancy (admitted rows before
        their release) — the prefill pool-sizing signal. The live
        post-pump occupancy is 0 by construction under batched
        admission (completed rows hand off immediately)."""
        return self._peak_occupancy


# -- the decode pool --------------------------------------------------------

class DecodeWorker:
    """Owns the DECODE/sample/verify super-step over its own
    :class:`~bigdl_tpu.serving.kv_pool.KVPool`: handed-off rows arrive
    as ``row_state`` payloads, queue with ``resume_carry`` attached,
    and are admitted through the engine's byte-exact restore path — a
    handoff is admitted exactly like a preempted row resuming. Decode
    never runs prompt prefill EXCEPT fault-recovery replay (a suspect
    row's carry is never trusted — the engine re-prefills
    ``prompt + output``, sharing the prefill pool's compiled bucket
    programs through the step cache).

    Wraps a stock :class:`ServingEngine` too, so priority preemption
    inside the pool, the watchdog, fault injection, finish-reason
    accounting, and the per-pool metrics plane all come for free, and
    N decode workers share ONE compiled decode (or verify) program.
    ``seed`` must match the front end's: a fault-recovery replay
    rebuilds RNG lanes from (seed, GLOBAL req_id)."""

    def __init__(self, model, n_slots: int = 8,
                 transfer: Optional[KVTransfer] = None,
                 **engine_kw) -> None:
        self.engine = ServingEngine(model, n_slots=n_slots, **engine_kw)
        self.transfer = transfer if transfer is not None \
            else InProcessTransfer()

    def ingest(self, blob: bytes) -> int:
        """Accept one packed handoff: reconstruct the request (global
        id intact) with its payload as ``resume_carry`` and queue it —
        the next step's admission restores the row bitwise. Returns
        the request id."""
        meta, payload = unpack_payload(blob)
        req = request_from_meta(meta)
        req.resume_carry = payload
        self.engine.scheduler.submit(req)
        return req.req_id

    def poll(self) -> int:
        """Drain the transfer channel into the queue; returns how many
        rows arrived."""
        n = 0
        while True:
            blob = self.transfer.recv()
            if blob is None:
                return n
            self.ingest(blob)
            n += 1

    def step(self) -> Dict[int, int]:
        """Poll the channel, then one engine super-step (admission of
        restored rows + the batched decode/verify dispatch)."""
        self.poll()
        return self.engine.step()

    @property
    def load(self) -> int:
        """Rows this worker is responsible for (queued + slot-holding
        + still on the wire) — the least-loaded routing key."""
        return (self.engine.scheduler.queue_depth
                + self.engine.scheduler.active
                + self.transfer.pending())

    def idle(self) -> bool:
        return self.engine.scheduler.idle() \
            and self.transfer.pending() == 0

    @property
    def occupancy(self) -> float:
        return self.engine.pool.occupancy()


# -- the front end ----------------------------------------------------------

class DisaggregatedEngine:
    """The disaggregated serving plane behind the familiar engine
    surface (``submit``/``step``/``drain``/``result``/``cancel``):
    ONE :class:`PrefillWorker` (admission + prefix cache) feeding
    ``decode_pools`` :class:`DecodeWorker` s over per-worker transfer
    channels, least-loaded routing, and loss-free requeue when a
    transfer fails mid-handoff.

    Construction knobs mirror :class:`ServingEngine` where they apply:
    ``admission``/``chunk_budget``/``prefix_cache``/``max_queue``/
    ``deadline_feasibility`` shape the PREFILL pool (admission lives
    there); ``policy``/``preemption`` shape the DECODE pools
    (decode-side scheduling lives there — the prefill pool shares the
    policy for admission ORDER only); ``watchdog`` applies to both
    (step recovery in the decode pools; its ``max_retries`` also
    bounds the prefill side's transfer-retry budget); ``compute_dtype``/
    ``kv_dtype``/``speculative``/``seed``/``clock``/``faults`` apply to
    both (the pools must agree on the carry layout, and lanes are
    seeded from the global seed + request id). ``transfer_factory``
    builds one channel per decode worker (default
    :class:`InProcessTransfer`; pass e.g. ``lambda i:
    BlockStoreTransfer(store, f"decode{i}")`` for a shared store).

    Output parity with the monolithic engine is the module-level
    contract; the front end's own metrics add the handoff plane:
    ``serving/handoffs``, ``serving/transfer_bytes``,
    ``serving/transfer_s``, ``serving/prefill_occupancy``,
    ``serving/decode_occupancy`` (see ``ServingMetrics``)."""

    def __init__(self, model, prefill_slots: int = 8,
                 decode_slots: int = 8, decode_pools: int = 1,
                 admission: str = "batched",
                 chunk_budget: Optional[int] = None,
                 prefix_cache=None,
                 compute_dtype=None, kv_dtype: Optional[str] = None,
                 speculative=None, seed: int = 0,
                 policy: str = "prefill_priority",
                 preemption: Optional[bool] = None,
                 deadline_feasibility: bool = False,
                 max_queue: Optional[int] = None,
                 keep_finished: Optional[int] = None,
                 watchdog=None, faults=None, clock=None,
                 metrics: Optional[ServingMetrics] = None,
                 transfer_factory=None) -> None:
        if decode_pools < 1:
            raise ValueError(
                f"decode_pools must be >= 1, got {decode_pools}")
        self._clock = clock if clock is not None else default_clock
        self.metrics = metrics if metrics is not None else ServingMetrics()
        shared = dict(compute_dtype=compute_dtype, kv_dtype=kv_dtype,
                      speculative=speculative, seed=seed, clock=clock,
                      faults=faults, keep_finished=keep_finished)
        # the prefill pool shares the decode policy so priority
        # traffic orders ADMISSION too (no preemption there: its rows
        # drain to handoff every pump, so eviction has nothing to buy)
        self.prefill = PrefillWorker(
            model, n_slots=prefill_slots, admission=admission,
            chunk_budget=chunk_budget, prefix_cache=prefix_cache,
            deadline_feasibility=deadline_feasibility,
            max_queue=max_queue, policy=policy, preemption=False,
            watchdog=watchdog, **shared)
        make = transfer_factory if transfer_factory is not None \
            else (lambda i: InProcessTransfer())
        self.decoders = [
            DecodeWorker(model, n_slots=decode_slots, transfer=make(i),
                         policy=policy, preemption=preemption,
                         watchdog=watchdog, **shared)
            for i in range(decode_pools)]

    # -- request surface ---------------------------------------------------

    def submit(self, *args, **kwargs) -> int:
        """Queue one request at the prefill door (the full
        :meth:`ServingEngine.submit` surface — validation, sampling
        params, priorities/deadlines, backpressure shedding)."""
        return self.prefill.submit(*args, **kwargs)

    def _engines(self):
        yield self.prefill.engine
        for w in self.decoders:
            yield w.engine

    def _lookup(self, req_id: int) -> Optional[Request]:
        for eng in self._engines():
            req = eng._finished.get(req_id)
            if req is not None:
                return req
        return None

    def result(self, req_id: int) -> Optional[np.ndarray]:
        req = self._lookup(req_id)
        return None if req is None else np.asarray(req.output, np.int32)

    def pop_result(self, req_id: int) -> Optional[np.ndarray]:
        for eng in self._engines():
            out = eng.pop_result(req_id)
            if out is not None:
                return out
        return None

    def logprobs(self, req_id: int) -> Optional[np.ndarray]:
        req = self._lookup(req_id)
        return None if req is None else np.asarray(req.logprobs,
                                                   np.float32)

    def request(self, req_id: int) -> Optional[Request]:
        return self._lookup(req_id)

    def cancel(self, req_id: int) -> bool:
        """Cancel wherever the request currently lives: the prefill
        pool (waiting / mid-prefill) or its decode pool (queued-for-
        restore / decoding). With the in-process transfer there is no
        wire window — every handoff lands in its decode pool's
        scheduler within the same front-end step — but a row on a
        CROSS-PROCESS wire is not recalled: this returns False and the
        caller must re-issue the cancel after the row lands."""
        for eng in self._engines():
            if eng.cancel(req_id):
                return True
        return False

    # -- the serving loop --------------------------------------------------

    def _handoff(self, req: Request, payload: Dict) -> None:
        worker = min(self.decoders, key=lambda w: w.load)
        t0 = self._clock()
        try:
            # pack inside the recovery scope too — the row already
            # left the prefill scheduler, so pack AND send failures
            # both requeue loss-free (bounded by the watchdog's retry
            # budget; past it the request fails with reason 'error')
            blob = pack_payload(request_meta(req), payload)
            worker.transfer.send(blob)
        except Exception:
            self.prefill.requeue(req, payload)
            return
        self.metrics.on_handoff(len(blob), self._clock() - t0)

    def step(self) -> Dict[int, int]:
        """One front-end super-step: pump the prefill pool, route every
        finished row to the least-loaded decode worker, then one decode
        super-step per pool. Returns the merged ``{req_id: last emitted
        1-based token}`` across pools."""
        for req, payload in self.prefill.pump():
            self._handoff(req, payload)
        out: Dict[int, int] = {}
        for worker in self.decoders:
            out.update(worker.step())
        self.metrics.on_pool_occupancy(
            self.prefill.occupancy,
            [w.occupancy for w in self.decoders])
        return out

    def idle(self) -> bool:
        return self.prefill.idle() and all(w.idle()
                                           for w in self.decoders)

    def drain(self) -> Dict[int, np.ndarray]:
        """Step until every submitted request has finished; returns
        ``{req_id: generated ids}`` for all retained FINISHED requests
        across pools (the monolithic ``drain`` contract)."""
        while not self.idle():
            self.step()
        out: Dict[int, np.ndarray] = {}
        for eng in self._engines():
            for rid, req in eng._finished.items():
                if req.state == FINISHED:
                    out[rid] = np.asarray(req.output, np.int32)
        return out

    @property
    def queue_depth(self) -> int:
        return sum(eng.scheduler.queue_depth for eng in self._engines())

    @property
    def active(self) -> int:
        return sum(eng.scheduler.active for eng in self._engines())

    # -- introspection -----------------------------------------------------

    def pool_summaries(self) -> Dict[str, Dict[str, float]]:
        """Per-pool metric summaries (``prefill``, ``decode_<i>``) —
        the disaggregated twin of ``engine.metrics.summary()``."""
        out = {"prefill": self.prefill.engine.metrics.summary()}
        for i, w in enumerate(self.decoders):
            out[f"decode_{i}"] = w.engine.metrics.summary()
        return out

    def summary(self) -> Dict[str, float]:
        """One flat dict: the front end's handoff-plane counters plus
        the pool-summed dispositions (finish_<reason> counters keep
        summing to the submitted total across the split), aggregate
        token counts, and the worst decode pool's decode-gap p99."""
        out = dict(self.metrics.summary())
        sums: Dict[str, float] = {}
        gap_p99 = 0.0
        for name, s in self.pool_summaries().items():
            for k, v in s.items():
                if k.startswith("serving/finish_") or k in (
                        "serving/shed", "serving/preempted",
                        "serving/retries", "serving/recovered_rows",
                        "serving/deadline_missed", "serving/degraded",
                        "serving/infeasible", "serving/finished_in_slo"):
                    sums[k] = sums.get(k, 0.0) + v
            if name != "prefill":
                gap_p99 = max(gap_p99,
                              s.get("serving/decode_gap_p99_s", 0.0))
        out.update(sums)
        pm = self.prefill.engine.metrics.metrics
        n_sub, _ = pm.get("serving/submitted")
        if n_sub:
            out["serving/submitted"] = n_sub
            out["serving/goodput"] = \
                sums.get("serving/finished_in_slo", 0.0) / n_sub
        n_fin = n_tok = 0.0
        for eng in self._engines():
            f, _ = eng.metrics.metrics.get("serving/finished")
            t, _ = eng.metrics.metrics.get("serving/tokens_out")
            n_fin += f
            n_tok += t
        out["serving/finished"] = n_fin
        out["serving/tokens_out"] = n_tok
        if gap_p99:
            out["serving/decode_gap_p99_s"] = gap_p99
        return out
