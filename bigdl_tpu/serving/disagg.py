"""Disaggregated serving: a prefill pool and a decode pool with KV-row
handoff (the DistServe/Splitwise pattern, PAPERS.md).

One engine interleaves prompt ingestion and decode on one device, so a
burst of long prompts steals decode steps from every in-flight row —
the interference chunked admission measures (``serving/decode_gap_s``)
and bounds, but cannot eliminate: the bound is still paid from the
decode budget. Past one host the fix is structural: run admission
(prefill + prefix cache) on a PREFILL POOL, run the decode/sample/
verify super-step on a DECODE POOL, and hand each finished KV row
across. Decode rows then never wait on anyone's prompt, and each pool
scales on its own axis (prefill is MXU-bound, decode weight-read-bound
— ``benchmarks/pod_projection.py`` prices the split).

The pieces were already lying around, which is why this module is thin:

* ``KVPool.row_state()`` serializes EVERYTHING a row carries (K/V +
  int8 scales + ``pos``, RNG lane, penalty counts, prompt mask, the
  ``chunk_done``/``chunk_target`` host mirrors, the draft-carry slice)
  and ``restore_row()`` is its byte-identical inverse — the SAME API
  the engine's loss-free preemption stash speaks, so stash and handoff
  can never drift apart field by field;
* the host tier (``serving/kv_tier.py``, shared across every pool of
  the plane) is the engine's existing "this row arrives with its state
  parked" handle — a handed-off request is admitted into the decode
  pool exactly like a preempted row resuming, fetched from the same
  :class:`~bigdl_tpu.serving.kv_tier.TieredKVStore` that holds
  preemption spills and the front end's failover copies
  (``Request.resume_carry`` remains the tier-less in-memory spelling);
* ``block_store`` is a working cross-process byte-transfer layer — the
  production-shaped :class:`BlockStoreTransfer` backend rides it, and
  :class:`InProcessTransfer` serializes through the same codec so the
  in-process tests exercise the real wire format.

Every engine contract is preserved (pinned by
tests/test_serving_disagg.py and ``serving_bench --scenario disagg``):

* **token identity** — per-row streams depend only on the row's own
  carry + params, so splitting admission and decode across pools
  changes WHERE state lives, never what any row computes: greedy and
  fixed-seed sampled outputs are token-identical to the monolithic
  :class:`~bigdl_tpu.serving.engine.ServingEngine`, through prefix
  hits, evict/readmit inside the decode pool, and fault recovery.
  Sampling lanes ride the payload (seeded by the prefill worker from
  the GLOBAL request id), so a decode worker reproduces the stream
  without knowing the request's seed;
* **zero extra compiles per pool** — every worker wraps a stock
  ``ServingEngine`` over the same model, and the per-(model, dtype)
  step caches are process-wide: N decode pools share ONE compiled
  decode (or verify) program, and the prefill pool shares the bucketed
  prefill set;
* **closed accounting** — shed/deadline/infeasible dispositions land
  at the prefill door, eos/stop/length/error at the decode pool, and
  the front end's ledger union keeps every ``finish_<reason>`` counter
  summing to the submitted total. New handoff observability:
  ``serving/handoffs``, ``serving/transfer_bytes``,
  ``serving/transfer_s``, and per-pool occupancies.

The wire payload is a CLOSED schema (:data:`ROW_PAYLOAD_KEYS`) checked
statically: the analyzer's SRV202 rule reads this declaration
(cross-module, like the carry-key schema it extends) and flags any
subscript on a ``payload``-named dict whose key is not in it — a
typo'd transfer key is machine-caught before it ships a row that
restores wrong.

**Pool-level fault tolerance** (``serving/health.py``): each decode
pool is a FAILURE DOMAIN. The front end stamps a heartbeat per
completed worker super-step and records transfer-send verdicts, and
classifies every pool HEALTHY / SUSPECT / DEAD from missed beats and
consecutive send failures (:class:`~bigdl_tpu.serving.health.
PoolHealth`, VirtualClock-driven so tests never sleep). SUSPECT pools
stop receiving new handoffs; a DEAD pool triggers **failover**
(:meth:`DisaggregatedEngine._failover_pool`): handoffs still on the
wire are re-routed untouched (channel state outlives the pool
process), and every row the dead pool's host-side ledger owned is
reconstructed on a survivor — loss-free from the front end's
last-handoff stash where that copy is still current, else by
byte-identical prefill replay of ``prompt + emitted`` (the PR 8
row-recovery contract lifted to pool scope). Token streams are
IDENTICAL through a pool death (greedy and fixed-seed sampled,
pinned by tests/test_serving_health.py and ``serving_bench
--scenario failover``) and survivors compile NOTHING new.
:meth:`DisaggregatedEngine.drain_pool` is the GRACEFUL twin: it stops
routing to a live pool, migrates its rows out through the ordinary
``row_state`` wire handoff, and retires it to STANDBY — reactivation
is compile-free (the step caches are process-wide). On top of both
sits the occupancy **autoscaler** (:class:`~bigdl_tpu.serving.health.
OccupancyAutoscaler` over the existing ``prefill_occupancy``/
``decode_occupancy`` signals): sustained pressure activates standby
pools, sustained cold drains-and-retires the least-loaded pool, with
hysteresis (dead band + sustain window + cooldown) so it never flaps.
Transfer sends harden accordingly: per-request EXPONENTIAL BACKOFF
and a send timeout (:class:`~bigdl_tpu.serving.health.
TransferRetryConfig`; the injector's ``transfer_stall`` mode
simulates the hung fabric), receiver-side duplicate suppression by
request id, and cancel() sweeps handoffs still in a channel so a
decode pool never restores a cancelled row.

    from bigdl_tpu.serving import DisaggregatedEngine

    eng = DisaggregatedEngine(lm, prefill_slots=8, decode_slots=8,
                              decode_pools=2, prefix_cache=True)
    rid = eng.submit([3, 7, 2], max_new_tokens=32)
    outs = eng.drain()                  # {rid: 1-based token ids}
    eng.metrics.summary()["serving/handoffs"]
"""

from __future__ import annotations

import json
import struct
from collections import deque
from dataclasses import asdict
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from bigdl_tpu.parallel.block_store import (
    BlockStore, decode_array, encode_array,
)
from bigdl_tpu.serving.engine import ServingEngine
from bigdl_tpu.serving.faults import FaultError, default_clock
from bigdl_tpu.serving.fences import fence
from bigdl_tpu.serving.kv_tier import TieredKVStore
from bigdl_tpu.serving.health import (
    DEAD, HEALTHY, POOL_ACTIVE, POOL_DEAD, POOL_STANDBY, AutoscalerConfig,
    HealthConfig, OccupancyAutoscaler, PoolHealth, TransferRetryConfig,
)
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.sampling import SamplingParams
from bigdl_tpu.serving.scheduler import CANCELLED, FINISHED, Request

#: THE serialized row-payload schema — every top-level key a handoff
#: payload may carry. ``carry`` is the B=1 target-carry slice (its own
#: keys are the SRV202 carry schema), ``draft`` the optional draft-carry
#: slice, ``chunk_done``/``chunk_target`` the host chunk mirrors,
#: ``adapter`` the row's LoRA adapter slot id (``serving/lora.py`` —
#: rides the wire so a restored row keeps gathering its tenant's
#: factors), and ``request`` the wire header's request metadata. Closed
#: like ``ServingMetrics.FINISH_REASONS``: the static analyzer (SRV202)
#: reads this declaration and flags any payload subscript outside it,
#: so a typo'd transfer key cannot silently drop a field on the floor.
ROW_PAYLOAD_KEYS = ("request", "carry", "draft", "chunk_done",
                    "chunk_target", "adapter")

_WIRE_MAGIC = b"BDRH"                  # row-handoff wire format v1


# -- request metadata <-> wire header ---------------------------------------

def request_meta(req: Request) -> Dict:
    """The JSON-serializable request half of a handoff payload: enough
    to reconstruct the request at the decode pool with its GLOBAL id
    (the RNG-lane key is a function of (engine seed, req_id), so the
    id must survive the wire), its post-degrade budgets, and its
    stream-so-far (empty for the normal prefill-complete handoff; the
    general mid-stream form keeps the codec future-proof)."""
    return {
        "req_id": int(req.req_id),
        "prompt": [int(t) for t in req.prompt],
        "output": [int(t) for t in req.output],
        "logprobs": [float(v) for v in req.logprobs],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_id": int(req.eos_id),
        "sampling": asdict(req.sampling if req.sampling is not None
                           else SamplingParams()),
        "draft_tokens": req.draft_tokens,
        "priority": int(req.priority),
        "deadline_s": req.deadline_s,
        "submit_time": float(req.submit_time),
        "first_token_time": req.first_token_time,
        # fault-budget continuity: a row bounced across pools by
        # repeated failures must keep burning ONE watchdog retry
        # budget, not get a fresh one per pool
        "retries": int(req.retries),
        "preemptions": int(req.preemptions),
        # multi-tenant plane (serving/lora.py, serving/constrain.py):
        # the adapter id must survive the wire so the decode pool
        # gathers the same tenant's factors, and the constraint
        # travels as its AUTOMATON meta — never a cursor: the
        # receiver rebuilds the cursor from the emitted prefix
        # (constraint.cursor(req.output)), THE replay rule
        "adapter_id": int(req.adapter_id),
        "constraint": (None if req.constraint is None
                       else req.constraint.to_meta()),
    }


def request_from_meta(meta: Dict) -> Request:
    """Reconstruct a :class:`Request` from its wire header (the decode
    side of :func:`request_meta`). ``seq`` stays unset — the receiving
    scheduler assigns its own arrival order, which is handoff order."""
    sp = dict(meta["sampling"])
    req = Request(
        req_id=int(meta["req_id"]),
        prompt=[int(t) for t in meta["prompt"]],
        max_new_tokens=int(meta["max_new_tokens"]),
        eos_id=int(meta["eos_id"]),
        sampling=SamplingParams(**sp),
        draft_tokens=meta.get("draft_tokens"),
        priority=int(meta.get("priority", 0)),
        deadline_s=meta.get("deadline_s"),
        submit_time=float(meta.get("submit_time", 0.0)),
        adapter_id=int(meta.get("adapter_id", 0)))
    cmeta = meta.get("constraint")
    if cmeta is not None:
        from bigdl_tpu.serving.constrain import TokenDFA

        req.constraint = TokenDFA.from_meta(cmeta)
    req.output = [int(t) for t in meta.get("output", ())]
    req.logprobs = [float(v) for v in meta.get("logprobs", ())]
    req.first_token_time = meta.get("first_token_time")
    req.retries = int(meta.get("retries", 0))
    req.preemptions = int(meta.get("preemptions", 0))
    return req


# -- the wire codec ---------------------------------------------------------

def pack_payload(meta: Dict, payload: Optional[Dict]) -> bytes:
    """Serialize one handoff — request header + ``KVPool.row_state``
    payload — to bytes: a JSON header (request metadata, chunk mirrors,
    and the ORDERED carry/draft key lists) followed by one
    length-prefixed :func:`~bigdl_tpu.parallel.block_store.encode_array`
    blob per leaf. Every leaf rides the self-describing array codec, so
    the receiver needs no out-of-band dtype/shape agreement (bf16 and
    int8 carries round-trip bitwise).

    ``payload=None`` packs a META-ONLY handoff (``carry_keys`` null, no
    array blobs): the REPLAY form pool failover sends when a dead
    pool's row has no current state copy — the receiver reconstructs
    the request and replays ``prompt + emitted`` through prefill
    (byte-identical, the PR 8 recovery contract)."""
    if payload is None:
        head = {"request": meta, "chunk_done": 0, "chunk_target": 0,
                "carry_keys": None, "draft_keys": None}
        hj = json.dumps(head).encode()
        return b"".join([_WIRE_MAGIC, struct.pack("<q", len(hj)), hj])
    carry = payload["carry"]
    draft = payload.get("draft")
    head = {
        "request": meta,
        "chunk_done": int(payload["chunk_done"]),
        "chunk_target": int(payload["chunk_target"]),
        "adapter": int(payload["adapter"]),
        "carry_keys": sorted(carry),
        "draft_keys": None if draft is None else sorted(draft),
    }
    hj = json.dumps(head).encode()
    parts = [_WIRE_MAGIC, struct.pack("<q", len(hj)), hj]
    # serialization IS a device→host crossing, so it wears the declared
    # fence idiom (serving/fences.py): ONE batched device_get of every
    # payload leaf instead of a hidden sync per array (ASY301)
    ordered = [carry[k] for k in head["carry_keys"]]
    if draft is not None:
        ordered += [draft[k] for k in head["draft_keys"]]
    host = fence("transfer", *ordered)
    if len(ordered) == 1:
        host = (host,)
    for arr in host:
        blob = encode_array(arr)
        parts.append(struct.pack("<q", len(blob)))
        parts.append(blob)
    return b"".join(parts)


def payload_header(blob: bytes) -> Dict:
    """Just the JSON header of a packed handoff — request metadata and
    key lists, no array decode. The cheap read failover and the cancel
    sweep use for bookkeeping (is this stash copy still current? whose
    row is on this wire?) without touching the payload bytes."""
    if blob[:4] != _WIRE_MAGIC:
        raise ValueError("not a row-handoff payload")
    (nh,) = struct.unpack_from("<q", blob, 4)
    return json.loads(blob[12:12 + nh].decode())


def unpack_payload(blob: bytes) -> Tuple[Dict, Optional[Dict]]:
    """Inverse of :func:`pack_payload`: ``(request metadata, row_state
    payload)`` with numpy leaves — exactly what ``KVPool.restore_row``
    accepts. A meta-only (replay) handoff returns ``payload=None``."""
    if blob[:4] != _WIRE_MAGIC:
        raise ValueError("not a row-handoff payload")
    off = 4
    (nh,) = struct.unpack_from("<q", blob, off)
    off += 8
    head = json.loads(blob[off:off + nh].decode())
    off += nh
    if head["carry_keys"] is None:
        return head["request"], None

    def _arrays(keys):
        nonlocal off
        out = {}
        for k in keys:
            (nb,) = struct.unpack_from("<q", blob, off)
            off += 8
            out[k] = decode_array(blob[off:off + nb])
            off += nb
        return {k: v[None] if v.ndim == 0 else v for k, v in out.items()}

    payload = {
        "carry": _arrays(head["carry_keys"]),
        "draft": (None if head["draft_keys"] is None
                  else _arrays(head["draft_keys"])),
        "chunk_done": int(head["chunk_done"]),
        "chunk_target": int(head["chunk_target"]),
        "adapter": int(head.get("adapter", 0)),
    }
    return head["request"], payload


# -- transfer backends ------------------------------------------------------

class KVTransfer:
    """One ordered byte channel from the prefill pool to ONE decode
    worker. ``send`` publishes a packed handoff; ``recv`` returns the
    next pending payload or None when the channel is empty (never
    blocks — the decode loop polls between steps). Backends:
    :class:`InProcessTransfer` (a deque, for tests and the in-process
    engine) and :class:`BlockStoreTransfer` (any
    :class:`~bigdl_tpu.parallel.block_store.BlockStore` — the
    cross-process production shape). Both carry the SAME packed bytes,
    so the in-process tests exercise the real wire format."""

    def send(self, blob: bytes) -> None:
        raise NotImplementedError

    def recv(self) -> Optional[bytes]:
        raise NotImplementedError

    def pending(self) -> int:
        """Sent-but-not-received payloads (drain/idle bookkeeping)."""
        raise NotImplementedError


class InProcessTransfer(KVTransfer):
    """Same-process queue backend: a deque of packed payloads."""

    def __init__(self) -> None:
        self._q: deque = deque()

    def send(self, blob: bytes) -> None:
        self._q.append(bytes(blob))

    def recv(self) -> Optional[bytes]:
        return self._q.popleft() if self._q else None

    def pending(self) -> int:
        return len(self._q)


class BlockStoreTransfer(KVTransfer):
    """Cross-process backend over a :class:`BlockStore`: sender and
    receiver each track their own monotone sequence number, so the
    channel is ordered with no coordination beyond the store itself
    (``FsBlockStore`` for same-host processes,
    ``CoordServiceBlockStore`` for a jax.distributed pod — the same
    backends the gradient exchange already runs on). Received keys are
    deleted, so the store never grows past the in-flight window.
    ``pending()`` probes the receiver's NEXT key only — cheap, and
    sufficient for the drain loop's "anything left?" question."""

    def __init__(self, store: BlockStore, channel: str = "disagg") -> None:
        self.store = store
        self.channel = str(channel)
        self._sent = 0
        self._received = 0

    def _key(self, n: int) -> str:
        return f"{self.channel}/row_{n:08d}"

    def send(self, blob: bytes) -> None:
        self.store.put(self._key(self._sent), blob)
        self._sent += 1

    def recv(self) -> Optional[bytes]:
        blob = self.store.try_get(self._key(self._received))
        if blob is None:
            return None
        self.store.delete(self._key(self._received))
        self._received += 1
        return blob

    def pending(self) -> int:
        # when sender and receiver share this object (the in-process
        # engine), the counters give the EXACT in-flight depth — the
        # least-loaded router needs the real number, or a same-step
        # burst all lands on whichever worker tied at "1". A pure
        # receiver (its own process; _sent == 0) falls back to a cheap
        # existence probe of its next key — never a payload fetch
        n = self._sent - self._received
        if n > 0:
            return n
        return 1 if self.store.contains(self._key(self._received)) else 0


# -- the prefill pool -------------------------------------------------------

class PrefillWorker:
    """Owns ADMISSION: the waiting queue, batched or chunked prompt
    ingestion, the prefix cache, sampling-lane seeding, and — on
    speculative configs — the draft-cache prefill. Produces COMPLETED
    KV rows: every pump, rows whose prompts are fully resident are
    serialized via ``pool.row_state()`` and released (slot freed for
    the next admission wave), never decoded here.

    Wraps a stock :class:`ServingEngine`, so every admission behavior —
    bucketed compile-bounded prefill, chunked streaming, prefix-cache
    reuse, backpressure/deadline shedding at the door, admission-side
    fault recovery — is the SAME code the monolithic engine runs, and
    the compiled prefill programs are shared through the per-(model,
    dtype) step caches.

    ``transfer`` is optional: with one attached (the standalone
    cross-process shape), :meth:`pump` packs and sends each finished
    row itself, requeueing loss-free on a failed send; without one (the
    in-process :class:`DisaggregatedEngine` shape) it returns
    ``(request, payload)`` pairs and the front end routes them."""

    def __init__(self, model, n_slots: int = 8,
                 transfer: Optional[KVTransfer] = None,
                 retry: Optional[TransferRetryConfig] = None,
                 **engine_kw) -> None:
        self.engine = ServingEngine(model, n_slots=n_slots, **engine_kw)
        self.transfer = transfer
        self.retry = retry if retry is not None else TransferRetryConfig()
        self._peak_occupancy = 0.0
        # exponential-backoff parking lot: (due_time, request) entries
        # a failed handoff deferred — pump() releases them back into
        # the queue once the engine clock passes their due time, so a
        # down fabric is probed at a decaying rate instead of
        # hammered every pump
        self._deferred: List[Tuple[float, Request]] = []

    def submit(self, *args, **kwargs) -> int:
        """Queue one request (the :meth:`ServingEngine.submit`
        surface, including backpressure shedding at the door)."""
        return self.engine.submit(*args, **kwargs)

    def _release(self, slot: int, req: Request) -> Dict:
        # the row leaves this pool entirely: its lifecycle continues at
        # a decode worker, so its FULL row_state payload is captured
        # FIRST (a row may leave the tables only as a handoff payload,
        # a requeue, or a finish disposition — the SRV206 invariant),
        # then it is popped (not finished) and its slot returns to the
        # free list for the next admission wave
        payload = self.engine.pool.row_state(slot)
        del self.engine.scheduler.running[slot]
        req.slot = None
        self.engine.pool.free(slot)
        self.engine._configured.discard(slot)
        self.engine._restored.discard(slot)
        # the cursor never travels — the decode pool rebuilds it from
        # the emitted prefix at slot configuration (the replay rule)
        self.engine._constraints.pop(slot, None)
        return payload

    def requeue(self, req: Request, payload: Dict) -> None:
        """Loss-free return of a handoff that could not be delivered
        (fault during pack or transfer): the payload goes back on the
        request and it re-enters the queue at its ORIGINAL arrival
        key — at the next due pump it restores byte-identically (no
        prefill replay) and hands off again. Re-entry BACKS OFF
        exponentially per request (``TransferRetryConfig.delay`` on
        the engine clock — attempt n waits base·2^(n-1) up to the
        cap), and the whole loop is BOUNDED by the engine watchdog's
        ``max_retries`` (the step-recovery budget): a persistently
        failing fabric fails the REQUEST with
        ``finish_reason='error'`` instead of wedging ``drain()`` in a
        restore→pack→send loop forever — the same liveness contract
        the step watchdog enforces."""
        eng = self.engine
        req.retries += 1
        mr = eng.watchdog.max_retries
        if mr is not None and req.retries > mr:
            eng._ledger_finish(req, "error", eng._clock())
            return
        eng._spill_or_carry(req, payload)
        eng.metrics.on_retry()
        delay = self.retry.delay(req.retries)
        if delay > 0:
            self._deferred.append((eng._clock() + delay, req))
        else:
            eng.scheduler.submit(req)

    def send_handoff(self, transfer: KVTransfer, req: Request,
                     payload: Optional[Dict], metrics: ServingMetrics,
                     health: Optional[PoolHealth] = None
                     ) -> Optional[bytes]:
        """Pack and send one handoff through the guarded path: the
        send consults the engine's fault injector (site
        ``"transfer"`` — the ``transfer_stall`` mode lands
        here), a raise OR an elapsed time past the configured
        ``send_timeout_s`` requeues the request loss-free with
        backoff (delivery unconfirmed — the RECEIVER deduplicates by
        request id in case a slow send did land), and the verdict
        feeds the target pool's health record. Returns the packed
        blob on confirmed delivery, None when the request was
        requeued (or failed out past the retry budget)."""
        eng = self.engine
        t0 = eng._clock()
        try:
            # pack INSIDE the recovery scope: the row already left
            # every scheduler table, so a serialization failure
            # (the transfer fence's device_get can surface real
            # device errors) must requeue it, not lose it
            blob = pack_payload(request_meta(req), payload)
            # the send consults the injector DIRECTLY, not through
            # engine._dispatch: that routing is the compiled-step
            # discipline (SRV201), and a send moves host bytes — every
            # device byte was already fenced inside pack_payload, so
            # the elapsed time below measures real pack+send wall
            if eng._faults is not None:
                eng._faults.call("transfer", transfer.send, blob)
            else:
                transfer.send(blob)
        except Exception:
            if health is not None:
                health.on_transfer_failure()
            self.requeue(req, payload)
            return None
        elapsed = eng._clock() - t0
        to = self.retry.send_timeout_s
        if to is not None and elapsed > to:
            # the send returned, but past the timeout the caller had
            # already abandoned it: treat delivery as UNCONFIRMED —
            # resend after backoff (ingest-side dedup absorbs the
            # case where the slow send did land) and mark the fabric
            if health is not None:
                health.on_transfer_failure()
            metrics.on_transfer_timeout()
            self.requeue(req, payload)
            return None
        if health is not None:
            health.on_transfer_ok()
        metrics.on_handoff(len(blob), elapsed)
        return blob

    def pump(self) -> List[Tuple[Request, Dict]]:
        """One admission super-step: release due backoff entries,
        deadline/feasibility drops, slot binding, bucketed (or
        chunked) prefill, then serialize-and-release every
        prompt-complete row. Returns the finished ``(request,
        row_state payload)`` pairs (empty when a transfer is
        attached — those were sent)."""
        eng = self.engine
        now = eng._clock()
        if self._deferred:
            due = [e for e in self._deferred if e[0] <= now]
            if due:
                self._deferred = [e for e in self._deferred
                                  if e[0] > now]
                for _, req in due:
                    eng.scheduler.submit(req)
        eng._admit()
        if eng.admitter is not None:
            eng.admitter.pump()
        # sample occupancy at its per-pump PEAK — after admission,
        # BEFORE the completed rows release their slots (post-release
        # the batched pool is empty by construction, and a pool-sizing
        # signal that always reads 0 can never fire)
        self._peak_occupancy = eng.pool.occupancy()
        out: List[Tuple[Request, Dict]] = []
        for slot, req in list(eng.scheduler.running.items()):
            if slot not in eng._configured:
                try:
                    # seeds the row's RNG lane/penalty counts (and the
                    # draft cache) so the payload carries them — the
                    # decode pool restores, never reseeds
                    eng._configure_slot(slot, req)
                except FaultError:
                    eng._recover_admission([(slot, req)])
                    continue
            payload = self._release(slot, req)
            if self.transfer is None:
                out.append((req, payload))
                continue
            self.send_handoff(self.transfer, req, payload, eng.metrics)
        return out

    def idle(self) -> bool:
        return self.engine.scheduler.idle() and not self._deferred

    def cancel_deferred(self, req_id: int) -> Optional[Request]:
        """Remove and return a request parked in the backoff lot
        (failed/timed-out handoff awaiting its retry window), or None.
        Cancellation must reach it here: a deferred request is in NO
        scheduler and has no stash entry (the stash records confirmed
        deliveries only), so without this sweep it would be
        uncancellable until its resend."""
        for k, (_, req) in enumerate(self._deferred):
            if req.req_id == req_id:
                del self._deferred[k]
                return req
        return None

    @property
    def occupancy(self) -> float:
        """The last pump's PEAK slot occupancy (admitted rows before
        their release) — the prefill pool-sizing signal. The live
        post-pump occupancy is 0 by construction under batched
        admission (completed rows hand off immediately)."""
        return self._peak_occupancy


# -- the decode pool --------------------------------------------------------

class DecodeWorker:
    """Owns the DECODE/sample/verify super-step over its own
    :class:`~bigdl_tpu.serving.kv_pool.KVPool`: handed-off rows arrive
    as ``row_state`` payloads, queue with ``resume_carry`` attached,
    and are admitted through the engine's byte-exact restore path — a
    handoff is admitted exactly like a preempted row resuming. Decode
    never runs prompt prefill EXCEPT fault-recovery replay (a suspect
    row's carry is never trusted — the engine re-prefills
    ``prompt + output``, sharing the prefill pool's compiled bucket
    programs through the step cache).

    Wraps a stock :class:`ServingEngine` too, so priority preemption
    inside the pool, the watchdog, fault injection, finish-reason
    accounting, and the per-pool metrics plane all come for free, and
    N decode workers share ONE compiled decode (or verify) program.
    ``seed`` must match the front end's: a fault-recovery replay
    rebuilds RNG lanes from (seed, GLOBAL req_id)."""

    def __init__(self, model, n_slots: int = 8,
                 transfer: Optional[KVTransfer] = None,
                 cancelled: Optional[Set[int]] = None,
                 claims: Optional[Dict[int, "DecodeWorker"]] = None,
                 **engine_kw) -> None:
        self.engine = ServingEngine(model, n_slots=n_slots, **engine_kw)
        self.transfer = transfer if transfer is not None \
            else InProcessTransfer()
        # liveness: a killed pool (process crash) runs nothing — the
        # front end stops stepping it and its missed heartbeats (or an
        # immediate kill_pool) classify it DEAD (serving/health.py)
        self.alive = True
        # shared cancel-sweep set (DisaggregatedEngine.cancel): request
        # ids cancelled while their payload was still on the wire —
        # ingest drops them so a cancelled row is never restored
        self._cancelled = cancelled if cancelled is not None else set()
        # shared delivery-claims registry (req_id -> the worker that
        # last admitted it): duplicate suppression must span POOLS —
        # a timed-out resend routes least-loaded, so the copy can land
        # on a different pool than the slow original. Standalone
        # workers get a private dict (self-claims only).
        self._claims = claims if claims is not None else {}

    def _owns(self, req_id: int) -> bool:
        """Is this request already anywhere in the worker (queued,
        slot-holding, or finished)? The duplicate-suppression check
        behind at-least-once sends: a timed-out handoff is resent, and
        if the slow original DID land, the copy must be dropped."""
        eng = self.engine
        if req_id in eng._finished:
            return True
        sched = eng.scheduler
        return (any(r.req_id == req_id for r in sched.running.values())
                or any(r.req_id == req_id
                       for r in sched.partial.values())
                or any(e[1].req_id == req_id for e in sched._waiting))

    def ingest(self, blob: bytes) -> Optional[int]:
        """Accept one packed handoff: reconstruct the request (global
        id intact) with its payload as ``resume_carry`` and queue it —
        the next step's admission restores the row bitwise (or, for a
        meta-only REPLAY handoff, re-prefills ``prompt + emitted``
        byte-identically). Returns the request id, or None when the
        payload was dropped: swept as cancelled mid-flight, or a
        duplicate of a row some pool already owns (a timed-out send
        that landed after its resend — checked across POOLS through
        the shared claims registry, then locally). A claim whose
        worker no longer owns the row (failover/drain moved it out)
        does not block: legitimate re-ingest after migration."""
        meta, payload = unpack_payload(blob)
        rid = int(meta["req_id"])
        if rid in self._cancelled:
            return None
        holder = self._claims.get(rid)
        if holder is not None and holder is not self \
                and holder._owns(rid):
            return None                      # cross-pool duplicate
        if self._owns(rid):
            return None                      # same-pool duplicate
        req = request_from_meta(meta)
        if self.engine.tier is not None and payload is not None:
            # the packed wire bytes ARE the row's tier entry: park
            # them in the shared host tier instead of a per-request
            # blob — admission fetches them back currency-checked
            self.engine.tier.put_packed(blob, req_id=rid)
        else:
            req.resume_carry = payload
        self.engine.scheduler.submit(req)
        self._claims[rid] = self
        return rid

    def poll(self) -> int:
        """Drain the transfer channel into the queue; returns how many
        rows were accepted."""
        n = 0
        while True:
            blob = self.transfer.recv()
            if blob is None:
                return n
            if self.ingest(blob) is not None:
                n += 1

    def step(self) -> Dict[int, int]:
        """Poll the channel, then one engine super-step (admission of
        restored rows + the batched decode/verify dispatch). A dead
        worker steps nothing — a crashed process runs no code."""
        if not self.alive:
            return {}
        self.poll()
        return self.engine.step()

    @property
    def load(self) -> int:
        """Rows this worker is responsible for (queued + slot-holding
        + still on the wire) — the least-loaded routing key."""
        return (self.engine.scheduler.queue_depth
                + self.engine.scheduler.active
                + self.transfer.pending())

    def idle(self) -> bool:
        return self.engine.scheduler.idle() \
            and self.transfer.pending() == 0

    @property
    def occupancy(self) -> float:
        return self.engine.pool.occupancy()


# -- the front end ----------------------------------------------------------

class DisaggregatedEngine:
    """The disaggregated serving plane behind the familiar engine
    surface (``submit``/``step``/``drain``/``result``/``cancel``):
    ONE :class:`PrefillWorker` (admission + prefix cache) feeding
    ``decode_pools`` :class:`DecodeWorker` s over per-worker transfer
    channels, least-loaded routing, and loss-free requeue when a
    transfer fails mid-handoff.

    Construction knobs mirror :class:`ServingEngine` where they apply:
    ``admission``/``chunk_budget``/``prefix_cache``/``max_queue``/
    ``deadline_feasibility`` shape the PREFILL pool (admission lives
    there); ``policy``/``preemption`` shape the DECODE pools
    (decode-side scheduling lives there — the prefill pool shares the
    policy for admission ORDER only); ``watchdog`` applies to both
    (step recovery in the decode pools; its ``max_retries`` also
    bounds the prefill side's transfer-retry budget); ``compute_dtype``/
    ``kv_dtype``/``speculative``/``seed``/``clock``/``faults`` apply to
    both (the pools must agree on the carry layout, and lanes are
    seeded from the global seed + request id). ``transfer_factory``
    builds one channel per decode worker (default
    :class:`InProcessTransfer`; pass e.g. ``lambda i:
    BlockStoreTransfer(store, f"decode{i}")`` for a shared store).

    POOL LIFECYCLE knobs (``serving/health.py``; module docstring):
    ``standby_pools`` builds extra decode workers that start idle
    (weights resident, programs shared — activation is compile-free);
    ``health`` (a :class:`~bigdl_tpu.serving.health.HealthConfig`)
    sets the heartbeat/transfer-failure thresholds behind the
    HEALTHY/SUSPECT/DEAD classification; ``transfer_retry`` (a
    :class:`~bigdl_tpu.serving.health.TransferRetryConfig`) sets the
    send timeout and per-request exponential backoff; ``autoscaler``
    (an :class:`~bigdl_tpu.serving.health.AutoscalerConfig`, or
    ``True`` for defaults) turns on the occupancy control loop that
    activates standby pools under sustained pressure and
    drains-and-retires cold ones. ``kill_pool``/``drain_pool``/
    ``pool_states`` are the operator surface.

    Output parity with the monolithic engine is the module-level
    contract — through pool deaths included; the front end's own
    metrics add the handoff plane: ``serving/handoffs``,
    ``serving/transfer_bytes``, ``serving/transfer_s``,
    ``serving/prefill_occupancy``, ``serving/decode_occupancy``, and
    the lifecycle counters ``serving/pool_deaths``/``failovers``/
    ``failover_s``/``migrated_rows``/``replayed_rows``/
    ``transfer_timeouts``/``autoscale_up``/``autoscale_down`` (see
    ``ServingMetrics``)."""

    def __init__(self, model, prefill_slots: int = 8,
                 decode_slots: int = 8, decode_pools: int = 1,
                 admission: str = "batched",
                 chunk_budget: Optional[int] = None,
                 prefix_cache=None,
                 compute_dtype=None, kv_dtype: Optional[str] = None,
                 speculative=None, seed: int = 0,
                 policy: str = "prefill_priority",
                 preemption: Optional[bool] = None,
                 deadline_feasibility: bool = False,
                 max_queue: Optional[int] = None,
                 keep_finished: Optional[int] = None,
                 watchdog=None, faults=None, clock=None,
                 metrics: Optional[ServingMetrics] = None,
                 transfer_factory=None,
                 standby_pools: int = 0,
                 health: Optional[HealthConfig] = None,
                 transfer_retry: Optional[TransferRetryConfig] = None,
                 autoscaler=None, adapters=None, tier=None,
                 autopilot=None, dispatch_ahead: int = 0) -> None:
        if decode_pools < 1:
            raise ValueError(
                f"decode_pools must be >= 1, got {decode_pools}")
        if standby_pools < 0:
            raise ValueError(
                f"standby_pools must be >= 0, got {standby_pools}")
        self._clock = clock if clock is not None else default_clock
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.health_config = health if health is not None \
            else HealthConfig()
        self.transfer_retry = transfer_retry if transfer_retry is not None \
            else TransferRetryConfig()
        # ONE host KV tier (serving/kv_tier.py) shared by the prefill
        # engine and every decode worker — THE unified stash: the
        # prefill side's transfer-retry payloads, each decode pool's
        # preemption spills, and the front end's last-handoff failover
        # copies all live under the same keys and the same byte
        # budget. The disaggregated plane always runs tiered (the old
        # per-request stash blobs and front-end _stash dict are this
        # store now); attach_metrics is first-wins, so the front-end
        # metrics object is the single spill/fetch sink.
        if tier is None or tier is True:
            tier = TieredKVStore()
        self.tier = tier
        self.tier.attach_metrics(self.metrics, clock=self._clock)
        # ONE AdapterBank object shared by the prefill engine and every
        # decode worker: the gather programs agree on the bank shapes,
        # and the refcount taken at the prefill door (submit) is
        # released by whichever engine finally finishes the request —
        # one retain, one release, however many pools the row crosses
        shared = dict(compute_dtype=compute_dtype, kv_dtype=kv_dtype,
                      speculative=speculative, seed=seed, clock=clock,
                      faults=faults, keep_finished=keep_finished,
                      adapters=adapters, tier=tier)
        # the prefill pool shares the decode policy so priority
        # traffic orders ADMISSION too (no preemption there: its rows
        # drain to handoff every pump, so eviction has nothing to buy)
        self.prefill = PrefillWorker(
            model, n_slots=prefill_slots, admission=admission,
            chunk_budget=chunk_budget, prefix_cache=prefix_cache,
            deadline_feasibility=deadline_feasibility,
            max_queue=max_queue, policy=policy, preemption=False,
            watchdog=watchdog, retry=self.transfer_retry, **shared)
        make = transfer_factory if transfer_factory is not None \
            else (lambda i: InProcessTransfer())
        # cancel-sweep set + delivery-claims registry, SHARED with
        # every decode worker's ingest: ids cancelled while their
        # payload sat in a transfer channel, and which pool admitted
        # each row (cross-pool duplicate suppression for timed-out
        # resends that route to a different pool)
        self._cancelled: Set[int] = set()
        self._claims: Dict[int, DecodeWorker] = {}
        self.decoders = [
            DecodeWorker(model, n_slots=decode_slots, transfer=make(i),
                         policy=policy, preemption=preemption,
                         watchdog=watchdog, cancelled=self._cancelled,
                         claims=self._claims,
                         # the window lives in the decode loop; the
                         # prefill pool drains to handoff every pump,
                         # so dispatch-ahead has nothing to buy there
                         dispatch_ahead=dispatch_ahead, **shared)
            for i in range(decode_pools + standby_pools)]
        # pool lifecycle: the first decode_pools workers serve, the
        # rest wait warm on the bench (serving/health.py states)
        self._pool_state = [POOL_ACTIVE] * decode_pools \
            + [POOL_STANDBY] * standby_pools
        self._health = [PoolHealth(self._clock, self.health_config)
                        for _ in self.decoders]
        # (the last-handoff copies that used to live in a per-front-end
        # _stash dict are tier row entries now: THE loss-free half of
        # pool failover — a dead pool's row whose tier entry is still
        # current re-routes bitwise — and the cancel sweep's ledger
        # source; every engine's finish/cancel/shed disposition drops
        # its entry eagerly, so nothing lingers until a hygiene sweep)
        # the front end's own stepping cadence: heartbeat SILENCE is
        # only meaningful while the plane is being driven (see step())
        self._last_step_t: Optional[float] = None
        if autoscaler:
            cfg = autoscaler if isinstance(autoscaler, AutoscalerConfig) \
                else AutoscalerConfig()
            self._scaler: Optional[OccupancyAutoscaler] = \
                OccupancyAutoscaler(cfg)
        else:
            self._scaler = None
        # the SLO autopilot (serving/autopilot.py): the PREFILL engine
        # hosts the loop (it owns admission — chunk budget, degrade,
        # the priority key fold — and its clock is the plane's clock),
        # and the pool autoscaler registers on the same bus so scale
        # decisions land in the one actuation log every other knob
        # uses (_autoscale remains the executing site — it owns the
        # pool tables)
        self.autopilot = autopilot or None
        if self.autopilot is not None:
            self.autopilot.attach(self.prefill.engine)
            self.prefill.engine.autopilot = self.autopilot
            if self._scaler is not None:
                self.autopilot.register_controller("pool_scale",
                                                   self._scaler)

    # -- request surface ---------------------------------------------------

    def submit(self, *args, **kwargs) -> int:
        """Queue one request at the prefill door (the full
        :meth:`ServingEngine.submit` surface — validation, sampling
        params, priorities/deadlines, backpressure shedding)."""
        return self.prefill.submit(*args, **kwargs)

    def _engines(self):
        yield self.prefill.engine
        for w in self.decoders:
            yield w.engine

    def _lookup(self, req_id: int) -> Optional[Request]:
        for eng in self._engines():
            req = eng._finished.get(req_id)
            if req is not None:
                return req
        return None

    def result(self, req_id: int) -> Optional[np.ndarray]:
        req = self._lookup(req_id)
        return None if req is None else np.asarray(req.output, np.int32)

    def pop_result(self, req_id: int) -> Optional[np.ndarray]:
        for eng in self._engines():
            out = eng.pop_result(req_id)
            if out is not None:
                return out
        return None

    def logprobs(self, req_id: int) -> Optional[np.ndarray]:
        req = self._lookup(req_id)
        return None if req is None else np.asarray(req.logprobs,
                                                   np.float32)

    def request(self, req_id: int) -> Optional[Request]:
        return self._lookup(req_id)

    def cancel(self, req_id: int) -> bool:
        """Cancel wherever the request currently lives: the prefill
        pool (waiting / mid-prefill), its decode pool (queued-for-
        restore / decoding), or — the wire window — a transfer channel
        a dead, draining, or not-yet-stepped pool has not consumed. A
        payload in flight is SWEPT, not recalled: the id joins the
        shared cancelled set every ``DecodeWorker.ingest`` consults
        (the decode pool drops the payload instead of restoring it),
        and the cancellation is ledgered HERE from the header of the
        row's tier entry (the last-handoff failover copy) so
        the ``finish_*`` union still sums to every submitted
        request's fate. Returns False only for unknown or
        already-finished requests."""
        for eng in self._engines():
            if eng.cancel(req_id):
                # the engine's own teardown dropped the shared tier
                # entry (engine.cancel -> _drop_tier_row)
                return True
        if self._lookup(req_id) is not None:
            return False                     # already finished
        # the backoff parking lot: a failed/timed-out handoff awaiting
        # its retry window is in NO scheduler and has no stash entry —
        # cancellation must reach it here or be silently lost until
        # the resend
        req = self.prefill.cancel_deferred(req_id)
        if req is not None:
            req.resume_carry = None
            self._ledger_cancel(req)
            return True
        blob = self.tier.pop_blob(req_id)
        if blob is None:
            return False                     # unknown request
        self._cancelled.add(req_id)
        self._ledger_cancel(
            request_from_meta(payload_header(blob)["request"]))
        return True

    def _ledger_cancel(self, req: Request) -> None:
        """Front-end cancellation ledger tail (wire sweep + backoff
        sweep): the request lands CANCELLED in the prefill engine's
        ledger so result()/accounting stay closed."""
        req.state = CANCELLED
        peng = self.prefill.engine
        # the adapter refcount taken at the prefill door follows the
        # request wherever it dies — including here, cancelled on the
        # wire before any pool owned it
        peng._release_adapter(req)
        peng._finished[req.req_id] = req
        peng._evict_finished()
        peng.metrics.on_cancel()
        peng.metrics.on_finish_reason("cancelled")

    # -- pool lifecycle (health, failover, drain, autoscaling) -------------

    def pool_states(self) -> List[str]:
        """Per-decode-pool lifecycle state (``active``/``standby``/
        ``dead``), index-aligned with ``self.decoders``."""
        return list(self._pool_state)

    def pool_health(self, i: int) -> str:
        """Decode pool ``i``'s current health classification."""
        return self._health[i].state()

    def _route_index(self) -> int:
        """The routing decision: least-loaded HEALTHY active decode
        pool; falls back to SUSPECT actives when no healthy pool
        exists (degraded service beats dropped rows). Raises when no
        active pool remains at all."""
        cands = [i for i, s in enumerate(self._pool_state)
                 if s == POOL_ACTIVE and self.decoders[i].alive]
        healthy = [i for i in cands
                   if self._health[i].state() == HEALTHY]
        pool = healthy if healthy else cands
        if not pool:
            raise RuntimeError(
                "no active decode pool to route to — every pool is "
                "dead or retired (add standby_pools, or activate one)")
        return min(pool, key=lambda i: self.decoders[i].load)

    def _check_health(self) -> None:
        """Classify every active pool; a DEAD verdict (heartbeat
        silence past ``dead_after_s``, ``dead_after_failures``
        consecutive send failures, or a forced kill) triggers
        failover before any routing this step.

        One deliberate exception: a pool whose WORKER is still alive
        (the fabric looks dead, the pool may be fine) is NOT failed
        over while it is the last serving capacity — with no survivor
        and no standby there is nowhere to move its rows, and
        declaring the whole plane down would turn a broken cable into
        a total outage. It keeps serving; the per-request transfer
        retry budget bounds the damage (requests error out, the
        engine never wedges). A worker that actually stopped
        (``kill_pool``, process exit) fails over regardless — and
        with no fallback that IS a total outage, raised loudly."""
        for i, st in enumerate(self._pool_state):
            if st != POOL_ACTIVE or self._health[i].state() != DEAD:
                continue
            fallback = any(
                s == POOL_ACTIVE and j != i and self.decoders[j].alive
                or s == POOL_STANDBY and self.decoders[j].alive
                for j, s in enumerate(self._pool_state))
            if not fallback and self.decoders[i].alive:
                continue
            self._failover_pool(i)

    def kill_pool(self, i: int, immediate: bool = True) -> None:
        """Operator/chaos hook: decode pool ``i`` crashes NOW — its
        worker stops stepping (a dead process runs no code). With
        ``immediate=True`` the death is known out-of-band (connection
        refused / process exit) and the next ``step()`` fails over at
        once; with ``immediate=False`` the front end discovers it
        through missed heartbeats on the shared clock
        (``HealthConfig.dead_after_s`` — a VirtualClock test advances
        time, never sleeps)."""
        if not 0 <= i < len(self.decoders):
            raise ValueError(f"no decode pool {i}")
        if self._pool_state[i] == POOL_DEAD:
            raise ValueError(f"decode pool {i} is already dead")
        self.decoders[i].alive = False
        if self._pool_state[i] == POOL_STANDBY:
            # a standby owns nothing: no failover to run, it just can
            # never be activated now
            self._pool_state[i] = POOL_DEAD
            self._health[i].force_dead()
            return
        if immediate:
            self._health[i].force_dead()

    def _activate_pool(self, i: int) -> None:
        """Promote a STANDBY pool to ACTIVE: compile-free (its engine
        shares every program through the process-wide step caches) —
        just routing state and a fresh bill of health."""
        if self._pool_state[i] != POOL_STANDBY:
            raise ValueError(
                f"decode pool {i} is {self._pool_state[i]}, not standby")
        if not self.decoders[i].alive:
            raise ValueError(f"decode pool {i} was killed on standby")
        self._pool_state[i] = POOL_ACTIVE
        self._health[i].reset()

    def _failover_pool(self, i: int) -> None:
        """Reconstruct everything DEAD decode pool ``i`` owns on the
        survivors — loss-free wherever a current state copy exists,
        byte-identical replay elsewhere. Three strata:

        1. handoffs still ON THE WIRE: channel state outlives the pool
           process (a deque here, a block store across processes), so
           the packed bytes re-route to a survivor untouched;
        2. rows in the pool's HOST-SIDE ledger (its scheduler tables —
           in the real deployment this ledger lives with the router,
           which streams every emitted token to clients anyway) whose
           last-handoff stash is still CURRENT (no tokens emitted
           since): the stash blob re-routes — restore is bitwise, no
           recompute;
        3. rows that decoded past their stash: device state died with
           the pool and is NEVER read — a meta-only REPLAY handoff
           re-prefills ``prompt + emitted`` on the survivor,
           byte-identical by the PR 8 recovery contract (RNG lanes are
           request-keyed, penalty counts rebuild from the emitted
           tokens).

        Survivors admit all three through their ordinary ingest path —
        zero new compiled programs. If no active pool survives, a
        standby pool is activated first (no standby → raises: total
        outage is the caller's problem)."""
        w = self.decoders[i]
        t0 = self._clock()
        w.alive = False
        self._pool_state[i] = POOL_DEAD
        self._health[i].force_dead()
        self.metrics.on_pool_death()
        if not any(s == POOL_ACTIVE for s in self._pool_state):
            stand = [j for j, s in enumerate(self._pool_state)
                     if s == POOL_STANDBY and self.decoders[j].alive]
            if not stand:
                raise RuntimeError(
                    f"decode pool {i} died with no surviving active "
                    "pool and no standby to activate")
            self._activate_pool(stand[0])
        n_migrated = n_replayed = 0
        while True:                          # stratum 1: the wire
            blob = w.transfer.recv()
            if blob is None:
                break
            self._forward(blob)
            n_migrated += 1
        sched = w.engine.scheduler
        stranded = sched.pop_waiting(lambda r: True)
        for slot in list(sched.running):
            stranded.append(sched.running.pop(slot))
        for slot in list(sched.partial):
            stranded.append(sched.partial.pop(slot))
        for req in stranded:                 # strata 2 + 3
            req.slot = None
            req.resume_carry = None
            blob = self.tier.get_blob(req.req_id)
            if blob is not None and \
                    payload_header(blob)["request"]["output"] \
                    == [int(t) for t in req.output]:
                n_migrated += 1
            else:
                blob = pack_payload(request_meta(req), None)
                self.tier.put_packed(blob, req_id=req.req_id)
                n_replayed += 1
            self._forward(blob)
        self.metrics.on_failover(n_migrated, n_replayed,
                                 self._clock() - t0)

    def drain_pool(self, i: int) -> int:
        """GRACEFULLY retire ACTIVE decode pool ``i``: stop routing to
        it, migrate every row it owns to the surviving pools through
        the ordinary ``row_state`` wire handoff (LOSS-FREE — the pool
        is alive, so mid-stream rows serialize their live carry and
        resume byte-identically on the receiver), and leave it
        STANDBY: weights resident, programs shared through the
        process-wide step caches, so both retiring and a later
        reactivation are compile-free. Returns the migrated row
        count."""
        if not 0 <= i < len(self.decoders):
            raise ValueError(f"no decode pool {i}")
        if self._pool_state[i] != POOL_ACTIVE:
            raise ValueError(
                f"decode pool {i} is {self._pool_state[i]}, not active")
        if sum(1 for s in self._pool_state if s == POOL_ACTIVE) < 2:
            raise ValueError(
                "cannot drain the last active decode pool — activate "
                "another first")
        w = self.decoders[i]
        self._pool_state[i] = POOL_STANDBY   # routing excludes it now
        n = 0
        while True:                          # unconsumed wire payloads
            blob = w.transfer.recv()
            if blob is None:
                break
            self._forward(blob)
            n += 1
        sched = w.engine.scheduler
        for req in sched.pop_waiting(lambda r: True):
            # queued-for-restore rows: their payload already sits in
            # the shared tier as packed bytes (ingest/requeue put it
            # there) and re-routes as-is when still current; otherwise
            # — a legacy in-memory carry, or no copy at all (replay-
            # queued, or budget-evicted) — re-pack from the request
            blob = self.tier.get_blob(req.req_id)
            if blob is None or \
                    payload_header(blob)["request"]["output"] \
                    != [int(t) for t in req.output]:
                payload, req.resume_carry = req.resume_carry, None
                blob = pack_payload(request_meta(req), payload)
                self.tier.put_packed(blob, req_id=req.req_id)
            self._forward(blob)
            n += 1
        seated = [(s, sched.running.pop(s)) for s in list(sched.running)]
        seated += [(s, sched.partial.pop(s)) for s in list(sched.partial)]
        for slot, req in seated:
            # slot-holding rows serialize their LIVE carry — the
            # clean path failover cannot take (it never trusts a
            # dead device)
            payload = w.engine.pool.row_state(slot)
            req.slot = None
            w.engine.pool.free(slot)
            w.engine._configured.discard(slot)
            w.engine._restored.discard(slot)
            w.engine._constraints.pop(slot, None)
            blob = pack_payload(request_meta(req), payload)
            self.tier.put_packed(blob, req_id=req.req_id)
            self._forward(blob)
            n += 1
        self.metrics.on_migrated(n)
        return n

    def _forward(self, blob: bytes) -> None:
        """Route one already-packed handoff to the best surviving
        pool. Failover/drain internals: the send is direct — the
        target was just chosen as a live survivor, and recovery paths
        do not re-enter the fault injector."""
        self.decoders[self._route_index()].transfer.send(blob)

    def _autoscale(self) -> None:
        active = [i for i, s in enumerate(self._pool_state)
                  if s == POOL_ACTIVE]
        standby = [i for i, s in enumerate(self._pool_state)
                   if s == POOL_STANDBY and self.decoders[i].alive]
        occ = sum(self.decoders[i].occupancy for i in active) \
            / max(len(active), 1)
        decision = self._scaler.observe(
            occ, self.prefill.engine.scheduler.queue_depth,
            can_up=bool(standby),
            can_down=len(active) > self._scaler.config.min_pools)
        if decision == "up":
            self._activate_pool(standby[0])
            self.metrics.on_autoscale("up")
        elif decision == "down":
            victim = min(active, key=lambda i: self.decoders[i].load)
            self.drain_pool(victim)
            self.metrics.on_autoscale("down")
        if decision and self.autopilot is not None:
            # the bus records pool actuations next to every other
            # knob's — ONE audit stream for the whole control plane
            self.autopilot.bus.note_pool_scale(decision)

    # -- the serving loop --------------------------------------------------

    def _handoff(self, req: Request, payload: Dict) -> None:
        i = self._route_index()
        worker = self.decoders[i]
        blob = self.prefill.send_handoff(worker.transfer, req, payload,
                                         self.metrics,
                                         health=self._health[i])
        if blob is not None:
            # the packed bytes double as the failover copy: one tier
            # entry per in-flight row under the shared host budget
            self.tier.put_packed(blob, req_id=req.req_id)

    def step(self) -> Dict[int, int]:
        """One front-end super-step: health sweep (failing over any
        pool classified DEAD), pump the prefill pool, route every
        finished row to the least-loaded healthy decode worker, one
        decode super-step per active pool (each completed step stamps
        the pool's heartbeat), then the autoscaler sample. Returns the
        merged ``{req_id: last emitted 1-based token}`` across
        pools."""
        now = self._clock()
        if self._last_step_t is None or \
                now - self._last_step_t \
                > self.health_config.suspect_after_s:
            # a gap in the CALLER's stepping cadence is not pool
            # silence: during a traffic lull nobody was expected to
            # beat, and classifying the whole fleet dead on the next
            # step would turn every idle minute into a pool massacre.
            # Restart every live pool's beat clock; a genuinely hung
            # worker (alive but not beating) re-accumulates silence
            # over the next dead_after_s of ACTIVE stepping.
            for i, st in enumerate(self._pool_state):
                if st == POOL_ACTIVE and self.decoders[i].alive:
                    self._health[i].beat()
        self._last_step_t = now
        self._check_health()
        for req, payload in self.prefill.pump():
            self._handoff(req, payload)
        out: Dict[int, int] = {}
        for i, worker in enumerate(self.decoders):
            if self._pool_state[i] != POOL_ACTIVE or not worker.alive:
                continue
            out.update(worker.step())
            self._health[i].beat()
        # (no stash hygiene sweep anymore: a finished request's
        # handoff copy is dropped AT the finish disposition by the
        # owning engine — ServingEngine._drop_tier_row — so the tier
        # never carries dead rows between steps)
        if self._scaler is not None:
            self._autoscale()
        self.metrics.on_pool_occupancy(
            self.prefill.occupancy,
            [w.occupancy for i, w in enumerate(self.decoders)
             if self._pool_state[i] == POOL_ACTIVE])
        return out

    def idle(self) -> bool:
        return self.prefill.idle() and all(w.idle()
                                           for w in self.decoders)

    def drain(self) -> Dict[int, np.ndarray]:
        """Step until every submitted request has finished; returns
        ``{req_id: generated ids}`` for all retained FINISHED requests
        across pools (the monolithic ``drain`` contract)."""
        while not self.idle():
            self.step()
        out: Dict[int, np.ndarray] = {}
        for eng in self._engines():
            # idle() watches schedulers, not windows: a worker whose
            # rows all finished can still hold in-flight dispatches —
            # flush them (split-sample pairing intact) so no device
            # handle outlives the drain
            eng.flush_window()
        for eng in self._engines():
            for rid, req in eng._finished.items():
                if req.state == FINISHED:
                    out[rid] = np.asarray(req.output, np.int32)
        return out

    @property
    def queue_depth(self) -> int:
        return sum(eng.scheduler.queue_depth for eng in self._engines())

    @property
    def active(self) -> int:
        return sum(eng.scheduler.active for eng in self._engines())

    # -- introspection -----------------------------------------------------

    def pool_summaries(self) -> Dict[str, Dict[str, float]]:
        """Per-pool metric summaries (``prefill``, ``decode_<i>``) —
        the disaggregated twin of ``engine.metrics.summary()``."""
        out = {"prefill": self.prefill.engine.metrics.summary()}
        for i, w in enumerate(self.decoders):
            out[f"decode_{i}"] = w.engine.metrics.summary()
        return out

    def summary(self) -> Dict[str, float]:
        """One flat dict: the front end's handoff-plane counters plus
        the pool-summed dispositions (finish_<reason> counters keep
        summing to the submitted total across the split), aggregate
        token counts, and the worst decode pool's decode-gap p99."""
        out = dict(self.metrics.summary())
        sums: Dict[str, float] = {}
        gap_p99 = 0.0
        for name, s in self.pool_summaries().items():
            for k, v in s.items():
                if k.startswith("serving/finish_") or k in (
                        "serving/shed", "serving/preempted",
                        "serving/retries", "serving/recovered_rows",
                        "serving/deadline_missed", "serving/degraded",
                        "serving/infeasible", "serving/finished_in_slo"):
                    sums[k] = sums.get(k, 0.0) + v
            if name != "prefill":
                gap_p99 = max(gap_p99,
                              s.get("serving/decode_gap_p99_s", 0.0))
        out.update(sums)
        pm = self.prefill.engine.metrics.metrics
        n_sub, _ = pm.get("serving/submitted")
        if n_sub:
            out["serving/submitted"] = n_sub
            out["serving/goodput"] = \
                sums.get("serving/finished_in_slo", 0.0) / n_sub
        n_fin = n_tok = 0.0
        for eng in self._engines():
            f, _ = eng.metrics.metrics.get("serving/finished")
            t, _ = eng.metrics.metrics.get("serving/tokens_out")
            n_fin += f
            n_tok += t
        out["serving/finished"] = n_fin
        out["serving/tokens_out"] = n_tok
        if gap_p99:
            out["serving/decode_gap_p99_s"] = gap_p99
        return out
