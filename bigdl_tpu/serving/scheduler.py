"""Request lifecycle + admission scheduling for the serving engine.

The reference scheduled jobs onto a fixed executor pool FIFO (SoCC'19);
here the "executors" are decode slots in the pooled KV cache and the
"jobs" are generation requests. The scheduler owns the waiting queue and
the WAITING → RUNNING → FINISHED lifecycle; the engine owns the tensors.
Under CHUNKED admission (``serving/chunked.py``) a request passes
through an extra PARTIAL stage between WAITING and RUNNING: it owns a
KV slot while its prompt streams in chunk by chunk, but only
``activate()`` adds it to the ``running`` table the decode step reads.

Admission policies:

* ``"prefill_priority"`` (default) — before EVERY decode step, waiting
  requests are admitted into any free slots (continuous batching: new
  arrivals slot into rows freed mid-flight, minimizing time-to-first-
  token and keeping the batch full); FIFO in arrival order;
* ``"fifo"`` — slots are only refilled once the whole running batch has
  drained (run-to-completion batching, the classic static-batch
  baseline; still FIFO across requests). Useful as the contrast
  baseline in benchmarks/serving_bench.py;
* ``"priority"`` — continuous refill like ``prefill_priority``, but the
  queue orders by (priority DESC, deadline ASC, arrival): higher
  ``Request.priority`` admits first, earliest absolute deadline breaks
  ties inside a class (EDF), arrival order breaks the rest. Under this
  policy the ENGINE may also PREEMPT: when waiting requests outrank the
  lowest-priority running row and no slot is free, that row is evicted
  loss-free (its KV row is stashed for byte-exact readmission — see
  ``ServingEngine._preempt_row``) and requeued WITH ITS ORIGINAL
  arrival key, so it resumes ahead of later same-priority arrivals.

``requeue()`` is the loss-free re-entry point shared by preemption and
fault recovery (serving/faults.py): the request keeps its original
``seq``, its emitted ``output``, and its retry/preemption counters —
only its place in a slot is given up.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from bigdl_tpu.serving.sampling import SamplingParams

WAITING, RUNNING, FINISHED = "waiting", "running", "finished"
CANCELLED = "cancelled"
SHED = "shed"
#: Mid-prefill rows under CHUNKED admission (serving/chunked.py): the
#: request owns a KV slot and its prompt is streaming in chunk by chunk,
#: but it must NOT decode yet — the engine's decode step reads only
#: ``running``, so PARTIAL rows sit in their own table until
#: ``activate()`` promotes them.
PARTIAL = "partial"

_POLICIES = ("prefill_priority", "fifo", "priority")

_INF = float("inf")


@dataclass
class Request:
    """One generation request's full lifecycle record.

    ``sampling`` carries the request's
    :class:`~bigdl_tpu.serving.sampling.SamplingParams` (None = greedy
    defaults — the engine normalizes at submit); ``logprobs`` collects
    the chosen tokens' raw model log-probs, one per output token;
    ``finish_reason`` is set by the engine at eviction (``"eos"``,
    ``"stop"`` for stop-token/stop-sequence hits, ``"length"``,
    ``"deadline"``/``"shed"`` for load-shed requests, ``"error"`` when
    the fault-recovery retry budget runs out).

    Resilience fields: ``priority`` (higher admits first — only the
    ``"priority"`` policy reads it), ``deadline_s`` (completion SLO in
    seconds after submit; expired WAITING requests are deadline-dropped,
    late finishes count against goodput), ``degrade`` (an optional
    :class:`~bigdl_tpu.serving.admission.Degrade` applied at admission
    when the engine is under pressure), ``preemptions``/``retries``
    (how often this request was preempted / fault-evicted), and
    ``resume_carry`` — a stashed ``KVPool.row_state`` payload (KV +
    int8 scales + RNG lane + penalty counts + chunk mirrors + draft
    slice), restored whole at readmission for byte-exact resumption.
    Preemption and the disaggregated prefill→decode handoff
    (``serving/disagg.py``) both park their state here; fault recovery
    clears it and replays via prefill of ``prompt + output`` instead:
    a suspect step's carry is never trusted."""

    req_id: int
    prompt: List[int]                  # 1-based word ids, non-empty
    max_new_tokens: int
    eos_id: int = -1                   # 1-based, -1 = none
    state: str = WAITING
    slot: Optional[int] = None
    next_token: Optional[int] = None   # 0-based token to feed next step
    output: List[int] = field(default_factory=list)   # 1-based ids
    sampling: Optional[SamplingParams] = None
    # speculative-decoding hint: None = the engine's configured draft
    # count, 0 = plain decode for this request, n = at most n drafts
    # per super-step (clamped to the engine's k; ignored by
    # non-speculative engines — it is a budget, not a semantic)
    draft_tokens: Optional[int] = None
    # multi-tenant fields (serving/lora.py, serving/constrain.py):
    # adapter_id 0 = the null adapter (base model); constraint is an
    # optional TokenDFA — the engine rebuilds its cursor from (this,
    # output) at every (re)admission, never checkpointing cursor state
    adapter_id: int = 0
    constraint: Optional[object] = None
    logprobs: List[float] = field(default_factory=list)
    finish_reason: Optional[str] = None
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    # -- resilience (serving/scheduler.py docstring) -----------------------
    priority: int = 0
    deadline_s: Optional[float] = None
    degrade: Optional[object] = None   # admission.Degrade
    degraded: bool = False
    # the pre-degrade (max_new_tokens, draft_tokens) pair, recorded by
    # the ONE degrade writer (ServingEngine._apply_degrade) so the
    # clamp is REVERTIBLE: when pressure drops while this row still
    # waits, _restore_degrade puts the originals back — a burst's
    # degrade must not outlive the burst
    _pre_degrade: Optional[tuple] = None
    seq: int = -1                      # arrival order, set by submit()
    preemptions: int = 0
    retries: int = 0
    resume_carry: Optional[dict] = None

    @property
    def deadline_time(self) -> Optional[float]:
        """Absolute completion deadline on the engine's clock."""
        if self.deadline_s is None:
            return None
        return self.submit_time + self.deadline_s

    @property
    def done_reason(self) -> Optional[str]:
        if self.state != FINISHED:
            return None
        if self.finish_reason is not None:
            return self.finish_reason
        if self.output and self.eos_id > 0 and self.output[-1] == self.eos_id:
            return "eos"
        return "length"


class Scheduler:
    """Priority/FIFO admission over a fixed slot pool (module
    docstring). The waiting queue is a heap of ``[key, req]`` entries;
    keys are assigned once per request (requeue reuses them), so a
    preempted request re-enters at its original position."""

    def __init__(self, policy: str = "prefill_priority",
                 tier=None) -> None:
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown admission policy {policy!r} (one of {_POLICIES})")
        self.policy = policy
        #: optional zero-arg callable returning the measured per-token
        #: service-time estimate (seconds) or None — set by the
        #: autopilot (``serving/autopilot.py``). With one attached,
        #: the priority key's deadline term becomes LEAST-LAXITY: the
        #: deadline minus the time the request's remaining budget
        #: needs, i.e. the latest feasible start — a long-budget
        #: request with the same deadline is genuinely more urgent.
        #: Evaluated ONCE at submit (requeue preserves the key), so
        #: heap order stays deterministic as the estimate drifts.
        self.service_estimate: Optional[object] = None
        self._waiting: List[list] = []            # heap of [key, req]
        self.running: Dict[int, Request] = {}     # slot -> request
        # mid-prefill rows (chunked admission): slot-bound but not yet
        # decoding — activate() moves them into `running`
        self.partial: Dict[int, Request] = {}
        self._seq = 0
        # host spill tier (serving/kv_tier.py). With one attached,
        # victim selection goes COLD-FIRST: spilling a row that has
        # not decoded recently costs the batch least, and its fetch
        # is furthest away. The stamps below track recency.
        self.tier = tier
        self._step_no = 0
        # slot -> step number of its occupant's last decode (admission
        # stamps the current step: a row admitted this step is WARM by
        # definition and must never be the same round's cold victim)
        self._last_decoded: Dict[int, int] = {}

    def _key(self, req: Request):
        if self.policy != "priority":
            return (0, 0.0, req.seq)
        dl = req.deadline_time
        urgency = _INF if dl is None else dl
        if dl is not None and self.service_estimate is not None:
            est = self.service_estimate()
            if est:
                # least-laxity: order by latest feasible START, not
                # by deadline — folds the measured service time into
                # the key (autopilot attach; plain EDF without one)
                rem = max(1, req.max_new_tokens - len(req.output))
                urgency = dl - est * rem
        return (-req.priority, urgency, req.seq)

    def submit(self, req: Request) -> None:
        if not req.prompt:
            raise ValueError("need a non-empty prompt")
        if req.max_new_tokens <= 0:
            raise ValueError("max_new_tokens must be positive")
        if req.seq < 0:
            req.seq = self._seq
            self._seq += 1
        req.state = WAITING
        heapq.heappush(self._waiting, [self._key(req), req])

    def requeue(self, req: Request) -> None:
        """Return an evicted RUNNING request to the waiting queue
        (preemption / fault recovery): its original arrival key — hence
        its place among same-priority peers — is preserved, and its slot
        binding is dropped. The engine frees the KV slot."""
        if req.slot is not None:
            if self.running.get(req.slot) is req:
                del self.running[req.slot]
            else:
                assert self.partial.get(req.slot) is req
                del self.partial[req.slot]
            req.slot = None
        req.state = WAITING
        req.next_token = None
        heapq.heappush(self._waiting, [self._key(req), req])

    def admissible(self, free_slots: int) -> int:
        """How many waiting requests may be admitted right now."""
        if not free_slots or not self._waiting:
            return 0
        if self.policy == "fifo" and (self.running or self.partial):
            return 0          # run-to-completion: wait for a full drain
        return min(free_slots, len(self._waiting))

    def admit(self, slot: int, partial: bool = False) -> Request:
        """Pop the best waiting request and bind it to ``slot``.
        ``partial=True`` binds it in the PARTIAL (mid-prefill) state —
        chunked admission streams its prompt in before ``activate()``
        lets it decode."""
        _, req = heapq.heappop(self._waiting)
        req.slot = slot
        self._last_decoded[slot] = self._step_no
        if partial:
            req.state = PARTIAL
            self.partial[slot] = req
        else:
            req.state = RUNNING
            self.running[slot] = req
        return req

    def activate(self, slot: int) -> Request:
        """Promote a PARTIAL row whose prompt has fully streamed in:
        it joins ``running`` and decodes from the next step on."""
        req = self.partial.pop(slot)
        req.state = RUNNING
        self.running[slot] = req
        self._last_decoded[slot] = self._step_no
        return req

    def note_decoded(self, slots) -> None:
        """Stamp one completed decode/verify super-step for ``slots``
        (the engine calls this once per HEALTHY dispatch) — the
        recency signal behind cold-first victim selection."""
        self._step_no += 1
        for slot in slots:
            self._last_decoded[slot] = self._step_no

    # -- priority/deadline surface (the engine's preemption loop) ----------

    def top_waiting(self) -> Optional[Request]:
        """The request the next ``admit()`` would pop, or None."""
        return self._waiting[0][1] if self._waiting else None

    def waiting_higher_than(self, priority: int) -> int:
        """Waiting requests that OUTRANK ``priority`` (strictly) — the
        preemption demand signal."""
        return sum(1 for _, r in self._waiting if r.priority > priority)

    def lowest_running(self) -> Optional[Request]:
        """The preemption victim candidate: the lowest-priority running
        row. Tie-break WITHIN a priority class: with a host tier
        attached, the COLDEST row (LRU over last-decoded step — its
        spill disturbs the batch least and eviction is loss-free
        either way); without one, most recent arrival first (least
        time in a slot — replay cost is smallest and its completion
        is furthest away). PARTIAL (mid-prefill) rows are never
        candidates — only ``running`` is scanned."""
        if not self.running:
            return None
        if self.tier is not None:
            return min(self.running.values(),
                       key=lambda r: (r.priority,
                                      self._last_decoded.get(r.slot, -1),
                                      -r.seq))
        return min(self.running.values(),
                   key=lambda r: (r.priority, -r.seq))

    def peek_waiting(self, n: int) -> List[Request]:
        """The ``n`` requests the next ``n`` ``admit()`` calls would
        pop, in order, WITHOUT popping them — the tier's prefetch
        window (keys are unique per request, so the heap entries
        totally order)."""
        return [r for _, r in heapq.nsmallest(n, self._waiting)]

    def iter_waiting(self):
        """Read-only iteration over WAITING requests in HEAP order
        (not admission order — cheaper than the sorted ``waiting``
        view). The degrade apply/restore sweeps use it; mutating
        priority/deadline/seq during iteration would corrupt the heap,
        mutating budget fields (``max_new_tokens``/``draft_tokens``)
        is safe — keys never depend on them."""
        for _, req in self._waiting:
            yield req

    def pop_waiting(self, pred) -> List[Request]:
        """Remove and return every WAITING request ``pred`` selects —
        the generic drop primitive behind deadline expiry and
        feasibility admission control (the survivors' heap order is
        preserved)."""
        keep, dropped = [], []
        for entry in self._waiting:
            (dropped if pred(entry[1]) else keep).append(entry)
        if dropped:
            self._waiting = keep
            heapq.heapify(self._waiting)
        return [req for _, req in dropped]

    def pop_expired(self, now: float) -> List[Request]:
        """Remove and return WAITING requests whose absolute deadline
        has already passed — admitting them would spend decode steps on
        a guaranteed SLO miss. The engine ledgers them with
        ``finish_reason='deadline'``."""
        return self.pop_waiting(
            lambda r: r.deadline_time is not None and now > r.deadline_time)

    # -- cancellation -------------------------------------------------------

    def cancel(self, req_id: int) -> Optional[Request]:
        """Dequeue a WAITING request: it will never be admitted and
        never occupies a slot. Returns the (now CANCELLED) request, or
        None if ``req_id`` is not waiting — the ENGINE cancels RUNNING
        requests (their KV slot must be freed; see
        ``ServingEngine.cancel``)."""
        for i, (_, req) in enumerate(self._waiting):
            if req.req_id == req_id:
                del self._waiting[i]
                heapq.heapify(self._waiting)
                req.state = CANCELLED
                return req
        return None

    def cancel_running(self, req_id: int) -> Optional[Request]:
        """Unbind a RUNNING (or mid-prefill PARTIAL) request
        (engine-driven cancellation): it leaves its table CANCELLED,
        with its slot id returned on the request untouched for the
        engine to free. None if neither running nor partial."""
        for table in (self.running, self.partial):
            for slot, req in table.items():
                if req.req_id == req_id:
                    del table[slot]
                    req.state = CANCELLED
                    return req
        return None

    def finish(self, req: Request, now: float) -> int:
        """Mark finished; returns the freed slot id. Covers RUNNING
        rows and (for fault-recovery error-outs) mid-prefill PARTIAL
        rows alike."""
        slot = req.slot
        assert slot is not None
        if self.running.get(slot) is req:
            del self.running[slot]
        else:
            assert self.partial.get(slot) is req
            del self.partial[slot]
        req.state = FINISHED
        req.slot = None
        req.finish_time = now
        return slot

    @property
    def waiting(self) -> List[Request]:
        """Waiting requests in admission order (a sorted VIEW — the
        backing store is a heap; kept for introspection/tests)."""
        return [r for _, r in sorted(self._waiting, key=lambda e: e[0])]

    @property
    def queue_depth(self) -> int:
        return len(self._waiting)

    @property
    def active(self) -> int:
        """Slot-holding requests: decoding rows plus mid-prefill
        PARTIAL rows (chunked admission)."""
        return len(self.running) + len(self.partial)

    def idle(self) -> bool:
        return (not self._waiting and not self.running
                and not self.partial)
