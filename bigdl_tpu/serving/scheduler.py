"""Request lifecycle + admission scheduling for the serving engine.

The reference scheduled jobs onto a fixed executor pool FIFO (SoCC'19);
here the "executors" are decode slots in the pooled KV cache and the
"jobs" are generation requests. The scheduler owns the waiting queue and
the WAITING → RUNNING → FINISHED lifecycle; the engine owns the tensors.

Admission policies:

* ``"prefill_priority"`` (default) — before EVERY decode step, waiting
  requests are admitted into any free slots (continuous batching: new
  arrivals slot into rows freed mid-flight, minimizing time-to-first-
  token and keeping the batch full);
* ``"fifo"`` — slots are only refilled once the whole running batch has
  drained (run-to-completion batching, the classic static-batch
  baseline; still FIFO across requests). Useful as the contrast
  baseline in benchmarks/serving_bench.py.

Both are FIFO in ARRIVAL ORDER — the policies differ only in WHEN free
slots are refilled, never in which request goes first.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

from bigdl_tpu.serving.sampling import SamplingParams

WAITING, RUNNING, FINISHED = "waiting", "running", "finished"
CANCELLED = "cancelled"

_POLICIES = ("prefill_priority", "fifo")


@dataclass
class Request:
    """One generation request's full lifecycle record.

    ``sampling`` carries the request's
    :class:`~bigdl_tpu.serving.sampling.SamplingParams` (None = greedy
    defaults — the engine normalizes at submit); ``logprobs`` collects
    the chosen tokens' raw model log-probs, one per output token;
    ``finish_reason`` is set by the engine at eviction (``"eos"``,
    ``"stop"`` for stop-token/stop-sequence hits, ``"length"``)."""

    req_id: int
    prompt: List[int]                  # 1-based word ids, non-empty
    max_new_tokens: int
    eos_id: int = -1                   # 1-based, -1 = none
    state: str = WAITING
    slot: Optional[int] = None
    next_token: Optional[int] = None   # 0-based token to feed next step
    output: List[int] = field(default_factory=list)   # 1-based ids
    sampling: Optional[SamplingParams] = None
    # speculative-decoding hint: None = the engine's configured draft
    # count, 0 = plain decode for this request, n = at most n drafts
    # per super-step (clamped to the engine's k; ignored by
    # non-speculative engines — it is a budget, not a semantic)
    draft_tokens: Optional[int] = None
    logprobs: List[float] = field(default_factory=list)
    finish_reason: Optional[str] = None
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None

    @property
    def done_reason(self) -> Optional[str]:
        if self.state != FINISHED:
            return None
        if self.finish_reason is not None:
            return self.finish_reason
        if self.output and self.eos_id > 0 and self.output[-1] == self.eos_id:
            return "eos"
        return "length"


class Scheduler:
    """FIFO admission over a fixed slot pool (see module docstring)."""

    def __init__(self, policy: str = "prefill_priority") -> None:
        if policy not in _POLICIES:
            raise ValueError(
                f"unknown admission policy {policy!r} (one of {_POLICIES})")
        self.policy = policy
        self.waiting: Deque[Request] = deque()
        self.running: Dict[int, Request] = {}     # slot -> request

    def submit(self, req: Request) -> None:
        if not req.prompt:
            raise ValueError("need a non-empty prompt")
        if req.max_new_tokens <= 0:
            raise ValueError("max_new_tokens must be positive")
        req.state = WAITING
        self.waiting.append(req)

    def admissible(self, free_slots: int) -> int:
        """How many waiting requests may be admitted right now."""
        if not free_slots or not self.waiting:
            return 0
        if self.policy == "fifo" and self.running:
            return 0          # run-to-completion: wait for a full drain
        return min(free_slots, len(self.waiting))

    def admit(self, slot: int) -> Request:
        """Pop the next waiting request (FIFO) and bind it to ``slot``."""
        req = self.waiting.popleft()
        req.state = RUNNING
        req.slot = slot
        self.running[slot] = req
        return req

    def cancel(self, req_id: int) -> Optional[Request]:
        """Dequeue a WAITING request: it will never be admitted and
        never occupies a slot. Returns the (now CANCELLED) request, or
        None if ``req_id`` is not waiting — RUNNING requests are not
        cancellable here (their slot state is mid-flight; they run to
        EOS/length like any other row)."""
        for i, req in enumerate(self.waiting):
            if req.req_id == req_id:
                del self.waiting[i]
                req.state = CANCELLED
                return req
        return None

    def finish(self, req: Request, now: float) -> int:
        """Mark finished; returns the freed slot id."""
        slot = req.slot
        assert slot is not None and self.running.get(slot) is req
        del self.running[slot]
        req.state = FINISHED
        req.slot = None
        req.finish_time = now
        return slot

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @property
    def active(self) -> int:
        return len(self.running)

    def idle(self) -> bool:
        return not self.waiting and not self.running
