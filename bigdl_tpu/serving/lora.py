"""Pooled per-row LoRA adapter bank for multi-tenant serving.

A production pool multiplexes many tenant fine-tunes over one set of
base weights. Swapping weight tensors per request would retrace the
compiled step (and serialize the pool on weight uploads); dispatching a
separate program per tenant would shatter the engine's one-program
discipline. This module keeps BOTH invariants: the low-rank factors of
every live adapter sit side by side in a pooled BANK, and each pool row
carries only an integer ``adapter_id`` as runtime data — the compiled
decode/prefill/verify steps gather the row's ``(A, B)`` factor pair from
the bank by id *inside* the program
(:func:`bigdl_tpu.models.transformer._adapter_delta`), so mixed
base/tenant traffic is ONE compiled program and admitting, evicting, or
swapping tenants never recompiles.

Layout (the contract with ``transformer.ADAPTER_SITES``): for each
transformer block ``i`` and each adapted projection ``site`` in
``(wq, wk, wv, wo, fc1, fc2)`` the bank holds

* ``f"{site}{i}_a"`` — ``(n_slots, r, in_dim)`` fp32, and
* ``f"{site}{i}_b"`` — ``(n_slots, out_dim, r)`` fp32,

and a row's delta for that projection is
``scale * (h @ A[id].T) @ B[id].T`` with ``scale = alpha / r``. Slot 0
is the permanently all-zeros NULL adapter: base-model rows gather exact
zeros, and adding 0.0 is the fp identity (up to ``-0.0 → +0.0``), which
is what makes null-adapter streams token-identical to an adapter-free
engine — pinned by tests/test_serving_lora.py.

Slot lifecycle mirrors the KV pool's: :meth:`AdapterBank.alloc` writes a
tenant's factors into a free slot and returns its id with refcount 1;
:meth:`retain` / :meth:`free` move the refcount, and when it reaches
zero the slot's rows are ZEROED (like the int8 KV scales on row free —
a recycled slot must not leak the previous tenant's factors through the
null-adapter identity) and the slot returns to the free list. The
``version`` counter bumps on every mutation so the engine can cache the
bank's device placement and invalidate it only when the host arrays
actually changed.

Sharding: under tensor parallelism the bank shards exactly like the
weights it adapts (``transformer.adapter_bank_specs``) — B out-sharded
for column-parallel sites, A in-sharded for row-parallel sites, the
slot axis always replicated. The fp32 partial delta of a row-parallel
site folds into the block's one closing psum
(``row_parallel_linear(partial_add=...)``), so the
two-collectives-per-block budget survives adapters.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass(frozen=True)
class AdapterSpec:
    """The hashable shape-and-scale summary of an :class:`AdapterBank`
    — what the step factories key their compile caches on (two engines
    over banks with equal specs share compiled steps; the factor VALUES
    are runtime data and never enter the key)."""

    rank: int
    n_slots: int
    alpha: float

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


class AdapterBank:
    """Pooled low-rank adapter factors, alloc/free'd like KV slots
    (module docstring). ``alpha`` defaults to ``rank`` (scale 1.0)."""

    def __init__(self, model, rank: int, n_slots: int = 8,
                 alpha: Optional[float] = None) -> None:
        import numpy as np

        from bigdl_tpu.models.transformer import adapter_site_shapes

        if rank <= 0:
            raise ValueError(f"rank must be positive, got {rank}")
        if n_slots < 2:
            raise ValueError(
                f"n_slots must be >= 2 (slot 0 is the reserved null "
                f"adapter), got {n_slots}")
        self.rank = int(rank)
        self.n_slots = int(n_slots)
        self.alpha = float(rank if alpha is None else alpha)
        self.site_shapes: List[Dict[str, tuple]] = adapter_site_shapes(model)
        self.arrays: Dict[str, "np.ndarray"] = {}
        for i, layer in enumerate(self.site_shapes):
            for site, (out_dim, in_dim) in layer.items():
                self.arrays[f"{site}{i}_a"] = np.zeros(
                    (self.n_slots, self.rank, in_dim), np.float32)
                self.arrays[f"{site}{i}_b"] = np.zeros(
                    (self.n_slots, out_dim, self.rank), np.float32)
        # slot 0 = null adapter: never allocated, never freed, refs pinned
        self._free: List[int] = list(range(self.n_slots - 1, 0, -1))
        self._refs: Dict[int, int] = {}
        self.version = 0

    @property
    def spec(self) -> AdapterSpec:
        return AdapterSpec(self.rank, self.n_slots, self.alpha)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def live(self) -> Dict[int, int]:
        """``{adapter_id: refcount}`` of allocated slots (copy)."""
        return dict(self._refs)

    def is_live(self, adapter_id: int) -> bool:
        """True for ids a row may carry: the null adapter or an
        allocated slot."""
        return adapter_id == 0 or adapter_id in self._refs

    # -- slot lifecycle ----------------------------------------------------

    def alloc(self, factors: Dict[str, "np.ndarray"]) -> int:
        """Write a tenant's factors into a free slot; returns its
        ``adapter_id`` with refcount 1. ``factors`` maps bank keys
        (``f"{site}{layer}_a"`` / ``_b``) to ``(r, in)`` / ``(out, r)``
        arrays; keys absent from ``factors`` stay zero (an adapter may
        touch only some projections). Unknown keys and shape mismatches
        raise — a silently ignored factor would serve the wrong model."""
        import numpy as np

        unknown = set(factors) - set(self.arrays)
        if unknown:
            raise KeyError(
                f"unknown adapter factor keys {sorted(unknown)} (bank "
                f"keys are site+layer pairs like 'wq0_a')")
        for key, val in factors.items():
            want = self.arrays[key].shape[1:]
            if tuple(np.shape(val)) != want:
                raise ValueError(
                    f"adapter factor {key!r} has shape "
                    f"{tuple(np.shape(val))}, bank expects {want}")
        if not self._free:
            raise RuntimeError(
                f"adapter bank full: all {self.n_slots - 1} tenant "
                f"slots are allocated")
        slot = self._free.pop()
        for key, val in factors.items():
            self.arrays[key][slot] = np.asarray(val, np.float32)
        self._refs[slot] = 1
        self.version += 1
        return slot

    def retain(self, adapter_id: int) -> None:
        """Bump an allocated slot's refcount (id 0 is a no-op — the
        null adapter is never refcounted)."""
        if adapter_id == 0:
            return
        if adapter_id not in self._refs:
            raise KeyError(f"adapter id {adapter_id} is not allocated")
        self._refs[adapter_id] += 1

    def free(self, adapter_id: int) -> None:
        """Drop one reference; at zero the slot's factor rows are ZEROED
        and the slot returns to the free list (recycled slots must read
        as the null adapter until re-allocated). Freeing id 0 raises."""
        if adapter_id == 0:
            raise ValueError("adapter id 0 is the reserved null adapter")
        if adapter_id not in self._refs:
            raise KeyError(f"adapter id {adapter_id} is not allocated")
        self._refs[adapter_id] -= 1
        if self._refs[adapter_id] > 0:
            return
        del self._refs[adapter_id]
        for arr in self.arrays.values():
            arr[adapter_id] = 0.0
        self._free.append(adapter_id)
        self.version += 1

    # -- device view -------------------------------------------------------

    def device_arrays(self) -> Dict[str, object]:
        """The bank as jnp arrays — what the engine feeds the compiled
        steps (cached against :attr:`version`; sharded engines place it
        with ``transformer.adapter_bank_specs``)."""
        import jax.numpy as jnp

        return {k: jnp.asarray(v) for k, v in self.arrays.items()}

    # -- test / bench helper -----------------------------------------------

    def random_factors(self, seed: int, amp: float = 0.05):
        """Deterministic random factors for every bank key (tests and
        the multitenant bench) — ``N(0, amp)`` for A, ``N(0, amp)`` for
        B, so the delta is small but nonzero at every site."""
        import numpy as np

        rng = np.random.default_rng(seed)
        return {k: rng.normal(0.0, amp, v.shape[1:]).astype(np.float32)
                for k, v in self.arrays.items()}
