"""Continuous-batching serving engine for :func:`TransformerLM`.

``generate()`` runs one request per call with a private KV carry and
pays the full weight-read bandwidth per token for a single row.
:class:`ServingEngine` instead serves MANY independent requests from one
pooled cache (:class:`bigdl_tpu.serving.kv_pool.KVPool`) stepped by ONE
compiled per-row-position decode program
(:func:`bigdl_tpu.models.transformer.make_batch_decode_step`):

* requests are ``submit()``-ed at any time and queue FIFO;
* before every decode step the scheduler admits waiting requests into
  free slots (continuous batching: admission happens MID-FLIGHT,
  between decode steps of the requests already running). The DEFAULT
  admission path (``admission="batched"``) groups the admitted prompts
  into power-of-two length buckets and ingests each bucket in ONE
  masked multi-row :func:`make_batch_prefill_step` call, row-scattering
  every result into the pooled cache — ragged prompt lengths share a
  BOUNDED set of compiled prefill programs instead of compiling per
  novel length mid-admission (see ``serving/admission.py``).
  ``admission="per_request"`` keeps PR 1's one-at-a-time B=1
  :func:`make_prefill_step` path (the parity baseline), and
  ``admission="chunked"`` STREAMS prompts in as budget-bounded
  suffix-continuation chunks interleaved with decode so long-prompt
  bursts never stall in-flight rows (``serving/chunked.py``);
* an optional :class:`bigdl_tpu.serving.prefix_cache.PrefixCache`
  (``prefix_cache=True`` or an instance) reuses prefilled K/V across
  requests sharing a token prefix — a full hit clones cached state
  straight into the pool, a partial hit prefills only the suffix;
* every ``step()`` decodes one token for ALL active rows at once —
  decode is weight-read-bound, so a batched step costs roughly what a
  single-row step costs and aggregate tokens/sec scales with occupancy
  (measured in benchmarks/serving_bench.py);
* rows are evicted at EOS or ``max_new_tokens`` and their slot returns
  to the free list for the next admission.

Decoding is SAMPLED per row (``bigdl_tpu.serving.sampling``): every
request carries its own :class:`~bigdl_tpu.serving.sampling.
SamplingParams` (temperature, top-k/top-p, penalties, seed, stop sets)
and its own ``jax.random`` lane in the pooled carry, and ONE compiled
step samples all rows at once — the knobs are per-row runtime arrays,
so greedy and sampled rows mix freely in a batch and changing knobs
never recompiles. The default params are greedy (``temperature=0``
degrades exactly to argmax inside the same program), and the pooled
step computes the same math as the single-request step, so default
engine outputs match per-request ``generate(..., temperature=0)`` token
for token — pinned by tests/test_serving.py for plain and bf16-serving
params; a fixed-seed sampled request reproduces its stream across
batching, slot placement, and eviction/readmission (pinned by
tests/test_serving_sampling.py). (The pooled and single-request steps
are numerically equal only to float round-off — different batch shapes
can reorder XLA reductions — so a checkpoint whose top-2 logprobs tie
within ~1e-5 could in principle break a tie differently; the parity
tests pin the realistic case, not a bitwise guarantee.) Stop-SEQUENCE
matching runs on host against each row's token tail; stop TOKEN ids
(incl. the per-request ``eos_id``) evict the row the step they appear,
with ``min_tokens`` banning them on device until the floor is met.

The jitted step/prefill functions come from the per-(model, dtype) step
cache (``get_batch_decode_step`` / ``get_prefill_step``), so several
engines over one model — or an engine plus ad-hoc ``generate()`` calls —
share compilations; prompt-length buckets re-trace once each inside the
cached prefill's own jit cache.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.serving.faults import (
    FaultError, WatchdogConfig, default_clock,
)
from bigdl_tpu.serving.fences import fence
from bigdl_tpu.serving.kv_pool import KVPool
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.sampling import (
    SamplingParams, advance_lane, knob_row_values, make_knob_rows,
    match_stop_sequences,
)
from bigdl_tpu.serving.scheduler import (
    FINISHED, SHED, WAITING, Request, Scheduler,
)


class _InFlight:
    """One dispatched-but-not-yet-fenced decode step in the engine's
    dispatch-ahead window: the device token/logprob handles the delayed
    consumer will read back through the decode fence, plus the host
    facts frozen
    at dispatch time that its bookkeeping needs (the row snapshot, the
    pre-dispatch clock read the watchdog's elapsed is measured from,
    the sampled/greedy split, and whether rows were already in flight —
    the decode-gap anchor)."""

    __slots__ = ("tok", "chosen", "active", "active_dev", "rows", "t0",
                 "n_sampled", "had_running")

    def __init__(self, tok, chosen, active, active_dev, rows, t0,
                 n_sampled, had_running):
        self.tok = tok                  # device handle: next 0-based ids
        self.chosen = chosen            # device handle: chosen logprobs
        self.active = active            # host bool mask at dispatch
        self.active_dev = active_dev    # the mask's PLACED device twin
        self.rows = rows                # {slot: Request} at dispatch
        self.t0 = t0                    # clock at dispatch (pre-launch)
        self.n_sampled = n_sampled      # sampled rows in the batch
        self.had_running = had_running  # decode-gap anchor flag


class ServingEngine:
    """Continuous-batching per-row-sampled decoder over a pooled KV cache.

    ``n_slots`` is the fixed decode capacity (concurrent requests);
    ``compute_dtype`` is the serving precision knob (weights + KV cache,
    e.g. ``jnp.bfloat16`` — scores and log-softmax stay fp32);
    ``policy`` is the admission policy (``"prefill_priority"`` = admit
    into freed rows before every step, ``"fifo"`` = refill only after
    the running batch drains, ``"priority"`` = continuous refill in
    (priority, deadline, arrival) order with loss-free preemption —
    see ``serving.scheduler`` and the resilience notes below);
    ``admission`` picks the prompt-ingestion pipeline: ``"batched"``
    (default — bucketed multi-row masked prefill, bounded compile set),
    ``"chunked"`` (streaming admission — requests bind a KV slot
    immediately and their prompts stream in as suffix-continuation
    chunks of at most ``chunk_budget`` tokens per step, interleaved
    with decode so an arrival burst never stalls in-flight rows for a
    whole admission wave; token-identical to batched, zero extra
    decode compiles — ``serving/chunked.py``),
    or ``"per_request"`` (PR 1's B=1-per-admission baseline);
    ``chunk_budget`` is the chunked pump's per-step prompt-token budget
    (default 32; only valid with ``admission="chunked"``);
    ``deadline_feasibility`` turns on feasibility ADMISSION CONTROL:
    waiting requests whose remaining DECLARED token budget
    (``max_new_tokens`` less what is already emitted — the pessimistic
    bound; a request that would stop early at EOS under a generous cap
    is shed conservatively, so deadline-carrying callers should set
    honest caps) cannot fit inside their
    deadline at the measured per-token service rate (the
    ``decode_step_s`` median over the measured tokens-per-step — so
    speculative engines' multi-token super-steps don't overstate
    service time) are dropped at
    admission with ``finish_reason="infeasible"`` (counted as shed +
    deadline-missed) instead of burning decode steps on a guaranteed
    SLO miss — the EDF-with-admission-control step beyond dropping
    only already-expired work;
    ``prefix_cache`` enables shared-prefix K/V reuse under batched
    admission: ``True`` for a default-capacity
    :class:`~bigdl_tpu.serving.prefix_cache.PrefixCache`, or pass a
    configured instance (``None`` = off);
    ``keep_finished`` bounds the finished-request ledger: only the N
    most recently finished requests stay retrievable via ``result()``
    (older ones are evicted oldest-first), so a long-lived engine under
    heavy traffic doesn't grow without bound. ``None`` keeps everything
    (then ``pop_result()`` is the caller's eviction lever);
    ``seed`` is the engine's base RNG seed: requests whose
    ``SamplingParams.seed`` is None draw from a lane folded from this
    base and their request id (fresh per request); an explicit
    per-request seed pins the lane regardless of the engine seed;
    ``mesh``/``parallelism`` swap in the SHARDED serving plane
    (``serving/sharded.py``): pass a ``jax.sharding.Mesh`` with
    ``data``/``model`` axes, or a ``{"data": N, "model": M}`` dict to
    build one from the host's devices. Slot rows shard over ``data``
    (token-identical to the unsharded engine — same per-row math, SPMD-
    partitioned), attention heads + MLP hidden over ``model``
    (Megatron two-psums-per-block under ``compat.shard_map``; equal to
    round-off). Still ONE compiled decode program per engine;
    ``kv_dtype="int8"`` stores the pooled K/V caches as per-(slot,
    head)-scaled int8 — half the KV bytes per slot, so an HBM budget
    holds ~2x the concurrent slots — with dequantization fused into the
    attention read (the Pallas pooled decode kernel on TPU, its jnp
    reference on CPU; ``ops/decode_attention.py``). Greedy outputs are
    parity-pinned against the float-KV engine and quantization adds
    ZERO decode compiles (tests/test_serving_kv_quant.py); default
    (None) follows ``compute_dtype``;
    ``speculative`` turns on DRAFT-AND-VERIFY decoding
    (``serving/speculative.py``): pass a
    :class:`~bigdl_tpu.serving.speculative.SpeculativeConfig` (or a
    bare draft model) and every step becomes a super-step — a small
    draft proposes up to ``k`` tokens per row, ONE fixed-width batched
    verify program (structurally the masked multi-row prefill) scores
    them all, and each row advances by the confirmed count (1..k+1
    tokens per step). Greedy output stays token-identical to the plain
    engine, fixed-seed sampled streams replay exactly (verification
    draws ride the per-slot RNG lanes), per-row draft budgets are
    runtime data of the one program (``submit(..., draft_tokens=0)``
    rows run as plain decode), and the draft's KV carry rides the same
    pool slots (tests/test_serving_speculative.py).

    RESILIENCE knobs (docs/serving.md "Operating under faults and
    overload"; all host-side or per-row runtime data — none of them
    adds a compiled program):

    * ``policy="priority"`` orders the queue by (priority DESC,
      deadline ASC, arrival) and enables loss-free PREEMPTION
      (``preemption=False`` disables it): when waiting requests
      outrank the lowest-priority running row and no slot is free,
      that row is evicted — its KV slice stashed on the request (and
      shared into the prefix cache when one is attached) — and
      readmitted later byte-identically (RNG lanes are request-keyed
      and recomputable, penalty counts rebuild from the emitted
      tokens);
    * ``max_queue`` bounds the waiting BACKLOG (queue depth beyond
      what the pool's free slots will absorb at the next admission —
      an idle engine with free capacity never sheds): a ``submit()``
      arriving past the bound is SHED — it lands in the finished
      ledger with ``finish_reason="shed"`` and empty output instead
      of raising (backpressure the caller can observe per request).
      WAITING requests whose ``deadline_s`` expires before admission
      are deadline-dropped the same way
      (``finish_reason="deadline"``);
    * ``degrade_at`` is the pressure threshold (queue depth at
      admission) beyond which a request's ``submit(...,
      degrade=Degrade(...))`` knobs apply — capping
      ``max_new_tokens`` and/or disabling speculation for that
      request (graceful degradation instead of shedding);
    * ``watchdog`` (a :class:`~bigdl_tpu.serving.faults.
      WatchdogConfig`) bounds step time and per-request retries: a
      decode/verify dispatch that raises, returns non-finite or
      out-of-range outputs, or exceeds ``step_timeout_s`` on the
      engine's clock is treated as FAILED — its outputs are
      discarded, its rows evicted and replayed from the prompt +
      emitted tokens (byte-identical streams, pinned by
      tests/test_serving_faults.py) — and a request evicted more than
      ``max_retries`` times finishes with ``finish_reason="error"``
      so a persistent fault fails requests instead of wedging the
      engine;
    * ``faults`` (a :class:`~bigdl_tpu.serving.faults.FaultInjector`)
      deterministically injects step failures / garbage outputs /
      stalls / admission errors at the engine's dispatch sites — the
      test harness for all of the above; ``clock`` swaps the engine's
      time source (a :class:`~bigdl_tpu.serving.faults.VirtualClock`
      lets deadline and stall tests run without sleeping);
    * ``autopilot`` (a :class:`~bigdl_tpu.serving.autopilot.Autopilot`)
      closes the control loop: sampled once at the end of every
      ``step()`` on the engine clock, it drives ``chunk_budget``,
      per-class ``Degrade`` apply/restore, and the speculative draft
      cap from windowed metrics through the declared actuator bus,
      folds the measured service-time estimate into the priority
      key, and preempts FOR deadlines (a short-deadline feasible
      waiter evicts the longest-slack running row rather than miss).
      Every actuation is host bookkeeping over per-row runtime data —
      the compiled-program set is untouched.
    """

    def __init__(self, model, n_slots: int = 8, compute_dtype=None,
                 policy: str = "prefill_priority",
                 metrics: Optional[ServingMetrics] = None,
                 admission: str = "batched",
                 chunk_budget: Optional[int] = None,
                 deadline_feasibility: bool = False,
                 prefix_cache=None,
                 keep_finished: Optional[int] = None,
                 seed: int = 0,
                 mesh=None, parallelism=None,
                 kv_dtype: Optional[str] = None,
                 speculative=None,
                 clock=None,
                 max_queue: Optional[int] = None,
                 degrade_at: Optional[int] = None,
                 preemption: Optional[bool] = None,
                 watchdog: Optional[WatchdogConfig] = None,
                 faults=None,
                 adapters=None,
                 tier=None,
                 autopilot=None,
                 dispatch_ahead: int = 0) -> None:
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.models.transformer import (
            get_batch_decode_step, get_batch_prefill_step, get_prefill_step,
            serving_params,
        )
        from bigdl_tpu.serving.admission import AdmissionController
        from bigdl_tpu.serving.prefix_cache import PrefixCache

        if admission not in ("batched", "per_request", "chunked"):
            raise ValueError(
                f"unknown admission mode {admission!r} "
                "(one of 'batched', 'per_request', 'chunked')")
        if chunk_budget is not None:
            if admission != "chunked":
                raise ValueError(
                    "chunk_budget requires admission='chunked' — it is "
                    "the streaming pump's per-step token budget")
            if int(chunk_budget) < 1:
                raise ValueError(
                    f"chunk_budget must be >= 1, got {chunk_budget}")
        if keep_finished is not None and keep_finished < 0:
            raise ValueError(
                f"keep_finished must be >= 0 or None, got {keep_finished}")
        if max_queue is not None and max_queue < 0:
            raise ValueError(
                f"max_queue must be >= 0 or None, got {max_queue}")
        if degrade_at is not None and degrade_at < 0:
            raise ValueError(
                f"degrade_at must be >= 0 or None, got {degrade_at}")
        if int(dispatch_ahead) < 0:
            raise ValueError(
                f"dispatch_ahead must be >= 0, got {dispatch_ahead} "
                "(0 = consume each decode readback immediately; W = keep "
                "up to W decode dispatches in flight behind the fence)")
        if preemption and policy != "priority":
            raise ValueError(
                "preemption=True requires policy='priority' — victim "
                "selection is a priority-order decision")
        # multi-tenant LoRA (serving/lora.py): an AdapterBank makes the
        # compiled steps gather per-row low-rank factors by the rows'
        # adapter ids — runtime data, one program for mixed traffic.
        # The per-request B=1 prefill path predates the batched row
        # convention the adapter arguments ride, so it stays base-only.
        if adapters is not None and admission == "per_request":
            raise ValueError(
                "adapters require admission='batched' or 'chunked' — "
                "the per-request prefill has no adapter arguments")
        self.adapters = adapters
        self._adapter_spec = None if adapters is None else adapters.spec
        # device-side bank cache, invalidated by the bank's version
        # counter (alloc/free mutate the host arrays; steady-state
        # decode reuses the placed arrays)
        self._bank_device = None
        self._bank_version = None
        # resilience wiring: the engine's ONE time source (a
        # VirtualClock here lets deadline/stall tests move time without
        # sleeping), the step watchdog, and the optional deterministic
        # fault injector the dispatch sites consult
        self._clock = clock if clock is not None else default_clock
        self.watchdog = watchdog if watchdog is not None \
            else WatchdogConfig()
        self._faults = faults
        self.max_queue = max_queue
        self.degrade_at = degrade_at
        # preemption defaults ON for the priority policy (it is the
        # policy's point), and is meaningless elsewhere
        self.preemption = (policy == "priority") if preemption is None \
            else bool(preemption)
        model._ensure_params()
        self.model = model
        self.max_len = model.modules[1].max_len
        self._vocab = model.modules[0].n_index   # step-health token range
        self.compute_dtype = compute_dtype
        # KV storage format: None follows compute_dtype (the status quo);
        # "int8" switches the pooled cache to the quantized layout
        # (per-(slot, head)-scaled int8 — half the KV bytes, double the
        # slots at equal HBM; see docs/serving.md "Quantized KV cache").
        # Spelling out "fp32"/"bf16" is allowed but must AGREE with
        # compute_dtype — the float cache always stores the serving
        # dtype, and a silent disagreement would misreport capacity.
        # normalize the dtype spelling: compute_dtype may arrive as the
        # jnp type, a np.dtype, or a string ("bfloat16") — all serve
        # identically, so all must classify identically here. The name
        # must match KVPool's stored-dtype mapping for EVERY float
        # dtype (fp16 engines serve fine and their default must keep
        # constructing), not just the two canonical serving formats —
        # so uncanonical dtypes keep their numpy name ("float16").
        stored = jnp.zeros((), compute_dtype or jnp.float32).dtype.name
        float_kv = {"float32": "fp32", "bfloat16": "bf16"}.get(stored,
                                                               stored)
        if kv_dtype is None:
            kv_dtype = float_kv
        elif kv_dtype not in ("fp32", "bf16", "int8"):
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r} "
                "(one of 'fp32', 'bf16', 'int8')")
        if kv_dtype != "int8" and kv_dtype != float_kv:
            raise ValueError(
                f"kv_dtype={kv_dtype!r} conflicts with "
                f"compute_dtype={compute_dtype!r} (the float KV cache "
                f"stores the serving dtype, {float_kv!r} here) — pick "
                "kv_dtype='int8' or drop the knob")
        self.kv_dtype = kv_dtype
        kv_quant = kv_dtype == "int8"
        # the sharded serving plane (serving/sharded.py): a mesh or a
        # {"data": N, "model": M} parallelism dict swaps the pooled
        # tensors onto a device mesh — slot rows shard over "data"
        # (token-identical: pure SPMD partitioning of the same per-row
        # math), weights/KV-heads over "model" (Megatron layout under
        # compat.shard_map). None/None is the stock single-device plane.
        if mesh is not None or parallelism is not None:
            from bigdl_tpu.serving.sharded import ShardPlane

            self._plane = ShardPlane(mesh=mesh, parallelism=parallelism)
            self.mesh = self._plane.mesh
        else:
            self._plane = None
            self.mesh = None
        # weights as resident device buffers in the serving dtype
        # (runtime arguments — never baked into the compiled programs);
        # tensor-parallel planes pre-shard them over the model axis
        sp = serving_params(model, compute_dtype)
        self.params = (jax.device_put(sp) if self._plane is None
                       else self._plane.place_params(model, sp))
        # the SAMPLED pooled step is the only decode program: greedy
        # requests are temperature=0 rows of the same compiled step, so
        # greedy-only and mixed traffic share one program (pinned by the
        # compile-count guards in tests/test_serving_sampling.py and
        # tests/test_serving_sharded.py). A SPECULATIVE engine swaps in
        # the fixed-width batched VERIFY step instead (serving/
        # speculative.py) — still exactly one target-side program, with
        # per-row draft lengths as runtime data (length-1 rows ARE plain
        # decode), and a layout-identical pooled carry.
        tp = self._plane is not None and self._plane.tensor_parallel
        if speculative is None:
            self._spec = None
            self._step_fn, pool_init = get_batch_decode_step(
                model, compute_dtype, sampling=True,
                mesh=self.mesh if tp else None, kv_quant=kv_quant,
                adapter=self._adapter_spec)
        else:
            from bigdl_tpu.serving.speculative import Speculator

            self._spec = Speculator(self, speculative,
                                    mesh=self.mesh if tp else None,
                                    kv_quant=kv_quant)
            self._step_fn = None
            pool_init = self._spec.pool_init
        self._pool_init = pool_init
        self.pool = (KVPool(pool_init, n_slots, kv_dtype=kv_dtype)
                     if self._plane is None
                     else self._plane.make_pool(model, pool_init, n_slots,
                                                kv_quant=kv_quant,
                                                kv_dtype=kv_dtype))
        if self._spec is not None:
            # the draft model's pooled carry rides the same slots
            self._spec.attach_pool(self.pool)
        # host spill tier (serving/kv_tier.py): True builds a default
        # MemBlockStore-backed TieredKVStore; an instance is shared
        # as-is (the disaggregated plane passes ONE tier to every
        # pool); None keeps the legacy in-memory stash semantics
        # (resume_carry blobs). With a tier, preemption spills rows to
        # host RAM under its byte budget, readmission fetches them
        # back currency-checked, and the scheduler's victim selection
        # goes cold-first (LRU over last-decoded step).
        if tier is True:
            from bigdl_tpu.serving.kv_tier import TieredKVStore

            tier = TieredKVStore()
        self.tier = tier or None
        self.scheduler = Scheduler(policy, tier=self.tier)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        if self.tier is not None:
            self.tier.attach_metrics(self.metrics, clock=self._clock)
        if self._plane is not None:
            self.metrics.set_mesh_shape(self._plane.data_shards,
                                        self._plane.model_shards)
        # KV-format observability: bytes one slot owns + the derived
        # effective-capacity number (slots a GiB of HBM would hold)
        self.metrics.set_kv_format(kv_dtype, self.pool.kv_bytes_per_slot)
        self.admission = admission
        self.keep_finished = keep_finished
        self.seed = int(seed)
        # host-side per-slot knob rows (greedy no-op state) + which
        # slots have been configured for their current occupant
        # the allow mask (constrained decoding — serving/constrain.py)
        # always rides: an all-True row is the sampler identity, and
        # carrying it unconditionally keeps the knob dict's structure
        # one shape for plain / constrained / sharded engines alike
        self._knobs = make_knob_rows(n_slots, vocab=self._vocab)
        # live constraint cursors by slot (host-side; rebuilt from
        # (request.constraint, request.output) at every (re)admission —
        # never checkpointed, the replay rule constrain.py states)
        self._constraints: Dict[int, object] = {}
        self._ban_base = np.zeros((n_slots,), bool)
        self._configured: set = set()
        # slots whose occupant arrived as a FULL row_state payload
        # (preemption resume or a disaggregated handoff): their RNG
        # lane / penalty counts / draft cache were restored verbatim,
        # so _configure_slot sets knobs only and skips the device
        # reseeding. Torn down with _configured everywhere a slot is.
        self._restored: set = set()
        # device-side knob cache: knobs only change at admission or a
        # min-tokens ban flip, so the steady-state decode loop reuses
        # the same device arrays instead of re-uploading every step
        self._knobs_device = None
        # dispatch-ahead window (docs/serving.md "Dispatch-ahead
        # decode"): up to ``dispatch_ahead`` decode dispatches stay in
        # flight BEHIND the one being consumed, each chained on the
        # previous dispatch's device token handle, so the decode-fence
        # readback of step N overlaps the device work of steps
        # N+1..N+W. The deque holds _InFlight entries oldest-first; the
        # delayed consumer (_consume_window) pops them. W=0 keeps the
        # deque depth at zero across step() calls — dispatch-then-
        # consume within one step, byte-for-byte the pre-window engine.
        self.dispatch_ahead = int(dispatch_ahead)
        self._window: deque = deque()
        # watchdog cold-start grace: the step timeout arms only after
        # one healthy step has completed (see _timed_out)
        self._warm = False
        # feasibility admission control (EDF-with-admission-control):
        # when on, _admit deadline-drops WAITING requests the running
        # decode_step_s median says cannot finish inside their deadline —
        # not just those already expired (finish_reason="infeasible")
        self.deadline_feasibility = bool(deadline_feasibility)
        # decode-stall bookkeeping: wall time of the last completed
        # decode/verify dispatch, None while no rows are in flight —
        # the gap between consecutive dispatches over a live batch is
        # the stall signal chunked admission bounds (serving/
        # decode_gap_s)
        self._last_decode_end: Optional[float] = None
        if admission in ("batched", "chunked"):
            # the tensor-parallel prefill shares the mesh (and must name
            # the sampling carry leaves in its shard_map specs); data-
            # only planes keep the stock prefill — its output rows
            # reshard into the sharded pool through the scatter
            self._batch_prefill_fn = get_batch_prefill_step(
                model, compute_dtype, mesh=self.mesh if tp else None,
                carry_sampling=tp, kv_quant=kv_quant,
                adapter=self._adapter_spec)
            # True -> default cache, False/None -> off, else an instance
            self.prefix_cache = (PrefixCache() if prefix_cache is True
                                 else (prefix_cache or None))
            # tier-backed prefix spill: capacity evictions demote to
            # the host tier and lookups promote back (kv_tier.py); an
            # explicitly pre-wired cache keeps its own tier
            if (self.tier is not None and self.prefix_cache is not None
                    and self.prefix_cache.tier is None):
                self.prefix_cache.tier = self.tier
            if admission == "chunked":
                from bigdl_tpu.serving.chunked import (
                    ChunkedAdmissionController,
                )

                self.admitter = ChunkedAdmissionController(
                    self, chunk_budget=chunk_budget or 32,
                    prefix_cache=self.prefix_cache)
            else:
                self.admitter = AdmissionController(
                    self, prefix_cache=self.prefix_cache)
        else:
            if prefix_cache:
                raise ValueError(
                    "prefix_cache requires admission='batched' or "
                    "'chunked' (the per-request prefill cannot continue "
                    "from a cached carry)")
            self.prefix_cache = None
            self.admitter = None
            self._prefill_fn = get_prefill_step(model, compute_dtype,
                                                kv_quant=kv_quant)
            # ONE fresh B=1 carry for prefill, built once and reused for
            # every admission (prefill returns a new carry; jax arrays
            # are immutable, so sharing the zero input is free — at 137M
            # scale a per-admission rebuild would be ~12 MB of pure
            # allocation churn). pool_init's carry layout is
            # make_decode_step's, so n_slots=1 IS the single-request
            # carry.
            self._zero_carry1 = pool_init(1)
        self._next_id = 0
        self._finished: Dict[int, Request] = {}
        # the SLO autopilot (serving/autopilot.py): an engine-wide
        # ceiling on the speculative draft count (runtime data the
        # super-step's _draft_budget reads — never a recompile), and
        # the closed control loop itself, sampled once at the end of
        # every step() on the engine clock. attach() binds the
        # actuator bus to this engine and folds the measured
        # service-time estimate into the scheduler's priority key.
        self.draft_cap: Optional[int] = None
        self.autopilot = autopilot or None
        if self.autopilot is not None:
            self.autopilot.attach(self)

    # -- request surface ---------------------------------------------------

    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int = 32,
               eos_id: int = -1, sampling: Optional[SamplingParams] = None,
               draft_tokens: Optional[int] = None, priority: int = 0,
               deadline_s: Optional[float] = None, degrade=None,
               adapter_id: int = 0, constraint=None) -> int:
        """Queue one generation request (1-based prompt ids, like
        ``generate()``); returns its request id. Raises if the request
        could ever overflow the cache (same ``max_len`` guard as
        ``generate()``).

        ``eos_id`` is the request's PRIVATE eos (1-based; -1 = none) —
        different requests in the same batch may stop on different
        tokens; it joins ``sampling.stop_token_ids`` in the min-tokens
        device ban. ``sampling`` carries the request's
        :class:`~bigdl_tpu.serving.sampling.SamplingParams` (None =
        greedy defaults, the pre-sampling engine behavior);
        ``sampling.max_tokens`` (when set) overrides
        ``max_new_tokens``; ``draft_tokens`` is the request's
        speculative-decoding budget HINT (None = the engine's configured
        draft count, 0 = plain decode for this request, n = at most n
        drafts per super-step, clamped to the engine's ``k``; ignored
        by non-speculative engines, so traces stay portable across
        engine configs).

        Resilience knobs (ignored semantically outside their engine
        configs, so traces stay portable): ``priority`` orders the
        queue and selects preemption victims under ``policy=
        "priority"`` (higher admits first); ``deadline_s`` is the
        request's completion SLO in seconds after submit (expired
        WAITING requests are dropped with ``finish_reason="deadline"``,
        late finishes count against ``serving/goodput``); ``degrade``
        is a :class:`~bigdl_tpu.serving.admission.Degrade` applied at
        admission when the engine is under pressure. When the engine's
        ``max_queue`` is set and the waiting BACKLOG (queue depth minus
        free slots) has reached it, the request is SHED instead of
        queued: it lands in the finished ledger with
        ``finish_reason="shed"`` and empty output — still returns the
        request id, so callers observe backpressure per request rather
        than as an exception.

        Multi-tenant knobs: ``adapter_id`` selects the request's LoRA
        adapter in the engine's :class:`~bigdl_tpu.serving.lora.
        AdapterBank` (0 = the null adapter ≡ base model; nonzero ids
        must be live in the bank, and the engine RETAINS the slot for
        the request's lifetime so a tenant unload cannot recycle
        factors under an in-flight row). On a SPECULATIVE engine a
        nonzero ``adapter_id`` requires ``draft_tokens=0``: the draft
        model carries no adapter factors, and scoring base-model drafts
        against an adapted target would silently corrupt accept-rate
        accounting — pinned by tests/test_serving_lora.py.
        ``constraint`` is an optional
        :class:`~bigdl_tpu.serving.constrain.TokenDFA`: the engine
        advances its cursor per emitted token and masks the row's
        sampler to the tokens the automaton allows (the per-row
        ``allow`` knob); constrained rows on a speculative engine run
        with draft budget 0 (the mask is per-position — a multi-token
        super-step would verify against a stale mask)."""
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("need a non-empty prompt")
        if draft_tokens is not None and int(draft_tokens) < 0:
            raise ValueError(
                f"draft_tokens must be >= 0 or None, got {draft_tokens}")
        adapter_id = int(adapter_id)
        if adapter_id:
            if self.adapters is None:
                raise ValueError(
                    f"adapter_id={adapter_id} but this engine has no "
                    "AdapterBank (pass adapters= at construction)")
            if not self.adapters.is_live(adapter_id):
                raise ValueError(
                    f"adapter id {adapter_id} is not allocated in the "
                    "bank (alloc() it first, or use 0 = base model)")
            if self._spec is not None and (draft_tokens is None
                                           or int(draft_tokens) > 0):
                raise ValueError(
                    "adapted requests on a speculative engine must "
                    "submit draft_tokens=0 — drafts are pinned to the "
                    "null adapter, and a base-model draft chain under "
                    "an adapted target would corrupt accept-rate "
                    "accounting")
        if constraint is not None and not hasattr(constraint, "cursor"):
            raise ValueError(
                "constraint must be a TokenDFA-like object with a "
                ".cursor(prefix) method (serving/constrain.py)")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be positive or None, got {deadline_s}")
        # SamplingParams validates on construction (frozen dataclass)
        sp = sampling if sampling is not None else SamplingParams()
        if sp.max_tokens is not None:
            max_new_tokens = sp.max_tokens
        if max_new_tokens <= 0:
            raise ValueError(
                f"max_new_tokens must be positive, got {max_new_tokens}")
        if len(prompt) - 1 + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the model's max_len "
                f"{self.max_len} — the cache position would silently "
                "clamp (same guard as generate())")
        # every validation precedes the submitted counter and the shed
        # decision: an invalid call must raise the same way loaded or
        # idle, and must never skew serving/submitted (goodput's
        # denominator)
        rid = self._next_id
        self._next_id += 1
        req = Request(
            req_id=rid, prompt=prompt, max_new_tokens=int(max_new_tokens),
            eos_id=int(eos_id), sampling=sp,
            draft_tokens=None if draft_tokens is None else int(draft_tokens),
            priority=int(priority),
            deadline_s=None if deadline_s is None else float(deadline_s),
            degrade=degrade,
            adapter_id=adapter_id, constraint=constraint,
            submit_time=self._clock())
        # hold the adapter slot for the request's lifetime (released at
        # every terminal disposition: finish, shed, cancel)
        if adapter_id:
            self.adapters.retain(adapter_id)
        self.metrics.on_submit()
        # admission backpressure: a bounded queue sheds at the door —
        # the cheapest place to reject work is before any of it runs.
        # The bound is on the BACKLOG (waiting beyond what the pool's
        # free slots will absorb at the next admission), so an idle
        # engine with free capacity never sheds — max_queue=0 means
        # "serve up to capacity, queue nothing", not "serve nothing".
        if self.max_queue is not None \
                and (self.scheduler.queue_depth - self.pool.free_slots
                     >= self.max_queue):
            self._shed(req, "shed")
            return rid
        self.scheduler.submit(req)
        return rid

    def result(self, req_id: int) -> Optional[np.ndarray]:
        """Generated 1-based ids for a FINISHED request, else None
        (also None once evicted by ``keep_finished``/``pop_result``)."""
        req = self._finished.get(req_id)
        return None if req is None else np.asarray(req.output, np.int32)

    def pop_result(self, req_id: int) -> Optional[np.ndarray]:
        """Like :meth:`result` but RELEASES the request's ledger entry —
        the memory-bounding consumption pattern for long-lived engines
        (take each output exactly once; see ``keep_finished`` for the
        automatic alternative)."""
        req = self._finished.pop(req_id, None)
        return None if req is None else np.asarray(req.output, np.int32)

    def logprobs(self, req_id: int) -> Optional[np.ndarray]:
        """Chosen-token raw model log-probs for a FINISHED request (one
        per output token), else None — the logprobs twin of
        :meth:`result`."""
        req = self._finished.get(req_id)
        return None if req is None else np.asarray(req.logprobs, np.float32)

    def cancel(self, req_id: int) -> bool:
        """Cancel a WAITING or RUNNING request. A waiting request is
        dequeued and never occupies a slot; a RUNNING request's slot is
        freed immediately — target AND draft caches alike (``pool.free``
        resets both position counters), mid-speculative-chunk included —
        and no token is ever emitted for it again (the next step simply
        has no such row). Either way the request lands in the finished
        ledger with state 'cancelled', keeping whatever output it had
        already emitted. Returns False (no-op) for requests already
        finished or unknown."""
        req = self.scheduler.cancel(req_id)
        if req is None:
            req = self.scheduler.cancel_running(req_id)
            if req is None:
                return False
            slot, req.slot = req.slot, None
            self.pool.free(slot)
            self._configured.discard(slot)
            self._restored.discard(slot)
            self._constraints.pop(slot, None)
            if self.admitter is not None:
                self.admitter.drop(slot)       # mid-prefill chunk plan
        # WAITING cancellations drop their stashed payload too: a
        # preempted/handed-off row cancelled before readmission must
        # not pin its KV slices in the finished ledger forever (the
        # same teardown contract _shed follows)
        req.resume_carry = None
        self._drop_tier_row(req)
        self._release_adapter(req)
        self.metrics.on_cancel()
        # cancellation is a disposition too: without this bucket the
        # finish_<reason> counters would not sum to every request's
        # fate (the accounting contract docs/serving.md states)
        self.metrics.on_finish_reason("cancelled")
        self._finished[req_id] = req
        self._evict_finished()
        return True

    def request(self, req_id: int) -> Optional[Request]:
        return self._finished.get(req_id)

    # -- the serving loop --------------------------------------------------

    def _evict_finished(self) -> None:
        # dict preserves insertion order = finish order → oldest-first
        if self.keep_finished is None:
            return
        while len(self._finished) > self.keep_finished:
            self._finished.pop(next(iter(self._finished)))

    def _place_rows(self, x):
        """Commit a per-slot array to the plane's mesh (identity on the
        single-device plane). Every slot-axis array the step consumes
        goes through here so its sharding matches the pooled carry —
        mismatched placements would recompile or silently gather."""
        return x if self._plane is None else self._plane.place_rows(x)

    def _admit(self) -> None:
        import jax.numpy as jnp

        now = self._clock()
        # deadline-drop: an expired WAITING request can only miss its
        # SLO — spending decode steps on it starves requests that can
        # still make theirs
        for req in self.scheduler.pop_expired(now):
            self._shed(req, "deadline")
        # the static degrade path's REVERT half: when the queue has
        # drained back below the pressure threshold, still-WAITING
        # degraded rows (preempted/fault-evicted under the burst) get
        # their recorded original limits back — a burst's clamp must
        # not outlive the burst (the autopilot's bus drives the same
        # restore from its own controller when attached)
        if (self.degrade_at is not None
                and self.scheduler.queue_depth < self.degrade_at):
            for req in self.scheduler.iter_waiting():
                self._restore_degrade(req)
        # feasibility admission control: with a measured per-token
        # service-time estimate in hand, a request whose DECLARED
        # budget (max_new_tokens — the only bound available before the
        # model runs; EOS-early traffic under a generous cap is shed
        # conservatively, so deadline callers should set honest caps)
        # cannot fit inside its deadline even decoding uncontended
        # from this instant is dropped at the door instead of spending
        # steps proving the miss. The
        # estimate is the running decode_step_s MEDIAN (robust to the
        # cold-compile first step and stall outliers) divided by the
        # measured tokens-per-step, so a speculative engine's
        # multi-token super-steps don't overstate service time and
        # shed requests that would have made it. Before the first
        # decode step there is no estimate and nothing is dropped —
        # feasibility control never guesses.
        if self.deadline_feasibility:
            est = self.metrics.service_time_estimate()
            if est is not None:
                def _infeasible(req: Request) -> bool:
                    dl = req.deadline_time
                    if dl is None:
                        return False
                    # price the budget the request would ACTUALLY get:
                    # under pressure _maybe_degrade will cap
                    # max_new_tokens at admission, and shedding on the
                    # un-degraded budget would drop requests the cap
                    # makes feasible (mirrors _maybe_degrade's
                    # first-admission condition)
                    cap = req.max_new_tokens
                    if (req.degrade is not None and not req.degraded
                            and not req.output
                            and self.degrade_at is not None
                            and self.scheduler.queue_depth
                            >= self.degrade_at
                            and req.degrade.max_new_tokens is not None):
                        cap = min(cap, int(req.degrade.max_new_tokens))
                    rem = cap - len(req.output)
                    return now + est * rem > dl

                for req in self.scheduler.pop_waiting(_infeasible):
                    self.metrics.on_infeasible()
                    self._shed(req, "infeasible")
        # loss-free preemption (priority policy): evict lowest-priority
        # running rows while strictly-higher-priority requests wait
        # without a free slot — each eviction stashes the row's KV for
        # byte-exact resumption, so this trades latency across classes
        # without ever trading correctness
        if self.preemption:
            while True:
                victim = self.scheduler.lowest_running()
                if victim is None:
                    break
                demand = self.scheduler.waiting_higher_than(victim.priority)
                if demand <= self.pool.free_slots:
                    break
                if self._window:
                    # a preemption spill snapshots the victim's DEVICE
                    # row state — with dispatches in flight the device
                    # KV is up to W positions AHEAD of the host's
                    # emitted prefix, so a mid-window spill would
                    # resume the row desynchronized. Flush first (only
                    # when a preemption is actually due — the window
                    # stays hot otherwise), then re-select: the flush
                    # may have finished the victim or freed its slot.
                    self._drain_window({})
                    continue
                self._preempt_row(victim)
            # deadline-aware preemption (autopilot): evict long-slack
            # running rows so short-deadline FEASIBLE waiters seat
            # before their would-miss point — within or below class,
            # where the static loop above only trades across classes.
            # Loss-free like every preemption: latency reorders,
            # tokens never do.
            if self.autopilot is not None:
                victims = list(self.autopilot.deadline_victims(self, now))
                if victims and self._window:
                    # same mid-window spill hazard; re-select after
                    # the flush for the same reasons as above
                    self._drain_window({})
                    victims = list(
                        self.autopilot.deadline_victims(self, now))
                for victim in victims:
                    self._preempt_row(victim)
        n = self.scheduler.admissible(self.pool.free_slots)
        if not n:
            return
        if self.tier is not None:
            # batch the host->host fetches for the rows about to seat
            # BEFORE admission touches the device, so tier latency
            # never lands inside the decode gap (the fetch itself is
            # host-side; only restore_row uploads, same as the legacy
            # stash path)
            self.tier.prefetch(self.scheduler.peek_waiting(n))
        if self.admitter is not None:
            # batched admission: bucketed multi-row masked prefill with
            # optional shared-prefix reuse (serving/admission.py)
            self.admitter.admit(n)
            self._note_shard_balance()
            return
        for _ in range(n):
            slot = self.pool.alloc()
            assert slot is not None          # admissible() checked
            req = self.scheduler.admit(slot)
            # the last fed token is the first decode input — exactly
            # generate()'s convention, so outputs match token-for-token
            # (called before the resume check: next_token/degrade are
            # needed on the restored path too)
            pf = self._admitted_prefill_tokens(req)
            payload = self._resume_payload(req)
            if payload is not None:
                # byte-exact resume: the stashed/spilled row_state
                # payload (KV + scales + lanes + mirrors + draft)
                # restores whole — _configure_slot then sets knobs only
                self.pool.restore_row(slot, payload)
                req.resume_carry = None
                self._restored.add(slot)
                continue
            if not pf:
                self.pool.set_pos(slot, 0)
                continue
            ptoks = jnp.asarray([pf], jnp.int32)
            try:
                _, pc = self._dispatch("prefill", self._prefill_fn,
                                       self.params, ptoks,
                                       self._zero_carry1)
            except FaultError:
                self._recover_admission([(slot, req)])
                continue
            # NO completion fence: the prefill dispatch is exactly the
            # work async dispatch-ahead overlaps with the decode step —
            # the step's one decode fence absorbs its completion, and
            # the per-phase prefill timer went with the wait (a timer
            # here would measure the launch — the ASY305 lie). The
            # PR 12 worksheet marked this site deletable
            # (docs/async_readiness.md).
            self.pool.write_prefill(slot, pc, len(pf))
        self._note_shard_balance()

    # -- resilience: shedding, degradation, preemption, recovery -----------

    def _release_adapter(self, req: Request) -> None:
        """Drop the adapter-slot reference :meth:`submit` took — called
        from every terminal disposition exactly once (finish ledger,
        shed, cancel), so a freed tenant's slot recycles only after its
        last in-flight request is gone."""
        if (req.adapter_id and self.adapters is not None
                and not getattr(req, "_adapter_released", False)):
            req._adapter_released = True   # terminal paths run once
            self.adapters.free(req.adapter_id)

    def _shed(self, req: Request, reason: str) -> None:
        """Load-shed a request WITHOUT running it (queue-full submit,
        waiting-deadline expiry, or a feasibility drop): ledgered with
        ``finish_reason`` set and empty output — observable
        backpressure, never an exception. Deadline expiry and
        feasibility drops both count as deadline misses (either way
        the SLO was not going to be met)."""
        self._release_adapter(req)
        req.state = SHED
        req.finish_reason = reason
        # a PREEMPTED request re-entering the queue carries its stashed
        # KV row; shedding it must drop that stash (n_layers*2 max_len
        # device slices) or the finished ledger pins it forever — the
        # same teardown contract cancel() follows
        req.resume_carry = None
        self._drop_tier_row(req)
        req.finish_time = self._clock()
        self._finished[req.req_id] = req
        self._evict_finished()
        self.metrics.on_finish_reason(reason)
        self.metrics.on_shed(deadline=(reason in ("deadline",
                                                  "infeasible")))

    def _maybe_degrade(self, req: Request) -> None:
        """Apply the request's ``degrade`` knob at FIRST admission when
        the waiting queue is at or past ``degrade_at`` — pure host-side
        bookkeeping (the caps become per-row runtime data)."""
        if (req.degrade is None or req.degraded or req.output
                or self.degrade_at is None
                or self.scheduler.queue_depth < self.degrade_at):
            return
        self._apply_degrade(req)

    def _apply_degrade(self, req: Request) -> bool:
        """The ONE degrade writer (a declared ACTUATION_SITES unit —
        serving/autopilot.py): clamp the request to its submitted
        ``Degrade`` knobs, RECORDING the originals on the request so
        the clamp is revertible while the row still waits. Both the
        static ``degrade_at`` path (via ``_maybe_degrade``) and the
        autopilot's per-class pressure loop land here. False when
        there is nothing to do (no knob, or already degraded)."""
        d = req.degrade
        if d is None or req.degraded:
            return False
        req._pre_degrade = (req.max_new_tokens, req.draft_tokens)
        if d.max_new_tokens is not None:
            req.max_new_tokens = min(req.max_new_tokens,
                                     int(d.max_new_tokens))
        if d.draft_tokens is not None:
            req.draft_tokens = int(d.draft_tokens)
        req.degraded = True
        self.metrics.on_degrade()
        return True

    def _restore_degrade(self, req: Request) -> bool:
        """Revert ``_apply_degrade`` for a still-WAITING row (a
        declared ACTUATION_SITES unit): put the recorded original
        ``max_new_tokens``/``draft_tokens`` back and clear the degraded
        mark, so the knob can re-apply if pressure returns. Only
        WAITING rows restore — a seated row's budget was already
        priced into its admission (feasibility, chunk planning), and a
        preempted-then-requeued row IS waiting, which is exactly the
        regression this fixes: before PR 19 a row degraded at a
        queue-depth spike kept its clamp forever, burst or no burst.
        False when the row is not a restorable degraded waiter."""
        if (not req.degraded or req._pre_degrade is None
                or req.state != WAITING):
            return False
        mnt, dt = req._pre_degrade
        # never clamp BELOW what already streamed out (a preempted
        # row's emitted tokens are immutable history)
        req.max_new_tokens = max(int(mnt), len(req.output))
        req.draft_tokens = dt
        req._pre_degrade = None
        req.degraded = False
        self.metrics.on_degrade_restored()
        return True

    def _admitted_prefill_tokens(self, req: Request) -> List[int]:
        """0-based tokens whose K/V must be resident before ``req``
        decodes: the original prompt plus everything already emitted —
        empty output for fresh requests, the REPLAY source for
        preempted/fault-evicted rows (the stream is its own lineage:
        re-prefilling ``prompt + output`` reconstructs exactly the
        cache state the evicted row had). Sets ``req.next_token`` to
        the last fed token and applies the degrade knob under
        pressure; returns everything before it (the prefill list)."""
        self._maybe_degrade(req)
        fed0 = [t - 1 for t in req.prompt] + [t - 1 for t in req.output]
        req.next_token = fed0[-1]
        return fed0[:-1]

    def _spill_or_carry(self, req: Request, payload: Optional[dict]) -> None:
        """Park a row's ``row_state`` payload for later readmission:
        into the host tier when one backs this engine (packed host
        bytes under the tier's budget — THE unified stash path), else
        on ``req.resume_carry`` (the legacy in-memory stash of device
        slices). One spelling for preemption, the disagg transfer
        requeue, and handoff staging."""
        if payload is None:
            return
        if self.tier is not None:
            self.tier.put_row(req, payload)
        else:
            req.resume_carry = payload

    def _resume_payload(self, req: Request) -> Optional[dict]:
        """The byte-exact resume source for a (re)admitted request: its
        in-memory stash if one rode the request (tier-less engines),
        else a currency-checked fetch from the host tier. None -> no
        resident copy: the row replays via prefill of ``prompt +
        output`` (the PR 8 contract — a budget-evicted tier entry
        downgrades to replay, never to corruption). Mid-stream resumes
        count ``serving/resumed_without_prefill``."""
        payload = req.resume_carry
        if payload is None and self.tier is not None:
            payload = self.tier.fetch_row(req)
        if payload is not None and req.output:
            self.metrics.on_resume_without_prefill()
        return payload

    def _drop_tier_row(self, req: Request) -> None:
        """Tier-side twin of ``req.resume_carry = None``: every
        terminal (or carry-distrusting) disposition drops the
        request's spilled row eagerly, so the host tier never pins a
        dead row's bytes — the fix for the old disagg wart where a
        finished row's stash lingered until a later hygiene sweep."""
        if self.tier is not None:
            self.tier.drop_row(req.req_id)

    def _dispatch(self, site: str, fn, *args):
        """Every serving-path device dispatch routes through here so
        the optional :class:`~bigdl_tpu.serving.faults.FaultInjector`
        can fail, corrupt, or stall it deterministically — a no-op
        passthrough without one."""
        if self._faults is None:
            return fn(*args)
        return self._faults.call(site, fn, *args)

    def _preempt_row(self, victim: Request) -> None:
        """Loss-free preemption of one RUNNING row: stash its FULL
        ``pool.row_state`` payload (KV + int8 scales + RNG lane +
        penalty counts + chunk mirrors + draft slice — restored
        bitwise at readmission through ``restore_row``, the same
        serialization the disaggregated handoff speaks) — into the
        host tier when one is attached (packed bytes under the tier
        budget, HBM freed outright), else on the request, share its
        carry into the prefix cache when one is attached (any request
        on the same prefix benefits), then free the slot and requeue
        the request at its ORIGINAL arrival key — preemption reorders
        latency, never tokens."""
        slot = victim.slot
        payload = self.pool.row_state(slot)
        if len(victim.prompt) + len(victim.output) > 1:
            self._spill_or_carry(victim, payload)
            if self.prefix_cache is not None:
                fed0 = [t - 1 for t in victim.prompt] + \
                       [t - 1 for t in victim.output]
                # namespaced by the victim's adapter: its K/V was
                # computed under those factors and must never serve a
                # prefix hit for another tenant
                self.prefix_cache.insert(fed0[:-1], payload["carry"],
                                         adapter_id=victim.adapter_id)
        victim.preemptions += 1
        self.scheduler.requeue(victim)            # running -> waiting
        self.pool.free(slot)
        self._configured.discard(slot)
        self._restored.discard(slot)
        self._constraints.pop(slot, None)
        self.metrics.on_preempt()

    def _recover_rows(self, rows, now: float) -> None:
        """Fault-recovery disposition for evicted rows: requeue each
        request for loss-free replay (its carry is never trusted — the
        stream replays via prefill of ``prompt + output``), or fail it
        out with ``finish_reason='error'`` once past the watchdog's
        per-request retry budget. Either way the engine keeps making
        progress — a persistent fault fails requests, not the engine."""
        for slot, req in rows:
            self._configured.discard(slot)
            self._restored.discard(slot)
            self._constraints.pop(slot, None)
            if self.admitter is not None:
                self.admitter.drop(slot)       # mid-prefill chunk plan
            req.retries += 1
            req.resume_carry = None
            # recovery never trusts a stashed copy either: a faulted
            # step may postdate the spill, so the tier row is dropped
            # and the request replays from prompt + output
            self._drop_tier_row(req)
            mr = self.watchdog.max_retries
            if mr is not None and req.retries > mr:
                self._finish_row(req, "error", now)   # frees the slot
            else:
                self.scheduler.requeue(req)           # running -> waiting
                self.pool.free(slot)
                self.metrics.on_retry()

    def _recover_admission(self, rows) -> None:
        """An admission-side prefill dispatch faulted: evict exactly
        its rows (slots freed, requests requeued or failed out); other
        buckets in the same admission round proceed normally."""
        self._recover_rows(rows, self._clock())

    def _recover_step(self, running, kind: str) -> None:
        """A decode/verify step failed (raised dispatch, garbage
        outputs, watchdog timeout): discard the step's outputs and
        evict EVERY implicated row — a whole-batch dispatch fault
        cannot be attributed to one row — for loss-free replay."""
        self._recover_rows(list(running.items()), self._clock())

    def _step_unhealthy(self, nxt, lps, active) -> Optional[str]:
        """Garbage verdict on a decode step's host-read outputs:
        non-finite chosen log-probs or out-of-range tokens on active
        rows (the NaN-logits / corrupted-readback failure shape).
        None = healthy."""
        if active.any():
            a_tok, a_lp = nxt[active], lps[active]
            if (not np.isfinite(a_lp).all() or (a_tok < 0).any()
                    or (a_tok >= self._vocab).any()):
                return "garbage"
        return None

    def _timed_out(self, elapsed: float) -> bool:
        """Watchdog timeout verdict. The timeout arms only after the
        engine's FIRST healthy step: a cold engine's first dispatch
        carries the one-time XLA compile (multi-second at LM scale on a
        real clock), and evicting the whole batch for a healthy-but-
        compiling device would burn every request's retry budget at
        startup. A stall missed during that grace window is only a slow
        CORRECT step — its outputs are valid, so accepting them costs
        latency, never correctness."""
        to = self.watchdog.step_timeout_s
        return to is not None and self._warm and elapsed > to

    def _note_shard_balance(self) -> None:
        """Post-admission shard-balance sample (sharded pools only):
        per-shard occupancy extremes + the max−min admission imbalance
        the balanced allocator is supposed to keep ≤ 1."""
        if self.pool.n_shards > 1:
            self.metrics.on_shard_slots(self.pool.used_per_shard(),
                                        self.pool.rows_per_shard)

    def _lane_key(self, req: Request):
        """The request's RNG-lane key: an explicit ``SamplingParams.seed``
        pins the lane (``sampling.lane_key`` — the rule ``generate()``
        shares), else a fresh lane folded from the engine seed and the
        request id. Either way the lane is a function of the REQUEST,
        never the slot, so readmission into any slot replays the same
        stream."""
        import jax

        from bigdl_tpu.serving.sampling import lane_key

        sp = req.sampling
        if sp.seed is not None:
            return lane_key(sp.seed)
        return jax.random.fold_in(lane_key(self.seed), req.req_id)

    def _configure_slot(self, slot: int, req: Request) -> None:
        """Thread one admitted request's SamplingParams into its slot:
        knob rows on host, RNG lane + penalty state on device. For a
        READMITTED request (preempted or fault-evicted mid-stream —
        ``req.output`` non-empty) the state resumes where it left off:
        the lane fast-forwards by one split per emitted draw
        (:func:`~bigdl_tpu.serving.sampling.advance_lane` — the lane
        after n draws is a pure function of the request seed), penalty
        counts rebuild from the emitted tokens, and the min-tokens ban
        reflects the CURRENT output length, not the fresh-request
        default. That host-side reconstruction is the whole loss-free
        eviction contract's second half (the KV half is prefill
        replay/the stashed row). A slot RESTORED from a full
        ``row_state`` payload (preemption resume, disaggregated
        handoff) skips the device half entirely: its lane, counts, and
        draft cache arrived verbatim with the payload — byte-identical
        to what the rebuild would write, without the device traffic —
        and only the host knob rows are (re)built here."""
        sp = req.sampling
        scal, ban_row = knob_row_values(sp, req.eos_id)
        for k, v in scal.items():
            self._knobs[k][slot] = v
        self._knobs["ban_ids"][slot] = ban_row
        self._ban_base[slot] = self._knobs["ban"][slot]
        if self._ban_base[slot] and req.output:
            # resumed mid-stream: the ban may already have lifted
            self._knobs["ban"][slot] = len(req.output) < sp.min_tokens
        # the slot's adapter id (runtime data of the compiled steps;
        # already set for restored rows — the payload carried it — but
        # rewriting the same value is harmless and covers every path)
        self.pool.adapter_ids[slot] = req.adapter_id
        # constraint cursor: rebuilt from (constraint, emitted prefix)
        # — THE replay rule; a recycled slot's stale mask is always
        # overwritten (all-True for unconstrained occupants)
        self._constraints.pop(slot, None)
        if req.constraint is not None:
            cur = req.constraint.cursor(req.output)
            self._constraints[slot] = cur
            cur.mask_row(self._vocab, out=self._knobs["allow"][slot])
        else:
            self._knobs["allow"][slot][:] = True
        self._knobs_device = None                # re-upload next step
        if slot in self._restored:
            self._restored.discard(slot)
            self._configured.add(slot)
            return
        key = self._lane_key(req)
        if req.output:
            key = advance_lane(key, len(req.output))
        self.pool.write_sampling(slot, key, req.prompt,
                                 output_ids=req.output)
        if self._spec is not None:
            # the draft cache ingests the fed stream alongside the
            # target's (every admission path configures through here)
            self._spec.prefill_draft(slot, req)
        self._configured.add(slot)

    def _finish_check(self, req: Request) -> Optional[str]:
        """Stop/length decision for the token JUST appended to
        ``req.output`` — THE one copy of the per-token finish rule
        (the decode loop and the speculative chunk emission both apply
        it, token by token, so multi-token super-steps stop exactly
        where the baseline would)."""
        sp = req.sampling
        n_out = len(req.output)
        tok1 = req.output[-1]
        if n_out >= sp.min_tokens:
            if req.eos_id > 0 and tok1 == req.eos_id:
                return "eos"
            if (tok1 in sp.stop_token_ids
                    or match_stop_sequences(req.output, sp.stop_sequences)):
                return "stop"
        if n_out >= req.max_new_tokens:
            return "length"
        return None

    def _finish_row(self, req: Request, reason: str, now: float) -> None:
        """Evict a finished request: free its slot, then the shared
        ledger tail (:meth:`_ledger_finish`)."""
        freed = self.scheduler.finish(req, now)
        self.pool.free(freed)
        self._configured.discard(freed)
        self._restored.discard(freed)
        self._constraints.pop(freed, None)
        self._ledger_finish(req, reason, now)

    def _ledger_finish(self, req: Request, reason: str,
                       now: float) -> None:
        """THE finish-ledger tail — reason counter, finished ledger,
        latency/logprob/SLO accounting (plus the recovery-success
        counter for requests that survived an eviction). One spelling
        shared by :meth:`_finish_row` (slot-holding rows) and slotless
        terminations (the disaggregated plane's transfer-retry
        error-out), so a new finish-time counter can never cover one
        path and miss the other."""
        self._release_adapter(req)
        req.finish_reason = reason
        req.resume_carry = None
        self._drop_tier_row(req)
        req.state = FINISHED
        req.finish_time = now
        self._finished[req.req_id] = req
        self._evict_finished()
        self.metrics.on_finish_reason(reason)
        if reason == "error":
            met = None          # neither goodput nor a deadline miss
        else:
            dl = req.deadline_time
            met = dl is None or now <= dl
            if req.retries > 0:
                self.metrics.on_recovered()
        self.metrics.on_finish(
            now - req.submit_time, len(req.output),
            mean_logprob=(float(np.mean(req.logprobs))
                          if req.logprobs else None),
            met_deadline=met)

    def _maybe_flip_ban(self, slot: int, req: Request) -> None:
        """min-tokens ban lifts the step the floor is met — a runtime
        VALUE change, never a recompile."""
        if self._ban_base[slot]:
            ban = len(req.output) < req.sampling.min_tokens
            if ban != self._knobs["ban"][slot]:
                self._knobs["ban"][slot] = ban
                self._knobs_device = None

    def _advance_constraint(self, slot: int, req: Request) -> None:
        """Advance a constrained row's automaton over the token JUST
        emitted and rewrite its allow-mask row — a runtime VALUE
        change, never a recompile (the constrained twin of
        :meth:`_maybe_flip_ban`; no-op for unconstrained rows)."""
        cur = self._constraints.get(slot)
        if cur is None:
            return
        cur.advance(req.output[-1])
        cur.mask_row(self._vocab, out=self._knobs["allow"][slot])
        self._knobs_device = None

    def _bank_device_arrays(self):
        """The adapter bank as placed device arrays, cached against the
        bank's version counter (tenant alloc/free re-uploads; the
        steady-state decode loop reuses). Tensor-parallel planes pin
        the Megatron bank sharding (``adapter_bank_specs``)."""
        if (self._bank_device is None
                or self._bank_version != self.adapters.version):
            import jax

            bank = self.adapters.device_arrays()
            if self._plane is not None and self._plane.tensor_parallel:
                from bigdl_tpu.models.transformer import adapter_bank_specs
                from bigdl_tpu.serving.sharded import named_sharding

                specs = adapter_bank_specs(self.model)
                bank = jax.device_put(
                    bank, {k: named_sharding(self.mesh, specs[k])
                           for k in bank})
            self._bank_device = bank
            self._bank_version = self.adapters.version
        return self._bank_device

    def _adapter_args(self):
        """The decode/verify dispatch's trailing adapter arguments:
        ``()`` without a bank, else ``(per-slot adapter ids, bank)`` —
        the ids re-upload each step like the token/active rows (tiny),
        the bank rides the version-keyed cache."""
        if self.adapters is None:
            return ()
        import jax.numpy as jnp

        ids = self._place_rows(jnp.asarray(self.pool.adapter_ids))
        return (ids, self._bank_device_arrays())

    def _prefill_adapter_args(self, row_adapter_ids):
        """The batched-prefill dispatch's trailing adapter arguments
        for one bucket: ``()`` without a bank, else ``(per-ROW ids,
        bank)`` — prefill rows are bucket rows, not pool slots, so the
        admission paths pass the bucket's own id list."""
        if self.adapters is None:
            return ()
        import jax.numpy as jnp

        return (jnp.asarray(np.asarray(row_adapter_ids, np.int32)),
                self._bank_device_arrays())

    def _note_host_step(self, t_begin: float, device_before: float,
                        n_samples: int = 1) -> None:
        """Record the per-super-step TRUE-HOST residue: the step's wall
        time minus the fenced-wait windows timed inside it (the
        ``DEVICE_PHASES`` accumulator — the time the host spent BLOCKED
        on a fence readback or the draft chain's completion pin). What
        remains is the Python the device waits on between dispatches —
        the number the dispatch-ahead window exists to shrink
        (``serving/host_step_s``; percentiles in ``summary()``),
        measured on the engine's clock like every other serving timer.

        ``n_samples`` keeps the host_step_s and decode_step_s series
        comparable sample-for-sample when one super-step consumed
        SEVERAL window entries (a flush): the residue lands once and
        the remaining samples are recorded as zeros — the flush's host
        cost is real but belongs to one wall-clock step."""
        dev = self.metrics.device_seconds - device_before
        self.metrics.add_phase(
            "host_step", max(0.0, (self._clock() - t_begin) - dev))
        for _ in range(max(0, int(n_samples) - 1)):
            self.metrics.add_phase("host_step", 0.0)

    def _note_decode_gap(self, had_running: bool) -> None:
        """Record the wall gap between consecutive decode (or verify)
        dispatch completions while rows stayed in flight across it —
        the decode-stall sample. Admission work between the two
        dispatches (a batched prefill wave, a chunk budget) is exactly
        what stretches the gap, which is the phenomenon
        ``serving_bench --scenario chunked`` measures."""
        now = self._clock()
        if had_running and self._last_decode_end is not None:
            self.metrics.on_decode_gap(now - self._last_decode_end)
        self._last_decode_end = now

    def step(self) -> Dict[int, int]:
        """Admit waiting requests (CHUNKED admission then pumps at most
        ``chunk_budget`` prompt tokens of streaming prefill —
        ``serving/chunked.py``), then decode for every active row: ONE
        token per row on the plain engine, up to ``k + 1`` on a
        speculative engine (draft-and-verify super-step —
        ``serving/speculative.py``). Returns ``{req_id: 1-based token}``
        emitted this step (the LAST emitted token per request when a
        super-step lands several; empty when the engine is idle or
        every slot-holding row is still mid-prefill)."""
        t_step = self._clock()
        dev0 = self.metrics.device_seconds
        ndec0 = self.metrics.decode_step_count
        try:
            return self._step_impl()
        finally:
            # exactly one host/device split sample per decode/verify
            # dispatch sample — recovery paths included (a recovered
            # step's discarded outputs still cost real host time), so
            # the host_step_s and decode_step_s series stay comparable
            # sample for sample. A step that consumed several window
            # entries (a flush) pads with zero samples to keep the pair
            # count aligned; a step that consumed none (filling the
            # window) records nothing — its host cost lands with the
            # step that eventually fences it.
            n_new = self.metrics.decode_step_count - ndec0
            if n_new > 0:
                self._note_host_step(t_step, dev0, n_samples=n_new)
            # the SLO autopilot's ONE control sample per super-step —
            # after the step's metrics landed, idle steps included
            # (pressure relief mostly happens in lulls)
            if self.autopilot is not None:
                self.autopilot.sample(self)

    def _account_token(self, slot: int, req: Request, tok0: int,
                       lp: float, now: float,
                       emitted: Dict[int, int]) -> Optional[str]:
        """Host bookkeeping for ONE emitted token (0-based ``tok0``
        with chosen log-prob ``lp``): append to the request's stream,
        record it in ``emitted``, stamp the first-token latency, and
        return the finish verdict (:meth:`_finish_check` — None =
        still generating). Shared by the decode window's delayed
        consumer and the speculative super-step's emission loop so the
        two planes cannot drift on per-token accounting."""
        tok1 = tok0 + 1                      # back to 1-based ids
        req.output.append(tok1)
        req.logprobs.append(lp)
        emitted[req.req_id] = tok1
        if req.first_token_time is None:
            req.first_token_time = now
            self.metrics.on_first_token(now - req.submit_time)
        return self._finish_check(req)

    def _window_open(self, running) -> bool:
        """May this step EXTEND the dispatch-ahead window — chain a new
        decode dispatch on the newest in-flight dispatch's device token
        handle without fencing anything first? Only when nothing the
        in-flight dispatches assumed has changed: same rows in the same
        slots, knobs still the cached device arrays (no ban flip /
        constraint rewrite invalidated them), no row whose knobs COULD
        change mid-window (an armed min-tokens ban lifts on a consume;
        a constrained row rewrites its allow mask every token). Any
        mismatch answers False and the caller flushes the window
        through the delayed consumer before dispatching classically."""
        if not self._window or self.dispatch_ahead < 1:
            return False
        if self._knobs_device is None:
            return False
        prev = self._window[-1]
        if len(prev.rows) != len(running):
            return False
        for slot, req in prev.rows.items():
            if running.get(slot) is not req:
                return False
            if slot not in self._configured:
                return False
            if slot in self._constraints:
                return False
            if self._ban_base[slot] and self._knobs["ban"][slot]:
                return False
        return True

    def _consume_window(self, emitted: Dict[int, int]) -> bool:
        """THE delayed consumer: fence the OLDEST in-flight decode
        dispatch and run its batched host bookkeeping (health verdict,
        watchdog, metrics, per-token accounting, finish checks). Rows
        that left ``running`` since the dispatch (finished or evicted
        out from under the window) have their readback values
        discarded — per-row independence makes the overshoot
        harmless. Returns False when the entry was unhealthy: its
        outputs are discarded, every implicated row is evicted for
        loss-free replay, and the REST of the window is discarded too
        (every newer dispatch chained through the poisoned carry)."""
        entry = self._window.popleft()
        t_f = self._clock()
        # ONE batched fence readback per dispatch (THE declared
        # delayed-consumer site — fences.DELAYED_CONSUMER_SITES; the
        # (N, V) distribution never crosses to host, only token ids +
        # chosen log-probs do). The t_f/now bracket is the fenced-wait
        # sample: the time the host was genuinely BLOCKED here, the
        # DEVICE_PHASES half of the host_step split.
        nxt, lps = fence("decode", entry.tok, entry.chosen)
        now = self._clock()
        self.metrics.add_phase("fence_wait", now - t_f)
        # the watchdog's elapsed spans dispatch → readback landed; at
        # W>0 that window covers host work on other in-flight steps
        # too, and a stall fault's clock advance at dispatch time is
        # inside it either way, so step_timeout_s keeps firing
        elapsed = now - entry.t0
        self.metrics.add_phase("decode_step", elapsed)
        running = self.scheduler.running
        rows = {slot: req for slot, req in entry.rows.items()
                if running.get(slot) is req}
        bad = self._step_unhealthy(nxt, lps, entry.active)
        if bad is None and self._timed_out(elapsed):
            bad = "timeout"
        if bad is not None:
            # outputs discarded; the pooled carry was committed at each
            # dispatch only so the pool keeps valid (post-donation)
            # buffers — every implicated row is evicted, so its bytes
            # die with the slot. Newer in-flight dispatches chained
            # through the poisoned carry: discard them unfenced. No gap
            # sample either: a discarded step served no tokens, and the
            # evicted batch anchors no future gap
            self._window.clear()
            self._recover_step(rows, bad)
            self._last_decode_end = None
            return False
        self._warm = True                  # arms the watchdog timeout
        # HEALTHY steps only: the decode-stall histogram measures gaps
        # between dispatches that actually served the batch
        self._note_decode_gap(entry.had_running)
        # recency stamps feed the tier's cold-first victim selection:
        # a row decoded this step is never the LRU preemption victim
        self.scheduler.note_decoded(list(rows))
        self.metrics.on_step(self.scheduler.queue_depth,
                             self.pool.occupancy(),
                             int(entry.active.sum()))
        self.metrics.on_sample_rows(entry.n_sampled,
                                    len(entry.rows) - entry.n_sampled)
        for slot, req in list(rows.items()):
            tok0 = int(nxt[slot])
            reason = self._account_token(slot, req, tok0,
                                         float(lps[slot]), now, emitted)
            if reason is not None:
                self._finish_row(req, reason, now)
            else:
                req.next_token = tok0
                self._maybe_flip_ban(slot, req)
                self._advance_constraint(slot, req)
        return True

    def _drain_window(self, emitted: Dict[int, int]) -> bool:
        """Flush every in-flight dispatch through the delayed consumer,
        oldest first. Returns False when a flushed entry was unhealthy
        (the consumer then discarded the rest of the window itself)."""
        while self._window:
            if not self._consume_window(emitted):
                return False
        return True

    def flush_window(self) -> None:
        """Flush every in-flight dispatch through the delayed consumer
        OUTSIDE a step() — drain()'s teardown and the disaggregated
        front end's — with the host/device split pairing intact: the
        flush records one host_step_s sample per consumed entry, so
        the host_step_s and decode_step_s series stay comparable
        sample for sample no matter who drove the flush."""
        if not self._window:
            return
        t0 = self._clock()
        dev0 = self.metrics.device_seconds
        ndec0 = self.metrics.decode_step_count
        self._drain_window({})
        n_new = self.metrics.decode_step_count - ndec0
        if n_new > 0:
            self._note_host_step(t0, dev0, n_samples=n_new)

    def _step_impl(self) -> Dict[int, int]:
        import jax.numpy as jnp

        emitted: Dict[int, int] = {}
        had_running = bool(self.scheduler.running)
        self._admit()
        if self.admitter is not None:
            self.admitter.pump()
        running = self.scheduler.running
        if not running:
            # nothing to dispatch: flush any leftover in-flight work
            # first (rows that finished out from under the window —
            # the consumer's row filter discards their readbacks),
            # then report idle. No decode dispatch this step: a gap
            # measured across an empty batch would be idle time, not
            # a stall
            self._drain_window(emitted)
            self._last_decode_end = None
            return emitted
        if self._spec is not None:
            slots = list(running)
            out = self._spec.step(running)
            # a healthy super-step emits for every running row; an
            # empty dict here means the step faulted and recovery
            # evicted the batch — no dispatch completed, so there is
            # no gap sample and no live batch to anchor the next one
            if out:
                self._note_decode_gap(had_running)
                self.scheduler.note_decoded(slots)
            else:
                self._last_decode_end = None
            return out
        if self._window_open(running):
            # STEADY-STATE window extension: nothing the in-flight
            # dispatches assumed changed, so the next dispatch chains
            # directly on the newest dispatch's device token handle —
            # exactly the value its delayed consumer will set
            # req.next_token to — and reuses its placed active mask.
            # No host→device token upload, no fence, no readback: the
            # device stays fed while step N-W's readback is in flight.
            prev = self._window[-1]
            tokens_dev = prev.tok
            active = prev.active
            active_dev = prev.active_dev
            rows = dict(prev.rows)
            n_sampled = prev.n_sampled
        else:
            # the window's assumptions broke (admission, finish, evict,
            # knob change) or it is empty: flush everything in flight
            # through the delayed consumer, then dispatch classically
            # from host-built token rows
            if not self._drain_window(emitted):
                # a flushed entry was unhealthy — recovery evicted the
                # batch and discarded the window; nothing to dispatch
                return emitted
            running = self.scheduler.running   # a flush may finish rows
            if not running:
                self._last_decode_end = None
                return emitted
            N = self.pool.n_slots
            tokens = np.zeros((N,), np.int32)
            active = np.zeros((N,), bool)
            n_sampled = 0
            for slot, req in list(running.items()):
                if slot not in self._configured:
                    try:
                        self._configure_slot(slot, req)
                    except FaultError:
                        # slot configuration dispatches device work (the
                        # speculative draft prefill) — a fault there
                        # evicts exactly this row for loss-free replay;
                        # the rest of the batch decodes without it
                        self._recover_admission([(slot, req)])
                        continue
                tokens[slot] = req.next_token
                active[slot] = True
                n_sampled += not req.sampling.is_greedy
            if not active.any():
                self._last_decode_end = None
                return emitted
            tokens_dev = self._place_rows(jnp.asarray(tokens))
            active_dev = self._place_rows(jnp.asarray(active))
            rows = {slot: req for slot, req in running.items()
                    if active[slot]}
        t0 = self._clock()
        if self._knobs_device is None:
            self._knobs_device = {k: self._place_rows(jnp.asarray(v))
                                  for k, v in self._knobs.items()}
        knobs = self._knobs_device
        try:
            tok, chosen, carry = self._dispatch(
                "decode", self._step_fn,
                self.params, tokens_dev, active_dev,
                self.pool.carry, knobs, *self._adapter_args())
        except FaultError:
            # the dispatch failed BEFORE running: the pooled carry was
            # never donated and stays valid. Everything already in the
            # window was dispatched BEFORE the fault and is healthy —
            # flush it through the delayed consumer (its tokens are
            # real), THEN evict + replay whatever rows remain (no gap
            # sample for the failed dispatch: nothing dispatched, and
            # the evicted batch anchors no future gap)
            self._drain_window(emitted)
            self._recover_step(self.scheduler.running, "fail")
            self._last_decode_end = None
            return emitted
        self.pool.carry = carry
        # the (N, V) distribution never crosses to host — sampling is
        # fused into the step; only token ids + chosen log-probs will,
        # through ONE batched fence readback at this entry's DELAYED
        # consumption (_consume_window — THE declared delayed-consumer
        # site, serving/fences.py). t0 rides the entry so the
        # watchdog's elapsed covers the device work, not the launch
        self._window.append(_InFlight(tok, chosen, active, active_dev,
                                      rows, t0, n_sampled, had_running))
        # delayed consumer: fence the oldest entry once the window
        # exceeds its DECLARED depth knob (fences.WINDOW_KNOBS —
        # ASY308 rejects any other bound). dispatch_ahead=0 consumes
        # the entry just appended: dispatch-then-fence within one
        # step, byte-for-byte the pre-window engine
        while len(self._window) > self.dispatch_ahead:
            if not self._consume_window(emitted):
                break
        return emitted

    def drain(self) -> Dict[int, np.ndarray]:
        """Step until every submitted request has finished; returns
        ``{req_id: generated 1-based ids}`` for all RETAINED finished
        requests (all of them unless ``keep_finished``/``pop_result``
        evicted some)."""
        while not self.scheduler.idle():
            self.step()
        # the last consume can finish every row while NEWER dispatches
        # are still in flight (their readbacks belong to finished rows
        # — pure overshoot): flush them so no device handle outlives
        # the drain. The consumer's row filter discards every token.
        self.flush_window()
        return {rid: np.asarray(r.output, np.int32)
                for rid, r in self._finished.items()
                if r.state == FINISHED}

    # -- introspection -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return self.scheduler.queue_depth

    @property
    def active(self) -> int:
        return self.scheduler.active

    def idle(self) -> bool:
        return self.scheduler.idle()
