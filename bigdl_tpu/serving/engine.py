"""Continuous-batching serving engine for :func:`TransformerLM`.

``generate()`` runs one request per call with a private KV carry and
pays the full weight-read bandwidth per token for a single row.
:class:`ServingEngine` instead serves MANY independent requests from one
pooled cache (:class:`bigdl_tpu.serving.kv_pool.KVPool`) stepped by ONE
compiled per-row-position decode program
(:func:`bigdl_tpu.models.transformer.make_batch_decode_step`):

* requests are ``submit()``-ed at any time and queue FIFO;
* before every decode step the scheduler admits waiting requests into
  free slots (continuous batching: admission happens MID-FLIGHT,
  between decode steps of the requests already running). The DEFAULT
  admission path (``admission="batched"``) groups the admitted prompts
  into power-of-two length buckets and ingests each bucket in ONE
  masked multi-row :func:`make_batch_prefill_step` call, row-scattering
  every result into the pooled cache — ragged prompt lengths share a
  BOUNDED set of compiled prefill programs instead of compiling per
  novel length mid-admission (see ``serving/admission.py``).
  ``admission="per_request"`` keeps PR 1's one-at-a-time B=1
  :func:`make_prefill_step` path (the parity baseline);
* an optional :class:`bigdl_tpu.serving.prefix_cache.PrefixCache`
  (``prefix_cache=True`` or an instance) reuses prefilled K/V across
  requests sharing a token prefix — a full hit clones cached state
  straight into the pool, a partial hit prefills only the suffix;
* every ``step()`` decodes one token for ALL active rows at once —
  decode is weight-read-bound, so a batched step costs roughly what a
  single-row step costs and aggregate tokens/sec scales with occupancy
  (measured in benchmarks/serving_bench.py);
* rows are evicted at EOS or ``max_new_tokens`` and their slot returns
  to the free list for the next admission.

Decoding is GREEDY (argmax), and the pooled step computes the same math
as the single-request step, so engine outputs match per-request
``generate(..., temperature=0)`` token for token — pinned by
tests/test_serving.py for plain and bf16-serving params. (The two steps
are numerically equal only to float round-off — different batch shapes
can reorder XLA reductions — so a checkpoint whose top-2 logprobs tie
within ~1e-5 could in principle break a tie differently; the parity
tests pin the realistic case, not a bitwise guarantee.)

The jitted step/prefill functions come from the per-(model, dtype) step
cache (``get_batch_decode_step`` / ``get_prefill_step``), so several
engines over one model — or an engine plus ad-hoc ``generate()`` calls —
share compilations; prompt-length buckets re-trace once each inside the
cached prefill's own jit cache.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import numpy as np

from bigdl_tpu.serving.kv_pool import KVPool
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.scheduler import FINISHED, Request, Scheduler


class ServingEngine:
    """Continuous-batching greedy decoder over a pooled KV cache.

    ``n_slots`` is the fixed decode capacity (concurrent requests);
    ``compute_dtype`` is the serving precision knob (weights + KV cache,
    e.g. ``jnp.bfloat16`` — scores and log-softmax stay fp32);
    ``policy`` is the admission policy (``"prefill_priority"`` = admit
    into freed rows before every step, ``"fifo"`` = refill only after
    the running batch drains — see ``serving.scheduler``);
    ``admission`` picks the prompt-ingestion pipeline: ``"batched"``
    (default — bucketed multi-row masked prefill, bounded compile set)
    or ``"per_request"`` (PR 1's B=1-per-admission baseline);
    ``prefix_cache`` enables shared-prefix K/V reuse under batched
    admission: ``True`` for a default-capacity
    :class:`~bigdl_tpu.serving.prefix_cache.PrefixCache`, or pass a
    configured instance (``None`` = off);
    ``keep_finished`` bounds the finished-request ledger: only the N
    most recently finished requests stay retrievable via ``result()``
    (older ones are evicted oldest-first), so a long-lived engine under
    heavy traffic doesn't grow without bound. ``None`` keeps everything
    (then ``pop_result()`` is the caller's eviction lever).
    """

    def __init__(self, model, n_slots: int = 8, compute_dtype=None,
                 policy: str = "prefill_priority",
                 metrics: Optional[ServingMetrics] = None,
                 admission: str = "batched",
                 prefix_cache=None,
                 keep_finished: Optional[int] = None) -> None:
        import jax

        from bigdl_tpu.models.transformer import (
            get_batch_decode_step, get_batch_prefill_step, get_prefill_step,
            serving_params,
        )
        from bigdl_tpu.serving.admission import AdmissionController
        from bigdl_tpu.serving.prefix_cache import PrefixCache

        if admission not in ("batched", "per_request"):
            raise ValueError(
                f"unknown admission mode {admission!r} "
                "(one of 'batched', 'per_request')")
        if keep_finished is not None and keep_finished < 0:
            raise ValueError(
                f"keep_finished must be >= 0 or None, got {keep_finished}")
        model._ensure_params()
        self.model = model
        self.max_len = model.modules[1].max_len
        self.compute_dtype = compute_dtype
        # weights as resident device buffers in the serving dtype
        # (runtime arguments — never baked into the compiled programs)
        self.params = jax.device_put(serving_params(model, compute_dtype))
        self._step_fn, pool_init = get_batch_decode_step(model, compute_dtype)
        self._pool_init = pool_init
        self.pool = KVPool(pool_init, n_slots)
        self.scheduler = Scheduler(policy)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.admission = admission
        self.keep_finished = keep_finished
        if admission == "batched":
            self._batch_prefill_fn = get_batch_prefill_step(model,
                                                            compute_dtype)
            # True -> default cache, False/None -> off, else an instance
            self.prefix_cache = (PrefixCache() if prefix_cache is True
                                 else (prefix_cache or None))
            self.admitter = AdmissionController(
                self, prefix_cache=self.prefix_cache)
        else:
            if prefix_cache:
                raise ValueError(
                    "prefix_cache requires admission='batched' (the "
                    "per-request prefill cannot continue from a cached "
                    "carry)")
            self.prefix_cache = None
            self.admitter = None
            self._prefill_fn = get_prefill_step(model, compute_dtype)
            # ONE fresh B=1 carry for prefill, built once and reused for
            # every admission (prefill returns a new carry; jax arrays
            # are immutable, so sharing the zero input is free — at 137M
            # scale a per-admission rebuild would be ~12 MB of pure
            # allocation churn). pool_init's carry layout is
            # make_decode_step's, so n_slots=1 IS the single-request
            # carry.
            self._zero_carry1 = pool_init(1)
        self._next_id = 0
        self._finished: Dict[int, Request] = {}

    # -- request surface ---------------------------------------------------

    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int = 32,
               eos_id: int = -1) -> int:
        """Queue one generation request (1-based prompt ids, like
        ``generate()``); returns its request id. Raises if the request
        could ever overflow the cache (same ``max_len`` guard as
        ``generate()``)."""
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("need a non-empty prompt")
        if len(prompt) - 1 + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the model's max_len "
                f"{self.max_len} — the cache position would silently "
                "clamp (same guard as generate())")
        rid = self._next_id
        self._next_id += 1
        self.scheduler.submit(Request(
            req_id=rid, prompt=prompt, max_new_tokens=int(max_new_tokens),
            eos_id=int(eos_id), submit_time=time.perf_counter()))
        self.metrics.on_submit()
        return rid

    def result(self, req_id: int) -> Optional[np.ndarray]:
        """Generated 1-based ids for a FINISHED request, else None
        (also None once evicted by ``keep_finished``/``pop_result``)."""
        req = self._finished.get(req_id)
        return None if req is None else np.asarray(req.output, np.int32)

    def pop_result(self, req_id: int) -> Optional[np.ndarray]:
        """Like :meth:`result` but RELEASES the request's ledger entry —
        the memory-bounding consumption pattern for long-lived engines
        (take each output exactly once; see ``keep_finished`` for the
        automatic alternative)."""
        req = self._finished.pop(req_id, None)
        return None if req is None else np.asarray(req.output, np.int32)

    def cancel(self, req_id: int) -> bool:
        """Cancel a WAITING request: it is dequeued, never occupies a
        slot, and lands in the finished ledger with state 'cancelled'
        and empty output. Returns False (no-op) for requests already
        running, finished, or unknown."""
        req = self.scheduler.cancel(req_id)
        if req is None:
            return False
        self.metrics.on_cancel()
        self._finished[req_id] = req
        self._evict_finished()
        return True

    def request(self, req_id: int) -> Optional[Request]:
        return self._finished.get(req_id)

    # -- the serving loop --------------------------------------------------

    def _evict_finished(self) -> None:
        # dict preserves insertion order = finish order → oldest-first
        if self.keep_finished is None:
            return
        while len(self._finished) > self.keep_finished:
            self._finished.pop(next(iter(self._finished)))

    def _admit(self) -> None:
        import jax.numpy as jnp

        n = self.scheduler.admissible(self.pool.free_slots)
        if not n:
            return
        if self.admitter is not None:
            # batched admission: bucketed multi-row masked prefill with
            # optional shared-prefix reuse (serving/admission.py)
            self.admitter.admit(n)
            return
        for _ in range(n):
            slot = self.pool.alloc()
            assert slot is not None          # admissible() checked
            req = self.scheduler.admit(slot)
            prompt0 = [t - 1 for t in req.prompt]     # 0-based
            if len(prompt0) > 1:
                t0 = time.perf_counter()
                ptoks = jnp.asarray([prompt0[:-1]], jnp.int32)
                _, pc = self._prefill_fn(self.params, ptoks,
                                         self._zero_carry1)
                self.pool.write_prefill(slot, pc, len(prompt0) - 1)
                self.metrics.add_phase("prefill",
                                       time.perf_counter() - t0)
            else:
                self.pool.set_pos(slot, 0)
            # the last prompt token is the first decode input — exactly
            # generate()'s convention, so outputs match token-for-token
            req.next_token = prompt0[-1]

    def step(self) -> Dict[int, int]:
        """Admit waiting requests, then decode ONE token for every active
        row. Returns ``{req_id: 1-based token}`` emitted this step (empty
        when the engine is idle)."""
        import jax.numpy as jnp

        self._admit()
        running = self.scheduler.running
        if not running:
            return {}
        N = self.pool.n_slots
        tokens = np.zeros((N,), np.int32)
        active = np.zeros((N,), bool)
        for slot, req in running.items():
            tokens[slot] = req.next_token
            active[slot] = True
        t0 = time.perf_counter()
        logp, carry = self._step_fn(self.params, jnp.asarray(tokens),
                                    jnp.asarray(active), self.pool.carry)
        self.pool.carry = carry
        # ONE host readback per step: the argmax reduces (N, V) → (N,)
        # on device before crossing
        nxt = np.asarray(jnp.argmax(logp, axis=-1))
        self.metrics.add_phase("decode_step", time.perf_counter() - t0)
        self.metrics.on_step(self.scheduler.queue_depth,
                             self.pool.occupancy(), int(active.sum()))

        emitted: Dict[int, int] = {}
        now = time.perf_counter()
        for slot, req in list(running.items()):
            tok0 = int(nxt[slot])
            tok1 = tok0 + 1                      # back to 1-based ids
            req.output.append(tok1)
            emitted[req.req_id] = tok1
            if req.first_token_time is None:
                req.first_token_time = now
                self.metrics.on_first_token(now - req.submit_time)
            done = ((req.eos_id > 0 and tok1 == req.eos_id)
                    or len(req.output) >= req.max_new_tokens)
            if done:
                freed = self.scheduler.finish(req, now)
                self.pool.free(freed)
                self._finished[req.req_id] = req
                self._evict_finished()
                self.metrics.on_finish(now - req.submit_time,
                                       len(req.output))
            else:
                req.next_token = tok0
        return emitted

    def drain(self) -> Dict[int, np.ndarray]:
        """Step until every submitted request has finished; returns
        ``{req_id: generated 1-based ids}`` for all RETAINED finished
        requests (all of them unless ``keep_finished``/``pop_result``
        evicted some)."""
        while not self.scheduler.idle():
            self.step()
        return {rid: np.asarray(r.output, np.int32)
                for rid, r in self._finished.items()
                if r.state == FINISHED}

    # -- introspection -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return self.scheduler.queue_depth

    @property
    def active(self) -> int:
        return self.scheduler.active

    def idle(self) -> bool:
        return self.scheduler.idle()
