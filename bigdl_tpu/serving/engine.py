"""Continuous-batching serving engine for :func:`TransformerLM`.

``generate()`` runs one request per call with a private KV carry and
pays the full weight-read bandwidth per token for a single row.
:class:`ServingEngine` instead serves MANY independent requests from one
pooled cache (:class:`bigdl_tpu.serving.kv_pool.KVPool`) stepped by ONE
compiled per-row-position decode program
(:func:`bigdl_tpu.models.transformer.make_batch_decode_step`):

* requests are ``submit()``-ed at any time and queue FIFO;
* before every decode step the scheduler admits waiting requests into
  free slots (continuous batching: admission happens MID-FLIGHT,
  between decode steps of the requests already running). The DEFAULT
  admission path (``admission="batched"``) groups the admitted prompts
  into power-of-two length buckets and ingests each bucket in ONE
  masked multi-row :func:`make_batch_prefill_step` call, row-scattering
  every result into the pooled cache — ragged prompt lengths share a
  BOUNDED set of compiled prefill programs instead of compiling per
  novel length mid-admission (see ``serving/admission.py``).
  ``admission="per_request"`` keeps PR 1's one-at-a-time B=1
  :func:`make_prefill_step` path (the parity baseline);
* an optional :class:`bigdl_tpu.serving.prefix_cache.PrefixCache`
  (``prefix_cache=True`` or an instance) reuses prefilled K/V across
  requests sharing a token prefix — a full hit clones cached state
  straight into the pool, a partial hit prefills only the suffix;
* every ``step()`` decodes one token for ALL active rows at once —
  decode is weight-read-bound, so a batched step costs roughly what a
  single-row step costs and aggregate tokens/sec scales with occupancy
  (measured in benchmarks/serving_bench.py);
* rows are evicted at EOS or ``max_new_tokens`` and their slot returns
  to the free list for the next admission.

Decoding is SAMPLED per row (``bigdl_tpu.serving.sampling``): every
request carries its own :class:`~bigdl_tpu.serving.sampling.
SamplingParams` (temperature, top-k/top-p, penalties, seed, stop sets)
and its own ``jax.random`` lane in the pooled carry, and ONE compiled
step samples all rows at once — the knobs are per-row runtime arrays,
so greedy and sampled rows mix freely in a batch and changing knobs
never recompiles. The default params are greedy (``temperature=0``
degrades exactly to argmax inside the same program), and the pooled
step computes the same math as the single-request step, so default
engine outputs match per-request ``generate(..., temperature=0)`` token
for token — pinned by tests/test_serving.py for plain and bf16-serving
params; a fixed-seed sampled request reproduces its stream across
batching, slot placement, and eviction/readmission (pinned by
tests/test_serving_sampling.py). (The pooled and single-request steps
are numerically equal only to float round-off — different batch shapes
can reorder XLA reductions — so a checkpoint whose top-2 logprobs tie
within ~1e-5 could in principle break a tie differently; the parity
tests pin the realistic case, not a bitwise guarantee.) Stop-SEQUENCE
matching runs on host against each row's token tail; stop TOKEN ids
(incl. the per-request ``eos_id``) evict the row the step they appear,
with ``min_tokens`` banning them on device until the floor is met.

The jitted step/prefill functions come from the per-(model, dtype) step
cache (``get_batch_decode_step`` / ``get_prefill_step``), so several
engines over one model — or an engine plus ad-hoc ``generate()`` calls —
share compilations; prompt-length buckets re-trace once each inside the
cached prefill's own jit cache.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import numpy as np

from bigdl_tpu.serving.kv_pool import KVPool
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.sampling import (
    SamplingParams, knob_row_values, make_knob_rows, match_stop_sequences,
)
from bigdl_tpu.serving.scheduler import FINISHED, Request, Scheduler


class ServingEngine:
    """Continuous-batching per-row-sampled decoder over a pooled KV cache.

    ``n_slots`` is the fixed decode capacity (concurrent requests);
    ``compute_dtype`` is the serving precision knob (weights + KV cache,
    e.g. ``jnp.bfloat16`` — scores and log-softmax stay fp32);
    ``policy`` is the admission policy (``"prefill_priority"`` = admit
    into freed rows before every step, ``"fifo"`` = refill only after
    the running batch drains — see ``serving.scheduler``);
    ``admission`` picks the prompt-ingestion pipeline: ``"batched"``
    (default — bucketed multi-row masked prefill, bounded compile set)
    or ``"per_request"`` (PR 1's B=1-per-admission baseline);
    ``prefix_cache`` enables shared-prefix K/V reuse under batched
    admission: ``True`` for a default-capacity
    :class:`~bigdl_tpu.serving.prefix_cache.PrefixCache`, or pass a
    configured instance (``None`` = off);
    ``keep_finished`` bounds the finished-request ledger: only the N
    most recently finished requests stay retrievable via ``result()``
    (older ones are evicted oldest-first), so a long-lived engine under
    heavy traffic doesn't grow without bound. ``None`` keeps everything
    (then ``pop_result()`` is the caller's eviction lever);
    ``seed`` is the engine's base RNG seed: requests whose
    ``SamplingParams.seed`` is None draw from a lane folded from this
    base and their request id (fresh per request); an explicit
    per-request seed pins the lane regardless of the engine seed;
    ``mesh``/``parallelism`` swap in the SHARDED serving plane
    (``serving/sharded.py``): pass a ``jax.sharding.Mesh`` with
    ``data``/``model`` axes, or a ``{"data": N, "model": M}`` dict to
    build one from the host's devices. Slot rows shard over ``data``
    (token-identical to the unsharded engine — same per-row math, SPMD-
    partitioned), attention heads + MLP hidden over ``model``
    (Megatron two-psums-per-block under ``compat.shard_map``; equal to
    round-off). Still ONE compiled decode program per engine;
    ``kv_dtype="int8"`` stores the pooled K/V caches as per-(slot,
    head)-scaled int8 — half the KV bytes per slot, so an HBM budget
    holds ~2x the concurrent slots — with dequantization fused into the
    attention read (the Pallas pooled decode kernel on TPU, its jnp
    reference on CPU; ``ops/decode_attention.py``). Greedy outputs are
    parity-pinned against the float-KV engine and quantization adds
    ZERO decode compiles (tests/test_serving_kv_quant.py); default
    (None) follows ``compute_dtype``;
    ``speculative`` turns on DRAFT-AND-VERIFY decoding
    (``serving/speculative.py``): pass a
    :class:`~bigdl_tpu.serving.speculative.SpeculativeConfig` (or a
    bare draft model) and every step becomes a super-step — a small
    draft proposes up to ``k`` tokens per row, ONE fixed-width batched
    verify program (structurally the masked multi-row prefill) scores
    them all, and each row advances by the confirmed count (1..k+1
    tokens per step). Greedy output stays token-identical to the plain
    engine, fixed-seed sampled streams replay exactly (verification
    draws ride the per-slot RNG lanes), per-row draft budgets are
    runtime data of the one program (``submit(..., draft_tokens=0)``
    rows run as plain decode), and the draft's KV carry rides the same
    pool slots (tests/test_serving_speculative.py).
    """

    def __init__(self, model, n_slots: int = 8, compute_dtype=None,
                 policy: str = "prefill_priority",
                 metrics: Optional[ServingMetrics] = None,
                 admission: str = "batched",
                 prefix_cache=None,
                 keep_finished: Optional[int] = None,
                 seed: int = 0,
                 mesh=None, parallelism=None,
                 kv_dtype: Optional[str] = None,
                 speculative=None) -> None:
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.models.transformer import (
            get_batch_decode_step, get_batch_prefill_step, get_prefill_step,
            serving_params,
        )
        from bigdl_tpu.serving.admission import AdmissionController
        from bigdl_tpu.serving.prefix_cache import PrefixCache

        if admission not in ("batched", "per_request"):
            raise ValueError(
                f"unknown admission mode {admission!r} "
                "(one of 'batched', 'per_request')")
        if keep_finished is not None and keep_finished < 0:
            raise ValueError(
                f"keep_finished must be >= 0 or None, got {keep_finished}")
        model._ensure_params()
        self.model = model
        self.max_len = model.modules[1].max_len
        self.compute_dtype = compute_dtype
        # KV storage format: None follows compute_dtype (the status quo);
        # "int8" switches the pooled cache to the quantized layout
        # (per-(slot, head)-scaled int8 — half the KV bytes, double the
        # slots at equal HBM; see docs/serving.md "Quantized KV cache").
        # Spelling out "fp32"/"bf16" is allowed but must AGREE with
        # compute_dtype — the float cache always stores the serving
        # dtype, and a silent disagreement would misreport capacity.
        # normalize the dtype spelling: compute_dtype may arrive as the
        # jnp type, a np.dtype, or a string ("bfloat16") — all serve
        # identically, so all must classify identically here. The name
        # must match KVPool's stored-dtype mapping for EVERY float
        # dtype (fp16 engines serve fine and their default must keep
        # constructing), not just the two canonical serving formats —
        # so uncanonical dtypes keep their numpy name ("float16").
        stored = jnp.zeros((), compute_dtype or jnp.float32).dtype.name
        float_kv = {"float32": "fp32", "bfloat16": "bf16"}.get(stored,
                                                               stored)
        if kv_dtype is None:
            kv_dtype = float_kv
        elif kv_dtype not in ("fp32", "bf16", "int8"):
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r} "
                "(one of 'fp32', 'bf16', 'int8')")
        if kv_dtype != "int8" and kv_dtype != float_kv:
            raise ValueError(
                f"kv_dtype={kv_dtype!r} conflicts with "
                f"compute_dtype={compute_dtype!r} (the float KV cache "
                f"stores the serving dtype, {float_kv!r} here) — pick "
                "kv_dtype='int8' or drop the knob")
        self.kv_dtype = kv_dtype
        kv_quant = kv_dtype == "int8"
        # the sharded serving plane (serving/sharded.py): a mesh or a
        # {"data": N, "model": M} parallelism dict swaps the pooled
        # tensors onto a device mesh — slot rows shard over "data"
        # (token-identical: pure SPMD partitioning of the same per-row
        # math), weights/KV-heads over "model" (Megatron layout under
        # compat.shard_map). None/None is the stock single-device plane.
        if mesh is not None or parallelism is not None:
            from bigdl_tpu.serving.sharded import ShardPlane

            self._plane = ShardPlane(mesh=mesh, parallelism=parallelism)
            self.mesh = self._plane.mesh
        else:
            self._plane = None
            self.mesh = None
        # weights as resident device buffers in the serving dtype
        # (runtime arguments — never baked into the compiled programs);
        # tensor-parallel planes pre-shard them over the model axis
        sp = serving_params(model, compute_dtype)
        self.params = (jax.device_put(sp) if self._plane is None
                       else self._plane.place_params(model, sp))
        # the SAMPLED pooled step is the only decode program: greedy
        # requests are temperature=0 rows of the same compiled step, so
        # greedy-only and mixed traffic share one program (pinned by the
        # compile-count guards in tests/test_serving_sampling.py and
        # tests/test_serving_sharded.py). A SPECULATIVE engine swaps in
        # the fixed-width batched VERIFY step instead (serving/
        # speculative.py) — still exactly one target-side program, with
        # per-row draft lengths as runtime data (length-1 rows ARE plain
        # decode), and a layout-identical pooled carry.
        tp = self._plane is not None and self._plane.tensor_parallel
        if speculative is None:
            self._spec = None
            self._step_fn, pool_init = get_batch_decode_step(
                model, compute_dtype, sampling=True,
                mesh=self.mesh if tp else None, kv_quant=kv_quant)
        else:
            from bigdl_tpu.serving.speculative import Speculator

            self._spec = Speculator(self, speculative,
                                    mesh=self.mesh if tp else None,
                                    kv_quant=kv_quant)
            self._step_fn = None
            pool_init = self._spec.pool_init
        self._pool_init = pool_init
        self.pool = (KVPool(pool_init, n_slots, kv_dtype=kv_dtype)
                     if self._plane is None
                     else self._plane.make_pool(model, pool_init, n_slots,
                                                kv_quant=kv_quant,
                                                kv_dtype=kv_dtype))
        if self._spec is not None:
            # the draft model's pooled carry rides the same slots
            self._spec.attach_pool(self.pool)
        self.scheduler = Scheduler(policy)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        if self._plane is not None:
            self.metrics.set_mesh_shape(self._plane.data_shards,
                                        self._plane.model_shards)
        # KV-format observability: bytes one slot owns + the derived
        # effective-capacity number (slots a GiB of HBM would hold)
        self.metrics.set_kv_format(kv_dtype, self.pool.kv_bytes_per_slot)
        self.admission = admission
        self.keep_finished = keep_finished
        self.seed = int(seed)
        # host-side per-slot knob rows (greedy no-op state) + which
        # slots have been configured for their current occupant
        self._knobs = make_knob_rows(n_slots)
        self._ban_base = np.zeros((n_slots,), bool)
        self._configured: set = set()
        # device-side knob cache: knobs only change at admission or a
        # min-tokens ban flip, so the steady-state decode loop reuses
        # the same device arrays instead of re-uploading every step
        self._knobs_device = None
        if admission == "batched":
            # the tensor-parallel prefill shares the mesh (and must name
            # the sampling carry leaves in its shard_map specs); data-
            # only planes keep the stock prefill — its output rows
            # reshard into the sharded pool through the scatter
            self._batch_prefill_fn = get_batch_prefill_step(
                model, compute_dtype, mesh=self.mesh if tp else None,
                carry_sampling=tp, kv_quant=kv_quant)
            # True -> default cache, False/None -> off, else an instance
            self.prefix_cache = (PrefixCache() if prefix_cache is True
                                 else (prefix_cache or None))
            self.admitter = AdmissionController(
                self, prefix_cache=self.prefix_cache)
        else:
            if prefix_cache:
                raise ValueError(
                    "prefix_cache requires admission='batched' (the "
                    "per-request prefill cannot continue from a cached "
                    "carry)")
            self.prefix_cache = None
            self.admitter = None
            self._prefill_fn = get_prefill_step(model, compute_dtype,
                                                kv_quant=kv_quant)
            # ONE fresh B=1 carry for prefill, built once and reused for
            # every admission (prefill returns a new carry; jax arrays
            # are immutable, so sharing the zero input is free — at 137M
            # scale a per-admission rebuild would be ~12 MB of pure
            # allocation churn). pool_init's carry layout is
            # make_decode_step's, so n_slots=1 IS the single-request
            # carry.
            self._zero_carry1 = pool_init(1)
        self._next_id = 0
        self._finished: Dict[int, Request] = {}

    # -- request surface ---------------------------------------------------

    def submit(self, prompt_ids: Sequence[int], max_new_tokens: int = 32,
               eos_id: int = -1, sampling: Optional[SamplingParams] = None,
               draft_tokens: Optional[int] = None) -> int:
        """Queue one generation request (1-based prompt ids, like
        ``generate()``); returns its request id. Raises if the request
        could ever overflow the cache (same ``max_len`` guard as
        ``generate()``).

        ``eos_id`` is the request's PRIVATE eos (1-based; -1 = none) —
        different requests in the same batch may stop on different
        tokens; it joins ``sampling.stop_token_ids`` in the min-tokens
        device ban. ``sampling`` carries the request's
        :class:`~bigdl_tpu.serving.sampling.SamplingParams` (None =
        greedy defaults, the pre-sampling engine behavior);
        ``sampling.max_tokens`` (when set) overrides
        ``max_new_tokens``; ``draft_tokens`` is the request's
        speculative-decoding budget HINT (None = the engine's configured
        draft count, 0 = plain decode for this request, n = at most n
        drafts per super-step, clamped to the engine's ``k``; ignored
        by non-speculative engines, so traces stay portable across
        engine configs)."""
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("need a non-empty prompt")
        if draft_tokens is not None and int(draft_tokens) < 0:
            raise ValueError(
                f"draft_tokens must be >= 0 or None, got {draft_tokens}")
        # SamplingParams validates on construction (frozen dataclass)
        sp = sampling if sampling is not None else SamplingParams()
        if sp.max_tokens is not None:
            max_new_tokens = sp.max_tokens
        if len(prompt) - 1 + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds the model's max_len "
                f"{self.max_len} — the cache position would silently "
                "clamp (same guard as generate())")
        rid = self._next_id
        self._next_id += 1
        self.scheduler.submit(Request(
            req_id=rid, prompt=prompt, max_new_tokens=int(max_new_tokens),
            eos_id=int(eos_id), sampling=sp,
            draft_tokens=None if draft_tokens is None else int(draft_tokens),
            submit_time=time.perf_counter()))
        self.metrics.on_submit()
        return rid

    def result(self, req_id: int) -> Optional[np.ndarray]:
        """Generated 1-based ids for a FINISHED request, else None
        (also None once evicted by ``keep_finished``/``pop_result``)."""
        req = self._finished.get(req_id)
        return None if req is None else np.asarray(req.output, np.int32)

    def pop_result(self, req_id: int) -> Optional[np.ndarray]:
        """Like :meth:`result` but RELEASES the request's ledger entry —
        the memory-bounding consumption pattern for long-lived engines
        (take each output exactly once; see ``keep_finished`` for the
        automatic alternative)."""
        req = self._finished.pop(req_id, None)
        return None if req is None else np.asarray(req.output, np.int32)

    def logprobs(self, req_id: int) -> Optional[np.ndarray]:
        """Chosen-token raw model log-probs for a FINISHED request (one
        per output token), else None — the logprobs twin of
        :meth:`result`."""
        req = self._finished.get(req_id)
        return None if req is None else np.asarray(req.logprobs, np.float32)

    def cancel(self, req_id: int) -> bool:
        """Cancel a WAITING request: it is dequeued, never occupies a
        slot, and lands in the finished ledger with state 'cancelled'
        and empty output. Returns False (no-op) for requests already
        running, finished, or unknown."""
        req = self.scheduler.cancel(req_id)
        if req is None:
            return False
        self.metrics.on_cancel()
        self._finished[req_id] = req
        self._evict_finished()
        return True

    def request(self, req_id: int) -> Optional[Request]:
        return self._finished.get(req_id)

    # -- the serving loop --------------------------------------------------

    def _evict_finished(self) -> None:
        # dict preserves insertion order = finish order → oldest-first
        if self.keep_finished is None:
            return
        while len(self._finished) > self.keep_finished:
            self._finished.pop(next(iter(self._finished)))

    def _place_rows(self, x):
        """Commit a per-slot array to the plane's mesh (identity on the
        single-device plane). Every slot-axis array the step consumes
        goes through here so its sharding matches the pooled carry —
        mismatched placements would recompile or silently gather."""
        return x if self._plane is None else self._plane.place_rows(x)

    def _admit(self) -> None:
        import jax.numpy as jnp

        n = self.scheduler.admissible(self.pool.free_slots)
        if not n:
            return
        if self.admitter is not None:
            # batched admission: bucketed multi-row masked prefill with
            # optional shared-prefix reuse (serving/admission.py)
            self.admitter.admit(n)
            self._note_shard_balance()
            return
        for _ in range(n):
            slot = self.pool.alloc()
            assert slot is not None          # admissible() checked
            req = self.scheduler.admit(slot)
            prompt0 = [t - 1 for t in req.prompt]     # 0-based
            if len(prompt0) > 1:
                t0 = time.perf_counter()
                ptoks = jnp.asarray([prompt0[:-1]], jnp.int32)
                _, pc = self._prefill_fn(self.params, ptoks,
                                         self._zero_carry1)
                self.pool.write_prefill(slot, pc, len(prompt0) - 1)
                self.metrics.add_phase("prefill",
                                       time.perf_counter() - t0)
            else:
                self.pool.set_pos(slot, 0)
            # the last prompt token is the first decode input — exactly
            # generate()'s convention, so outputs match token-for-token
            req.next_token = prompt0[-1]
        self._note_shard_balance()

    def _note_shard_balance(self) -> None:
        """Post-admission shard-balance sample (sharded pools only):
        per-shard occupancy extremes + the max−min admission imbalance
        the balanced allocator is supposed to keep ≤ 1."""
        if self.pool.n_shards > 1:
            self.metrics.on_shard_slots(self.pool.used_per_shard(),
                                        self.pool.rows_per_shard)

    def _lane_key(self, req: Request):
        """The request's RNG-lane key: an explicit ``SamplingParams.seed``
        pins the lane (``sampling.lane_key`` — the rule ``generate()``
        shares), else a fresh lane folded from the engine seed and the
        request id. Either way the lane is a function of the REQUEST,
        never the slot, so readmission into any slot replays the same
        stream."""
        import jax

        from bigdl_tpu.serving.sampling import lane_key

        sp = req.sampling
        if sp.seed is not None:
            return lane_key(sp.seed)
        return jax.random.fold_in(lane_key(self.seed), req.req_id)

    def _configure_slot(self, slot: int, req: Request) -> None:
        """Thread one admitted request's SamplingParams into its slot:
        knob rows on host, RNG lane + penalty state on device."""
        sp = req.sampling
        scal, ban_row = knob_row_values(sp, req.eos_id)
        for k, v in scal.items():
            self._knobs[k][slot] = v
        self._knobs["ban_ids"][slot] = ban_row
        self._ban_base[slot] = self._knobs["ban"][slot]
        self._knobs_device = None                # re-upload next step
        self.pool.write_sampling(slot, self._lane_key(req), req.prompt)
        if self._spec is not None:
            # the draft cache ingests the prompt alongside the target's
            # (every admission path configures through here)
            self._spec.prefill_draft(slot, req)
        self._configured.add(slot)

    def _finish_check(self, req: Request) -> Optional[str]:
        """Stop/length decision for the token JUST appended to
        ``req.output`` — THE one copy of the per-token finish rule
        (the decode loop and the speculative chunk emission both apply
        it, token by token, so multi-token super-steps stop exactly
        where the baseline would)."""
        sp = req.sampling
        n_out = len(req.output)
        tok1 = req.output[-1]
        if n_out >= sp.min_tokens:
            if req.eos_id > 0 and tok1 == req.eos_id:
                return "eos"
            if (tok1 in sp.stop_token_ids
                    or match_stop_sequences(req.output, sp.stop_sequences)):
                return "stop"
        if n_out >= req.max_new_tokens:
            return "length"
        return None

    def _finish_row(self, req: Request, reason: str, now: float) -> None:
        """Evict a finished request: free its slot, ledger it, account
        the latency/throughput metrics."""
        req.finish_reason = reason
        freed = self.scheduler.finish(req, now)
        self.pool.free(freed)
        self._configured.discard(freed)
        self._finished[req.req_id] = req
        self._evict_finished()
        self.metrics.on_finish(
            now - req.submit_time, len(req.output),
            mean_logprob=float(np.mean(req.logprobs)))

    def _maybe_flip_ban(self, slot: int, req: Request) -> None:
        """min-tokens ban lifts the step the floor is met — a runtime
        VALUE change, never a recompile."""
        if self._ban_base[slot]:
            ban = len(req.output) < req.sampling.min_tokens
            if ban != self._knobs["ban"][slot]:
                self._knobs["ban"][slot] = ban
                self._knobs_device = None

    def step(self) -> Dict[int, int]:
        """Admit waiting requests, then decode for every active row:
        ONE token per row on the plain engine, up to ``k + 1`` on a
        speculative engine (draft-and-verify super-step —
        ``serving/speculative.py``). Returns ``{req_id: 1-based token}``
        emitted this step (the LAST emitted token per request when a
        super-step lands several; empty when the engine is idle)."""
        import jax.numpy as jnp

        self._admit()
        running = self.scheduler.running
        if not running:
            return {}
        if self._spec is not None:
            return self._spec.step(running)
        N = self.pool.n_slots
        tokens = np.zeros((N,), np.int32)
        active = np.zeros((N,), bool)
        n_sampled = 0
        for slot, req in running.items():
            if slot not in self._configured:
                self._configure_slot(slot, req)
            tokens[slot] = req.next_token
            active[slot] = True
            n_sampled += not req.sampling.is_greedy
        t0 = time.perf_counter()
        if self._knobs_device is None:
            self._knobs_device = {k: self._place_rows(jnp.asarray(v))
                                  for k, v in self._knobs.items()}
        knobs = self._knobs_device
        tok, chosen, carry = self._step_fn(
            self.params, self._place_rows(jnp.asarray(tokens)),
            self._place_rows(jnp.asarray(active)),
            self.pool.carry, knobs)
        self.pool.carry = carry
        # the (N, V) distribution never crosses to host — sampling is
        # fused into the step; only token ids + chosen log-probs do
        nxt = np.asarray(tok)
        lps = np.asarray(chosen)
        self.metrics.add_phase("decode_step", time.perf_counter() - t0)
        self.metrics.on_step(self.scheduler.queue_depth,
                             self.pool.occupancy(), int(active.sum()))
        self.metrics.on_sample_rows(n_sampled, len(running) - n_sampled)

        emitted: Dict[int, int] = {}
        now = time.perf_counter()
        for slot, req in list(running.items()):
            tok0 = int(nxt[slot])
            tok1 = tok0 + 1                      # back to 1-based ids
            req.output.append(tok1)
            req.logprobs.append(float(lps[slot]))
            emitted[req.req_id] = tok1
            if req.first_token_time is None:
                req.first_token_time = now
                self.metrics.on_first_token(now - req.submit_time)
            reason = self._finish_check(req)
            if reason is not None:
                self._finish_row(req, reason, now)
            else:
                req.next_token = tok0
                self._maybe_flip_ban(slot, req)
        return emitted

    def drain(self) -> Dict[int, np.ndarray]:
        """Step until every submitted request has finished; returns
        ``{req_id: generated 1-based ids}`` for all RETAINED finished
        requests (all of them unless ``keep_finished``/``pop_result``
        evicted some)."""
        while not self.scheduler.idle():
            self.step()
        return {rid: np.asarray(r.output, np.int32)
                for rid, r in self._finished.items()
                if r.state == FINISHED}

    # -- introspection -----------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return self.scheduler.queue_depth

    @property
    def active(self) -> int:
        return self.scheduler.active

    def idle(self) -> bool:
        return self.scheduler.idle()
