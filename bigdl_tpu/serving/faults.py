"""Fault injection + watchdog config for the serving plane.

A serving engine that assumes every device dispatch succeeds is one
slow host, one NaN'd logit, or one wedged step away from dropping its
whole batch. This module is the RESILIENCE half of the serving plane's
operability story (docs/serving.md "Operating under faults and
overload"): a deterministic fault injector the engine's dispatch sites
consult, a virtual clock so stalls are SIMULATED (tier-1 runs no
sleeps), and the watchdog knobs that bound how long a step may take and
how many times one request may be retried before it is failed out.

Recovery leans on the property the serving plane already owns: the
request stream is LOSS-FREE under eviction + readmission (per-request
RNG lanes + prefill replay of ``prompt + emitted``), so the engine's
answer to ANY suspect step — a raised dispatch, garbage outputs, a
watchdog timeout — is uniform: discard the step's outputs, evict the
implicated rows, and let normal admission replay them byte-identically
(pinned by tests/test_serving_faults.py). The BigDL reference survives
executor loss the same way — recompute from lineage rather than
checkpointing per-task state (arXiv:1804.05839); here "lineage" is the
emitted token stream itself.

Injection is DETERMINISTIC BY SEED: every dispatch draws one uniform
from a private ``numpy`` Generator, so a (seed, trace) pair replays the
same fault schedule run after run — which is what lets the fault suite
pin byte-identity instead of eyeballing flakes.

    from bigdl_tpu.serving import FaultInjector, ServingEngine
    from bigdl_tpu.serving.faults import VirtualClock, WatchdogConfig

    clk = VirtualClock()
    eng = ServingEngine(
        lm, n_slots=4, clock=clk,
        watchdog=WatchdogConfig(step_timeout_s=5.0, max_retries=3),
        faults=FaultInjector(seed=1, p_fail=0.2, p_stall=0.1,
                             stall_s=30.0, clock=clk))
    ...                       # streams identical to the fault-free run
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


#: THE closed clock-site vocabulary (the FENCE_SITES pattern, for
#: time): the only units in the serving plane allowed to read the raw
#: wall clock. Everything else runs on the ONE injected engine clock
#: (``ServingEngine(clock=...)`` — a :class:`VirtualClock` in tests,
#: :func:`default_clock` in production), so every process in a pod and
#: every replay sees the same time source. The analyzer extracts this
#: frozenset (cross-module) and MH403 flags any raw
#: ``time.time``/``perf_counter``/``monotonic``/``sleep`` spelled in
#: the serving tree outside these units; a genuinely new raw site must
#: be added here FIRST — a reviewable one-line diff.
CLOCK_SITES = frozenset({
    "faults.default_clock",           # the production clock source
    "metrics.ServingMetrics.on_step",  # serve-duration anchor timestamps
})


class FaultError(RuntimeError):
    """An injected (or real, if callers raise it) dispatch failure.
    The engine's recovery path catches exactly this: the step's outputs
    are discarded and its rows are evicted and replayed."""

    def __init__(self, site: str, kind: str = "fail") -> None:
        super().__init__(f"injected {kind} at {site!r} dispatch")
        self.site = site
        self.kind = kind


class VirtualClock:
    """A manually-advanced clock the engine (and injector) can share.

    The stall fault and the deadline machinery both need TIME to move
    without the test suite sleeping: pass one instance as the engine's
    ``clock=`` and the injector's ``clock=`` and a "slow step" is just
    ``advance(stall_s)`` between dispatch and readback — the watchdog
    sees the elapsed time, the wall clock sees none of it."""

    def __init__(self, start: float = 0.0) -> None:
        self.t = float(start)

    def advance(self, seconds: float) -> None:
        if seconds < 0:
            raise ValueError(f"cannot rewind the clock ({seconds})")
        self.t += float(seconds)

    def __call__(self) -> float:
        return self.t


class SteppingClock(VirtualClock):
    """A :class:`VirtualClock` that advances itself ``tick_s`` on
    every READ — deterministic virtual time that actually PASSES as
    the engine runs, with no sleeping and no wall clock.

    The plain VirtualClock never moves unless the test advances it, so
    timed spans measured INSIDE a step (decode_step_s, decode gaps,
    TTFT) all come out zero and everything built on them — the
    feasibility estimate, deadline-aware preemption, the autopilot's
    windowed signals — degenerates. With a SteppingClock every clock
    read costs one tick, so a decode step's elapsed time is (reads
    between t0 and t1) x tick_s: fixed per code path, hence
    deterministic per trace. The autopilot tests and the bench's
    workload-zoo replay run on it — same seeded trace in, same
    goodput out, every run."""

    def __init__(self, tick_s: float = 0.001, start: float = 0.0) -> None:
        super().__init__(start)
        if tick_s <= 0:
            raise ValueError(f"tick_s must be positive, got {tick_s}")
        self.tick_s = float(tick_s)

    def __call__(self) -> float:
        self.advance(self.tick_s)
        return self.t


@dataclass(frozen=True)
class WatchdogConfig:
    """Step-health knobs for :class:`ServingEngine`.

    ``step_timeout_s`` — a decode/verify dispatch whose host-side
    elapsed time (on the ENGINE's clock) exceeds this is treated as
    failed even though it returned: its outputs are discarded and its
    rows evicted + replayed (None = no timeout check). The timeout
    arms only after the engine's first HEALTHY step — a cold engine's
    first dispatch carries the one-time XLA compile, and a stall
    accepted during that grace window is merely a slow correct step
    (latency, never correctness). ``max_retries``
    — per-REQUEST fault budget: a request evicted by recovery more than
    this many times finishes with ``finish_reason='error'`` instead of
    requeueing, so a persistent fault degrades to failed requests, not
    a wedged engine (None = retry forever; byte-identity still holds,
    liveness is the caller's problem)."""

    step_timeout_s: Optional[float] = None
    max_retries: Optional[int] = 3

    def __post_init__(self):
        if self.step_timeout_s is not None and self.step_timeout_s <= 0:
            raise ValueError(
                f"step_timeout_s must be positive, got {self.step_timeout_s}")
        if self.max_retries is not None and self.max_retries < 0:
            raise ValueError(
                f"max_retries must be >= 0, got {self.max_retries}")


#: Dispatch sites the engine routes through the injector. "decode" is
#: the pooled decode step, "verify"/"draft" the speculative plane's two
#: dispatches, "prefill" every admission-side prefill (B=1, bucketed,
#: and prefix-suffix alike), "transfer" the disaggregated plane's
#: handoff sends (serving/disagg.py).
SITES = ("decode", "verify", "draft", "prefill", "transfer")


class FaultInjector:
    """Deterministic per-dispatch fault source (module docstring).

    ``p_fail``/``p_garbage``/``p_stall`` apply to STEP sites (decode /
    verify / draft): raise before dispatching, corrupt the returned
    outputs (float leaves → NaN, int leaves → -1: the "device returned
    garbage logits" shape the engine's health check must catch), or
    advance the shared :class:`VirtualClock` by ``stall_s`` after the
    dispatch (a slow step the watchdog times out). ``p_admit_fail``
    applies to the "prefill" site (admission errors).
    ``p_transfer_stall`` applies to the "transfer" site (disaggregated
    handoff sends): the fabric HANGS — the shared clock advances by
    ``stall_s`` and the send raises WITHOUT delivering, the shape a
    caller abandoning a hung ``BlockStoreTransfer.send`` at its
    timeout observes (the sender requeues with backoff;
    ``serving/health.py``). At most one fault
    fires per dispatch (the probabilities stack); ``max_faults`` caps
    the total injected so a high-rate schedule still lets traffic
    through eventually. ``counts`` tallies injections by kind — tests
    assert faults actually fired instead of passing vacuously."""

    def __init__(self, seed: int = 0, p_fail: float = 0.0,
                 p_garbage: float = 0.0, p_stall: float = 0.0,
                 p_admit_fail: float = 0.0,
                 p_transfer_stall: float = 0.0, stall_s: float = 10.0,
                 clock: Optional[VirtualClock] = None,
                 max_faults: Optional[int] = None) -> None:
        import numpy as np

        for name, p in (("p_fail", p_fail), ("p_garbage", p_garbage),
                        ("p_stall", p_stall),
                        ("p_admit_fail", p_admit_fail),
                        ("p_transfer_stall", p_transfer_stall)):
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must lie in [0, 1], got {p}")
        if p_fail + p_garbage + p_stall > 1.0:
            raise ValueError("p_fail + p_garbage + p_stall must be <= 1")
        if (p_stall > 0.0 or p_transfer_stall > 0.0) and clock is None:
            raise ValueError(
                "p_stall/p_transfer_stall need a shared VirtualClock — "
                "stalls are simulated by advancing it, never by sleeping")
        self.p_fail = float(p_fail)
        self.p_garbage = float(p_garbage)
        self.p_stall = float(p_stall)
        self.p_admit_fail = float(p_admit_fail)
        self.p_transfer_stall = float(p_transfer_stall)
        self.stall_s = float(stall_s)
        self.clock = clock
        self.max_faults = max_faults
        self.counts: Dict[str, int] = {
            "fail": 0, "garbage": 0, "stall": 0, "admit_fail": 0,
            "transfer_stall": 0}
        # the sanctioned SEEDED source (MH404's contract): an explicit
        # per-injector Generator keyed by the constructor seed — the
        # fault schedule is a pure function of (seed, dispatch order),
        # never of ambient/global RNG state, so chaos runs replay
        # byte-identically across processes and reruns
        self._rng = np.random.default_rng(int(seed))

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def _armed(self) -> bool:
        return self.max_faults is None or self.total < self.max_faults

    def call(self, site: str, fn, *args):
        """Dispatch ``fn(*args)`` through the fault schedule. One
        uniform draw per call decides the outcome, so the schedule is a
        pure function of (seed, dispatch order)."""
        u = float(self._rng.random())
        if site == "prefill":
            if self._armed() and u < self.p_admit_fail:
                self.counts["admit_fail"] += 1
                raise FaultError(site, "admit_fail")
            return fn(*args)
        if site == "transfer":
            if self._armed() and u < self.p_transfer_stall:
                # the hung-fabric shape: time passes (the caller's send
                # timeout elapses on the shared clock), nothing is
                # delivered, and the abandoned send surfaces as a raise
                self.counts["transfer_stall"] += 1
                self.clock.advance(self.stall_s)
                raise FaultError(site, "transfer_stall")
            return fn(*args)
        if self._armed() and u < self.p_fail:
            self.counts["fail"] += 1
            raise FaultError(site, "fail")
        out = fn(*args)
        if self._armed() and u < self.p_fail + self.p_garbage:
            self.counts["garbage"] += 1
            return _corrupt(out)
        if self._armed() and u < self.p_fail + self.p_garbage + self.p_stall:
            self.counts["stall"] += 1
            self.clock.advance(self.stall_s)
        return out


def _corrupt(out: Tuple):
    """The "garbage device output" transform: every float array leaf of
    a dispatch's output tuple becomes all-NaN and every integer array
    all -1; dict leaves (the carry) pass through untouched — corrupting
    the carry would be undetectable by construction, and the engine
    evicts every implicated row anyway, so the carry's bytes die with
    the slots regardless."""
    import jax.numpy as jnp
    import numpy as np

    def bad(x):
        if isinstance(x, dict):
            return x
        dt = np.dtype(getattr(x, "dtype", np.float32))
        if dt.kind == "f":
            return jnp.full_like(x, jnp.nan)
        if dt.kind in "iu":
            return jnp.full_like(x, -1)
        return x

    if isinstance(out, tuple):
        return tuple(bad(x) for x in out)
    return bad(out)


def default_clock():
    """The engine's default time source (the real wall clock) — a
    declared :data:`CLOCK_SITES` unit: the ONE production read of the
    raw clock, behind which every serving timer/deadline/backoff
    decision runs (MH403 flags raw reads anywhere else)."""
    return time.perf_counter()
