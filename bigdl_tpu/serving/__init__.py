"""bigdl_tpu.serving — continuous-batching inference engine.

The serving layer between the model zoo and the parallel stack: many
independent generation requests share ONE pooled, slot-indexed KV cache
and ONE compiled per-row decode program, with FIFO admission into rows
freed mid-flight (continuous batching). Decoding is sampled PER ROW
(``sampling.py``): each request's ``SamplingParams`` (temperature,
top-k/top-p, penalties, seed, stop sets) ride as per-row runtime arrays
of the one compiled step — greedy and sampled requests mix freely with
zero recompiles, and per-row RNG lanes make a fixed seed bit-stable
across batching and slot readmission. Admission itself is batched and
shape-stable: ragged prompts prefill together through a bounded set of
power-of-two length buckets (``admission.py``), optionally reusing
shared-prefix K/V from a ref-counted radix cache (``prefix_cache.py``);
``admission="chunked"`` streams prompts in as bounded suffix-
continuation chunks interleaved with decode, so an arrival burst never
stalls in-flight rows for a whole admission wave (``chunked.py``).
The plane is OPERABLE under faults and overload (``scheduler.py`` +
``faults.py``): priority classes with per-request deadlines and
loss-free preemption (evicted rows resume byte-identically), bounded-
queue admission backpressure with shed/deadline-drop/degrade policies,
and a step watchdog + deterministic fault injector whose
retry-with-evict recovery replays failed, garbage, or stalled steps
without ever wedging the engine. Past one host loop, ``disagg.py``
splits the plane into a PREFILL POOL and DECODE POOLS with serialized
KV-row handoff between them (``KVPool.row_state``/``restore_row`` —
the same byte-exact payload the preemption stash speaks; in-process
queue or ``block_store`` transfer backends), token-identical to the
monolithic engine at zero extra compiles per pool — and ``health.py``
makes each POOL a failure domain: heartbeat/transfer-failure health
classification, decode-pool failover that reconstructs every stranded
row loss-free-or-replayed with token-identical streams, graceful
``drain_pool`` migration, backoff-hardened transfer retries, and an
occupancy autoscaler with hysteresis. The plane is MULTI-TENANT
(``lora.py`` + ``constrain.py``): a pooled per-row LoRA adapter bank
lets every request carry its own adapter id as runtime data of the one
compiled step (id 0 = the base model, mixed traffic recompiles
nothing), and per-row token-mask constrained decoding rides the same
knob arrays — both replay byte-identically through preemption,
handoff, and failover. And capacity scales past HBM (``kv_tier.py``):
a :class:`TieredKVStore` backs any engine with a budgeted host-RAM
spill tier — the BigDL paper's BlockManager storage level mirrored
below HBM — so cold KV rows spill as packed ``row_state`` bytes and
resume WITHOUT re-prefill, evicted warm prefixes demote/promote
through the same tier, and the preemption stash, disagg handoff
staging, and failover copies become one store with one byte budget.
See ``docs/serving.md``.

    from bigdl_tpu.serving import SamplingParams, ServingEngine

    eng = ServingEngine(lm, n_slots=8, compute_dtype=jnp.bfloat16,
                        prefix_cache=True)
    rid = eng.submit([3, 7, 2], max_new_tokens=32, eos_id=5,
                     sampling=SamplingParams(temperature=0.8,
                                             top_k=50, seed=42))
    outputs = eng.drain()            # {rid: 1-based token ids}
    print(eng.logprobs(rid))         # chosen-token model log-probs
    print(eng.metrics.summary())     # TTFT percentiles, tokens/sec, ...
"""

from bigdl_tpu.serving.admission import (
    AdmissionController, Degrade, bucket_len,
)
from bigdl_tpu.serving.autopilot import (
    ACTUATION_SITES, ActuatorBus, Autopilot, AutopilotConfig, Controller,
)
from bigdl_tpu.serving.chunked import ChunkedAdmissionController
from bigdl_tpu.serving.constrain import (
    ConstraintCursor, ConstraintError, TokenDFA, fixed_sequence,
    from_token_sets,
)
from bigdl_tpu.serving.disagg import (
    BlockStoreTransfer, DecodeWorker, DisaggregatedEngine,
    InProcessTransfer, KVTransfer, PrefillWorker, ROW_PAYLOAD_KEYS,
    pack_payload, payload_header, unpack_payload,
)
from bigdl_tpu.serving.health import (
    AutoscalerConfig, HealthConfig, OccupancyAutoscaler, PoolHealth,
    TransferRetryConfig,
)
from bigdl_tpu.serving.engine import ServingEngine
from bigdl_tpu.serving.faults import (
    FaultError, FaultInjector, SteppingClock, VirtualClock, WatchdogConfig,
)
from bigdl_tpu.serving.fences import FENCE_SITES, fence, fence_wait
from bigdl_tpu.serving.kv_pool import KVPool
from bigdl_tpu.serving.kv_tier import TieredKVStore
from bigdl_tpu.serving.lora import AdapterBank, AdapterSpec
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.prefix_cache import PrefixCache
from bigdl_tpu.serving.sampling import SamplingParams
from bigdl_tpu.serving.scheduler import Request, Scheduler
from bigdl_tpu.serving.sharded import (
    ShardedEngine, ShardedKVPool, emulate_cpu_devices, make_mesh,
)
from bigdl_tpu.serving.speculative import SpeculativeConfig

__all__ = ["ServingEngine", "KVPool", "ServingMetrics", "Request",
           "Scheduler", "AdmissionController",
           "ChunkedAdmissionController", "PrefixCache",
           "SamplingParams", "SpeculativeConfig", "bucket_len",
           "ShardedEngine", "ShardedKVPool", "make_mesh",
           "emulate_cpu_devices", "Degrade", "FaultError",
           "FaultInjector", "VirtualClock", "WatchdogConfig",
           "FENCE_SITES", "fence", "fence_wait",
           "DisaggregatedEngine", "PrefillWorker", "DecodeWorker",
           "KVTransfer", "InProcessTransfer", "BlockStoreTransfer",
           "ROW_PAYLOAD_KEYS", "pack_payload", "payload_header",
           "unpack_payload", "HealthConfig", "PoolHealth",
           "TransferRetryConfig", "AutoscalerConfig",
           "OccupancyAutoscaler", "AdapterBank", "AdapterSpec",
           "TokenDFA", "ConstraintCursor", "ConstraintError",
           "fixed_sequence", "from_token_sets", "TieredKVStore",
           "ACTUATION_SITES", "ActuatorBus", "Autopilot",
           "AutopilotConfig", "Controller", "SteppingClock"]
