"""Pool-level health, transfer retry, and occupancy autoscaling for the
disaggregated serving plane (``serving/disagg.py``).

PR 13 split serving into a prefill pool and decode pools, but fault
tolerance stopped at the ROW: the step watchdog evicts-and-replays a
faulted dispatch, yet a dead POOL (process crash, hung transfer fabric,
persistent device failure) strands every in-flight row it owns. A fleet
serving millions of users loses whole hosts, not single steps — this
module gives the plane a pool-level failure domain:

* :class:`PoolHealth` — the per-pool liveness model. Each decode worker
  stamps a heartbeat after every completed super-step (on the ENGINE's
  clock, so a :class:`~bigdl_tpu.serving.faults.VirtualClock` lets the
  whole state machine run in tests without one sleep), and every
  transfer send to the pool records success or failure. The front end
  classifies from those two signals: missed beats or consecutive
  transfer failures move a pool HEALTHY → SUSPECT → DEAD
  (:class:`HealthConfig` holds the thresholds). SUSPECT pools stop
  receiving NEW handoffs but keep serving their rows; a DEAD pool
  triggers failover (``DisaggregatedEngine._failover_pool``) — every
  row it owned is reconstructed on a surviving pool, loss-free where a
  current handoff stash exists, else by byte-identical prefill replay
  of ``prompt + emitted`` (the PR 8 recovery contract lifted from row
  to pool).
* :class:`TransferRetryConfig` — send-side hardening. A failed handoff
  used to retry IMMEDIATELY (the next pump); now each request backs
  off exponentially (``delay(n)`` doubles per attempt up to a cap,
  measured on the engine clock) and a send whose elapsed time exceeds
  ``send_timeout_s`` is treated as FAILED-UNCONFIRMED: requeued for
  resend, with the receiver deduplicating by request id so a
  late-but-delivered payload can never admit twice. The fault
  injector's ``transfer_stall`` mode (``serving/faults.py``) simulates
  the hung fabric this bounds. Retries stay bounded by the watchdog's
  ``max_retries`` budget — a persistently failing fabric fails the
  request with ``finish_reason='error'``, never wedges ``drain()``.
* :class:`OccupancyAutoscaler` — the control loop over the plane's
  existing ``prefill_occupancy``/``decode_occupancy`` signals (the
  pool-sizing remainder ROADMAP recorded at PR 13). It drains-and-
  retires cold decode pools and activates standby pools under
  sustained pressure, with HYSTERESIS so it never flaps: an action
  needs the signal past a threshold for ``sustain`` CONSECUTIVE
  samples, the up/down thresholds are separated by a dead band, and
  any action opens a ``cooldown``-step window in which no further
  action fires. Reversing a decision therefore takes a genuine
  occupancy swing across the whole band, sustained, outside cooldown —
  a boundary-riding signal can oscillate forever without triggering
  anything (``docs/serving.md`` "Pool failover and autoscaling" has
  the math).

Everything here is host-side bookkeeping over plain floats/ints — no
jax, no device traffic, no compiled programs. Deliberately: pool
lifecycle decisions must keep working exactly when devices are failing.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from bigdl_tpu.serving.autopilot import Controller

#: The closed pool-health vocabulary (the FINISH_REASONS pattern):
#: HEALTHY pools receive new handoffs, SUSPECT pools keep their rows
#: but stop receiving new work, DEAD pools are failed over and never
#: touched again (their device state is untrusted by definition).
HEALTHY, SUSPECT, DEAD = "healthy", "suspect", "dead"

#: Pool lifecycle states the front end tracks per decode pool. ACTIVE
#: pools are routed to and stepped; STANDBY pools are built (weights
#: resident, step programs shared through the process-wide caches — so
#: activation is compile-free) but idle; DEAD pools were failed over.
#: ``drain_pool`` moves active → standby; the autoscaler moves both
#: directions.
POOL_ACTIVE, POOL_STANDBY, POOL_DEAD = "active", "standby", "dead"


@dataclass(frozen=True)
class HealthConfig:
    """Thresholds for :class:`PoolHealth`.

    ``suspect_after_s``/``dead_after_s`` — seconds of heartbeat SILENCE
    (on the engine clock) before a pool is classified SUSPECT / DEAD. A
    worker beats once per completed super-step, so silence means the
    pool is not making progress — hung, crashed, or partitioned.
    ``suspect_after_failures``/``dead_after_failures`` — CONSECUTIVE
    transfer-send failures to the pool before the same verdicts (a
    delivered send resets the run): the fabric-side death signal, which
    sees a pool the heartbeat path cannot even reach."""

    suspect_after_s: float = 3.0
    dead_after_s: float = 10.0
    suspect_after_failures: int = 2
    dead_after_failures: int = 5

    def __post_init__(self):
        if not 0 < self.suspect_after_s <= self.dead_after_s:
            raise ValueError(
                f"need 0 < suspect_after_s <= dead_after_s, got "
                f"{self.suspect_after_s}/{self.dead_after_s}")
        if not 0 < self.suspect_after_failures \
                <= self.dead_after_failures:
            raise ValueError(
                f"need 0 < suspect_after_failures <= "
                f"dead_after_failures, got "
                f"{self.suspect_after_failures}/"
                f"{self.dead_after_failures}")


class PoolHealth:
    """One pool's liveness record: last heartbeat + the consecutive
    transfer-failure run, classified against a :class:`HealthConfig`
    on demand. ``force_dead()`` is the operator/router short-circuit
    for a death known out-of-band (connection refused, process exit) —
    classification never resurrects a forced-dead pool."""

    def __init__(self, clock, config: Optional[HealthConfig] = None) -> None:
        self._clock = clock
        self.config = config if config is not None else HealthConfig()
        self._last_beat = float(clock())
        self._failures = 0
        self._forced_dead = False

    def beat(self) -> None:
        """Stamp a liveness beat (one per completed worker super-step)."""
        self._last_beat = float(self._clock())

    def on_transfer_failure(self) -> None:
        self._failures += 1

    def on_transfer_ok(self) -> None:
        self._failures = 0

    def force_dead(self) -> None:
        self._forced_dead = True

    def reset(self) -> None:
        """Fresh bill of health (pool activation from standby): the
        beat clock restarts NOW so a pool idle on the bench since
        construction is not born dead. Forced death is permanent."""
        if self._forced_dead:
            raise ValueError("a forced-dead pool cannot be reset")
        self._last_beat = float(self._clock())
        self._failures = 0

    @property
    def silent_s(self) -> float:
        """Seconds since the last beat, on the shared clock."""
        return float(self._clock()) - self._last_beat

    def state(self) -> str:
        """Classify: DEAD / SUSPECT / HEALTHY (module docstring)."""
        cfg = self.config
        if self._forced_dead or self.silent_s > cfg.dead_after_s \
                or self._failures >= cfg.dead_after_failures:
            return DEAD
        if self.silent_s > cfg.suspect_after_s \
                or self._failures >= cfg.suspect_after_failures:
            return SUSPECT
        return HEALTHY


@dataclass(frozen=True)
class TransferRetryConfig:
    """Send-side hardening knobs for the handoff path.

    ``send_timeout_s`` — a send whose elapsed time (engine clock)
    exceeds this is treated as FAILED even if it eventually returned:
    delivery is unconfirmed (the abandoned-hang shape), so the request
    requeues for resend and the RECEIVER deduplicates by request id
    (``DecodeWorker.ingest``) in case the slow send did land. None =
    no timeout verdict. ``backoff_base_s``/``backoff_cap_s`` — the
    per-request exponential backoff between retries: attempt ``n``
    waits ``min(cap, base * 2**(n-1))`` before the row re-enters the
    queue, so a down fabric is probed at a decaying rate instead of
    hammered every pump."""

    send_timeout_s: Optional[float] = None
    backoff_base_s: float = 0.02
    backoff_cap_s: float = 2.0

    def __post_init__(self):
        if self.send_timeout_s is not None and self.send_timeout_s <= 0:
            raise ValueError(
                f"send_timeout_s must be positive or None, got "
                f"{self.send_timeout_s}")
        if self.backoff_base_s < 0 or self.backoff_cap_s < 0:
            raise ValueError("backoff times must be >= 0")

    def delay(self, n_retries: int) -> float:
        """Backoff before retry ``n_retries`` (1-based)."""
        if n_retries <= 0:
            return 0.0
        return min(self.backoff_cap_s,
                   self.backoff_base_s * (2.0 ** (n_retries - 1)))


@dataclass(frozen=True)
class AutoscalerConfig:
    """Hysteresis knobs for :class:`OccupancyAutoscaler`.

    ``high_water``/``low_water`` — mean ACTIVE-decode-pool occupancy
    thresholds; the gap between them is the dead band (a signal inside
    it never triggers anything). ``sustain`` — consecutive samples the
    signal must sit past a threshold before the action fires (one
    sample per front-end step). ``cooldown`` — front-end steps after
    ANY action during which no further action may fire (counted in
    steps, not seconds, so a VirtualClock test is deterministic).
    ``min_pools`` — the floor scale-down never goes below."""

    high_water: float = 0.85
    low_water: float = 0.30
    sustain: int = 3
    cooldown: int = 8
    min_pools: int = 1

    def __post_init__(self):
        if not 0.0 <= self.low_water < self.high_water <= 1.0:
            raise ValueError(
                f"need 0 <= low_water < high_water <= 1, got "
                f"{self.low_water}/{self.high_water}")
        if self.sustain < 1:
            raise ValueError(f"sustain must be >= 1, got {self.sustain}")
        if self.cooldown < 0:
            raise ValueError(
                f"cooldown must be >= 0, got {self.cooldown}")
        if self.min_pools < 1:
            raise ValueError(
                f"min_pools must be >= 1, got {self.min_pools}")


class OccupancyAutoscaler(Controller):
    """The pool-count control loop (module docstring): one
    :meth:`observe` per front-end step returns ``"up"``, ``"down"``,
    or None; the engine executes (activate a standby pool / drain the
    least-loaded active pool). Pure host arithmetic — deterministic
    given the occupancy series, which is what lets the bench assert
    flap-freedom instead of eyeballing it.

    PR 19 generalized this class's dead-band/sustain/cooldown
    discipline into the autopilot's :class:`~bigdl_tpu.serving.
    autopilot.Controller` base (it debuted here in PR 14); the
    autoscaler is now that base plus the occupancy-specific sample
    shape — ``backlog`` vetoes the low side (a backlogged lull means
    admission is catching up, not that capacity is idle) — so every
    autopilot knob and the pool count share ONE flap-freedom
    argument. A :class:`~bigdl_tpu.serving.disagg.
    DisaggregatedEngine` built with ``autopilot=`` registers this
    controller on the bus, putting pool scale decisions in the same
    actuation log as every other knob."""

    def __init__(self, config: Optional[AutoscalerConfig] = None) -> None:
        self.config = config if config is not None else AutoscalerConfig()
        super().__init__(self.config.high_water, self.config.low_water,
                         sustain=self.config.sustain,
                         cooldown=self.config.cooldown)

    def observe(self, occupancy: float, backlog: int,
                can_up: bool, can_down: bool) -> Optional[str]:
        """One control sample: ``occupancy`` is the mean over ACTIVE
        decode pools, ``backlog`` the prefill pool's waiting depth
        (scale-down is refused while work is queued).
        ``can_up``/``can_down`` gate on what the engine can actually
        do (a standby pool exists / more than ``min_pools`` active)."""
        return Controller.observe(self, occupancy, can_up=can_up,
                                  can_down=can_down,
                                  hold_down=backlog > 0)
