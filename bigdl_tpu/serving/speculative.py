"""Speculative decoding: draft-and-verify under the one-program
discipline.

Decode emits one token per model invocation, so per-request latency is
bound by SEQUENTIAL target-model steps no matter how well the engine
batches across requests. Speculative decoding breaks that bound the way
this repo breaks every serving bound — by restructuring the driver loop
around what the hardware does well (the BigDL thesis, arXiv:1804.05839)
and hiding per-step host/launch latency behind larger device steps (the
MLPerf-TPU-pod playbook, arXiv:1909.09756):

* a small DRAFT model proposes ``k`` tokens per row each super-step
  (``k + 1`` chained invocations of the existing per-row batched decode
  step — cheap, the draft is small);
* the TARGET model scores all proposed positions in ONE batched verify
  step (:func:`bigdl_tpu.models.transformer.make_batch_verify_step` —
  structurally the masked multi-row prefill: per-row start offsets
  already express "continue this row's suffix", so the verify program
  is shape-stable);
* each row advances by however many draws the target confirms —
  between 1 (all drafts rejected; exactly the plain decode step) and
  ``k + 1`` (all accepted plus the bonus draw) tokens per super-step.

The serving invariants carry over wholesale:

* **one compiled program** — per-row draft length is runtime data of
  the fixed-width ``(n_slots, k + 1)`` verify program. Mixed
  speculative/normal traffic (per-request ``draft_tokens=0`` rows,
  budget-capped rows, min-tokens-banned rows) adds ZERO target-side
  compiles: the speculative engine runs one verify program where the
  baseline runs one decode program (pinned by
  tests/test_serving_speculative.py via tests/compile_guards.py);
* **greedy parity** — temperature-0 rows verify by argmax agreement,
  so greedy speculative output is token-identical to the baseline
  engine and ``generate()`` (test-pinned, like sampling's
  temperature=0 contract);
* **seed replay** — verification draws ride the per-slot RNG lanes
  from ``serving/sampling.py``: the verify step splits each row's lane
  once per chunk position IN ORDER and advances it by exactly the
  emitted count, so a fixed-seed sampled request produces the SAME
  stream as the non-speculative engine, across eviction/readmission,
  batching, and admission modes. The draft only decides how many of
  those draws land per step — never their values — which also means a
  WRONG or weak draft degrades throughput, not correctness. That
  draft-independence is exact on the int8 cache too: the verify
  step's chunk attention reads FLOAT chunk K/V with the grow-only
  scale merge + quantized scatter deferred until acceptance is known,
  merging over ACCEPTED columns only — a rejected draft can touch
  neither a row's (slot, head) scales nor its stored bytes (pinned by
  the garbage-draft parity tests in tests/test_serving_speculative.py
  and tests/test_serving_kv_quant.py).
  (Acceptance is sampled-token agreement, deliberately traded against
  Leviathan-style distribution-matching rejection sampling, whose
  draft-dependent randomness consumption cannot replay the baseline
  stream; see ``make_batch_verify_step``'s docstring.)

KV bookkeeping: the draft's pooled KV carry rides alongside the
target's in the one :class:`~bigdl_tpu.serving.kv_pool.KVPool`
(``attach_draft`` — same slot ids, same allocator, freed together).
Rejected drafts need no cache rewrite on EITHER side: both caches
wrote the whole chunk, and the accepted-prefix rollback is pointer
arithmetic — ``pos`` advances by the emitted count only, leaving
rejected positions as stale bytes behind the per-row causal mask (the
same masking that makes recycled slots safe). The draft loop runs
``k + 1`` iterations (not ``k``) so the k-th draft's K/V lands too and
a fully-accepted chunk leaves no hole in the draft cache.

    from bigdl_tpu.serving import ServingEngine, SpeculativeConfig

    eng = ServingEngine(lm, n_slots=8,
                        speculative=SpeculativeConfig(draft_lm, k=4))
    rid = eng.submit([3, 7, 2], max_new_tokens=64)
    eng.submit([9, 9], max_new_tokens=8, draft_tokens=0)  # normal row
    outs = eng.drain()
    eng.metrics.summary()["serving/accept_rate"]    # drafts confirmed
    eng.metrics.summary()["serving/tokens_per_step"]  # > 1 when drafts land
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from bigdl_tpu.serving.admission import bucket_len
from bigdl_tpu.serving.fences import fence, fence_wait


@dataclass(frozen=True)
class SpeculativeConfig:
    """Speculative-decoding knobs for :class:`ServingEngine`.

    ``draft`` is the proposer: a TransformerLM-shaped model over the
    SAME vocabulary as the target (its ids are fed to the target
    verbatim) with ``max_len`` at least the target's (its cache tracks
    the same positions). ``k`` is the drafts proposed per super-step —
    the verify chunk width is ``k + 1`` and tokens-per-step ranges over
    ``1..k+1``. Per-request ``submit(..., draft_tokens=)`` can lower
    (never raise) the budget per row at runtime."""

    draft: Any
    k: int = 4

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(
                f"k must be >= 1 (draft tokens per super-step), got "
                f"{self.k} — a k=0 engine is the plain ServingEngine")


class Speculator:
    """The engine's speculative plane: owns the draft model's serving
    state (params, decode/prefill steps, pooled carry attachment) and
    the draft→verify→emit super-step. Built by
    :class:`~bigdl_tpu.serving.engine.ServingEngine` when its
    ``speculative=`` knob is set; reads the engine's pool/scheduler/
    metrics/knobs the way :class:`AdmissionController` does."""

    def __init__(self, engine, config, mesh=None,
                 kv_quant: bool = False) -> None:
        import jax

        from bigdl_tpu.models.transformer import (
            get_batch_decode_step, get_batch_prefill_step,
            get_batch_verify_step, serving_params,
        )

        if not isinstance(config, SpeculativeConfig):
            # accept a bare draft model for the common case
            config = SpeculativeConfig(draft=config)
        self.engine = engine
        self.config = config
        self.k = int(config.k)
        self.width = self.k + 1
        draft = config.draft
        draft._ensure_params()
        tgt_vocab = engine.model.modules[0].n_index
        if draft.modules[0].n_index != tgt_vocab:
            raise ValueError(
                f"draft vocab {draft.modules[0].n_index} != target vocab "
                f"{tgt_vocab} — draft proposals are target token ids")
        self.draft_max_len = draft.modules[1].max_len
        if self.draft_max_len < engine.max_len:
            raise ValueError(
                f"draft max_len {self.draft_max_len} < target max_len "
                f"{engine.max_len} — the draft cache tracks the same "
                "positions as the target's")
        self.draft = draft
        dtype = engine.compute_dtype
        # ONE target-side program: the fixed-width verify step is the
        # speculative engine's decode step (a length-1 row IS plain
        # decode); its init_carry is the decode carry, so the pool is
        # layout-identical to a non-speculative engine's
        # the target scores each row under that row's ADAPTER (the
        # engine threads per-slot ids + the bank into the dispatch);
        # drafts are pinned to the null adapter — submit() rejects
        # adapted requests unless draft_tokens=0 — so the draft plane
        # below stays adapter-free by construction
        self.verify_fn, self.pool_init = get_batch_verify_step(
            engine.model, dtype, width=self.width, mesh=mesh,
            kv_quant=kv_quant, adapter=engine._adapter_spec)
        # draft plane: weights REPLICATED (a model small enough to
        # draft with is small enough to replicate — on data-sharded
        # meshes XLA partitions the per-row step over the carry's slot
        # sharding), plain float cache, greedy proposals
        self._draft_step_fn, self._draft_init = get_batch_decode_step(
            draft, dtype)
        self._draft_prefill_fn = get_batch_prefill_step(draft, dtype)
        self._draft_params = jax.device_put(serving_params(draft, dtype))
        # shared fresh B=1 carry for draft prefills (immutable, reused)
        self._zero_draft1 = self._draft_init(1)

    # -- pool wiring --------------------------------------------------------

    def attach_pool(self, pool) -> None:
        plane = self.engine._plane
        pool.attach_draft(
            self._draft_init,
            specs=None if plane is None
            else plane.draft_carry_specs(self.draft))

    # -- admission ----------------------------------------------------------

    def prefill_draft(self, slot: int, req) -> None:
        """Ingest an admitted request's fed stream (prompt + any tokens
        emitted before a preemption/fault eviction) into the DRAFT
        cache — called from the engine's slot configuration, so every
        admission path (batched, per_request, prefix-cache hits,
        loss-free readmission) feeds the draft the same way. Bucketed
        masked B=1 prefill: the compiled draft-prefill set stays
        bounded by the power-of-two buckets, no matter how many
        distinct prompt lengths traffic brings. (No draft-side prefix
        cache or preemption stash: draft prefill is cheap and a stale
        draft cache could only cost acceptance, never correctness —
        but the bookkeeping would be real.)"""
        import jax.numpy as jnp

        eng = self.engine
        prompt0 = [t - 1 for t in req.prompt] + \
                  [t - 1 for t in req.output]
        pf = prompt0[:-1]
        if not pf:
            eng.pool.set_draft_pos(slot, 0)
            return
        L = bucket_len(len(pf), self.draft_max_len)
        toks = np.zeros((1, L), np.int32)
        toks[0, :len(pf)] = pf
        # routed through the engine's fault hook like every other
        # serving dispatch (SRV201): an un-routed draft prefill would
        # silently escape fault injection and retry accounting — a
        # raised FaultError propagates to the caller (_configure_slot's
        # callers recover the row like any admission-side fault).
        # NO completion fence, no phase timer: the draft prefill
        # overlaps the decode step under async dispatch and the super-
        # step's verify fence absorbs its completion (the PR 12
        # worksheet's deletable entry — docs/async_readiness.md).
        _, dc = eng._dispatch(
            "prefill", self._draft_prefill_fn,
            self._draft_params, jnp.asarray(toks),
            np.asarray([len(pf)], np.int32), self._zero_draft1)
        eng.pool.write_draft_prefill(slot, dc, len(pf))

    # -- the super-step ------------------------------------------------------

    def _draft_budget(self, slot: int, req) -> int:
        """Row r's draft count this super-step — runtime data, never a
        recompile. Capped by the engine ``k``, the per-request
        ``draft_tokens`` hint, the remaining token budget (a chunk must
        not overshoot ``max_new_tokens`` — that would desync the RNG
        lane from the baseline stream), and forced to 0 while the row's
        min-tokens ban is up (the ban is per-STEP host state; a chunk
        must not cross its flip). Constrained rows
        (``serving/constrain.py``) are likewise forced to 0: the allow
        mask is a function of the emitted PREFIX, so every chunk
        position after the first would verify against a stale mask."""
        k = self.k if req.draft_tokens is None \
            else min(int(req.draft_tokens), self.k)
        # the autopilot's engine-wide ceiling (ActuatorBus.
        # set_draft_cap): when the windowed accept rate says drafts
        # are dying at verify, the cap cuts spend for EVERY row —
        # per-row hints still apply below it, and None means the
        # configured k. Runtime data, never a recompile.
        cap = getattr(self.engine, "draft_cap", None)
        if cap is not None:
            k = min(k, int(cap))
        if self.engine._knobs["ban"][slot]:
            k = 0
        if slot in self.engine._constraints:
            k = 0
        rem = req.max_new_tokens - len(req.output)
        return max(0, min(k, rem - 1))

    def _chunk_unhealthy(self, nxt, lps, nem, lengths, active):
        """Garbage verdict on a verify step's host-read outputs — the
        chunked twin of ``ServingEngine._step_unhealthy``: active rows
        must report an emit count in ``1..lengths[r]`` and finite
        log-probs / in-range tokens over their emitted columns. None =
        healthy."""
        if not active.any():
            return None
        a_nem = nem[active]
        if (a_nem < 1).any() or (a_nem > lengths[active]).any():
            return "garbage"
        emit = np.arange(nxt.shape[1])[None, :] < nem[:, None]
        emit &= active[:, None]
        if (not np.isfinite(lps[emit]).all() or (nxt[emit] < 0).any()
                or (nxt[emit] >= self.engine._vocab).any()):
            return "garbage"
        return None

    def step(self, running) -> Dict[int, int]:
        """One draft-and-verify super-step over every active row:
        propose (``k + 1`` draft-decode dispatches), verify (ONE target
        dispatch), roll the draft cache back to the accepted prefix,
        then account the emitted tokens host-side exactly like the
        baseline per-token loop (same finish rules, truncating a chunk
        at its first stop condition). Returns ``{req_id: last emitted
        1-based token}`` — multi-token emissions land in
        ``Request.output``; the dict mirrors the baseline ``step()``
        shape for callers that only poll liveness.

        Resilience: both dispatch sites route through the engine's
        fault hook (``draft``/``verify`` — serving/faults.py). A raised
        dispatch, garbage verify outputs (non-finite log-probs,
        out-of-range tokens or emit counts), or a super-step exceeding
        the watchdog budget discards the step and evicts every
        implicated row for loss-free replay — both pooled carries are
        first re-pointed at their latest VALID buffers (earlier
        dispatches in the step donated the old ones), then the rows'
        bytes die with their freed slots."""
        import jax.numpy as jnp

        from bigdl_tpu.serving.faults import FaultError

        eng = self.engine
        t_start = eng._clock()
        N = eng.pool.n_slots
        tokens = np.zeros((N,), np.int32)
        active = np.zeros((N,), bool)
        k_r = np.zeros((N,), np.int32)
        n_sampled = 0
        for slot, req in list(running.items()):
            if slot not in eng._configured:
                try:
                    eng._configure_slot(slot, req)
                except FaultError:
                    # the draft-prefill dispatch inside slot
                    # configuration faulted: evict exactly this row for
                    # loss-free replay, keep the rest of the super-step
                    eng._recover_admission([(slot, req)])
                    continue
            tokens[slot] = req.next_token
            active[slot] = True
            k_r[slot] = self._draft_budget(slot, req)
            n_sampled += not req.sampling.is_greedy
        if not active.any():
            return {}
        if eng._knobs_device is None:
            eng._knobs_device = {k: eng._place_rows(jnp.asarray(v))
                                 for k, v in eng._knobs.items()}
        knobs = eng._knobs_device

        # propose: kmax+1 chained draft steps, kmax = the step's LARGEST
        # per-row budget (host data — every dispatch reuses the one
        # compiled draft program; an all-normal/banned step pays one
        # dispatch, not k+1). Iteration j is active for row r while
        # j <= k_r[r], so short-budget rows mask out and row r's last
        # iteration writes its k_r-th draft's K/V — a fully-accepted
        # chunk leaves no hole. Chunk columns past kmax are zero pad
        # the fixed-width verify program never reads (lengths <= kmax+1)
        t0 = eng._clock()
        u = eng._place_rows(jnp.asarray(tokens))
        dcarry = eng.pool.draft_carry
        kmax = int(k_r[active].max()) if active.any() else 0
        drafts = []
        try:
            for j in range(kmax + 1):
                act_j = eng._place_rows(jnp.asarray(active & (k_r >= j)))
                logp_d, dcarry = eng._dispatch(
                    "draft", self._draft_step_fn,
                    self._draft_params, u, act_j, dcarry)
                u = jnp.argmax(logp_d, axis=-1).astype(jnp.int32)
                if j < self.k:
                    drafts.append(u)
        except FaultError:
            # earlier iterations donated the pooled draft carry; keep
            # the latest VALID buffers before evicting the rows
            eng.pool.draft_carry = dcarry
            eng._recover_step(running, "fail")
            return {}
        while len(drafts) < self.k:
            drafts.append(eng._place_rows(jnp.zeros((N,), jnp.int32)))
        # completion fence pinning the draft timer: u is the chain's
        # last output, so waiting on it waits on every draft dispatch —
        # no copy, and the drafts themselves STAY on device for the
        # verify step (the async-friendly half of the super-step)
        fence_wait("draft", u)
        eng.metrics.add_phase("draft", eng._clock() - t0)

        # verify: ONE fixed-width target dispatch for the whole fleet
        lengths = np.where(active, k_r + 1, 0).astype(np.int32)
        vtoks = eng._place_rows(jnp.concatenate(
            [jnp.asarray(tokens)[:, None]] + [d[:, None] for d in drafts],
            axis=1))
        t0 = eng._clock()
        try:
            vt, vlp, n_emit, carry = eng._dispatch(
                "verify", self.verify_fn,
                eng.params, vtoks, eng._place_rows(jnp.asarray(lengths)),
                eng.pool.carry, knobs, *eng._adapter_args())
        except FaultError:
            eng.pool.draft_carry = dcarry     # target carry never donated
            eng._recover_step(running, "fail")
            return {}
        eng.pool.carry = carry
        # ONE batched fence readback for the whole verify result —
        # tokens, log-probs, emit counts cross to host together
        # (serving/fences.py) instead of as three separate syncs. The
        # verify site stays an IMMEDIATE consumer (window depth
        # structurally 0 — fences.DELAYED_CONSUMER_SITES): next
        # super-step's draft budgets are a host decision made from
        # THIS readback, so there is nothing to dispatch ahead of it.
        # The t_f bracket is the fenced-wait sample — the blocked half
        # of the host_step split (metrics.DEVICE_PHASES)
        t_f = eng._clock()
        nxt, lps, nem = fence("verify", vt, vlp, n_emit)
        now_f = eng._clock()
        eng.metrics.add_phase("fence_wait", now_f - t_f)
        eng.metrics.add_phase("decode_step", now_f - t0)
        bad = self._chunk_unhealthy(nxt, lps, nem, lengths, active)
        if bad is None and eng._timed_out(eng._clock() - t_start):
            bad = "timeout"
        if bad is not None:
            # outputs discarded; both carries keep valid buffers and
            # every implicated row is evicted, so the suspect bytes die
            # with the freed slots
            eng.pool.draft_carry = dcarry
            eng._recover_step(running, bad)
            return {}
        eng._warm = True                   # arms the watchdog timeout

        # draft rollback: the loop advanced active rows by k_r+1; keep
        # the accepted prefix + the emission that will be re-fed (pure
        # pointer arithmetic — stale chunk bytes sit behind the mask)
        act_dev = eng._place_rows(jnp.asarray(active))
        dcarry = dict(dcarry)
        dcarry["pos"] = jnp.where(
            act_dev,
            dcarry["pos"] - (eng._place_rows(jnp.asarray(k_r)) + 1)
            + n_emit,
            dcarry["pos"])
        eng.pool.draft_carry = dcarry

        eng.metrics.on_step(eng.scheduler.queue_depth,
                            eng.pool.occupancy(), int(active.sum()))
        eng.metrics.on_sample_rows(n_sampled, len(running) - n_sampled)

        # emission: the baseline per-token accounting, applied to each
        # chunk token IN ORDER and truncated at the first stop — a stop
        # mid-chunk discards the tail exactly as the baseline engine
        # would never have sampled it (the row is evicted; its
        # over-advanced lane/counts die with the slot)
        emitted: Dict[int, int] = {}
        n_landed = 0          # chunk tokens that actually reached outputs
        now = eng._clock()
        for slot, req in list(running.items()):
            m = int(nem[slot])
            reason = None
            for j in range(m):
                # the engine's shared per-token accounting
                # (_account_token): append + emitted + first-token
                # latency + finish verdict — one spelling for the
                # decode window's delayed consumer and this loop
                reason = eng._account_token(
                    slot, req, int(nxt[slot, j]),
                    float(lps[slot, j]), now, emitted)
                n_landed += 1
                if reason is not None:
                    break
            if reason is not None:
                eng._finish_row(req, reason, now)
            else:
                req.next_token = int(nxt[slot, m - 1])
                eng._maybe_flip_ban(slot, req)
                eng._advance_constraint(slot, req)
        # accounted AFTER truncation: accepted = landed minus the one
        # non-draft draw per row, so accept_rate/tokens_per_step report
        # what the engine actually emitted, not what the verify step
        # confirmed before a mid-chunk stop discarded the tail
        n_rows = int(active.sum())
        eng.metrics.on_spec_step(int(k_r[active].sum()),
                                 n_landed - n_rows, n_rows)
        return emitted
