"""Ref-counted radix-tree prefix cache over token prefixes.

Production prompt streams repeat: a shared system prompt, a few-shot
preamble, a conversation replayed with one more turn. Prefill cost is
linear in prompt length, so recomputing a shared prefix per request is
pure waste. This cache stores PREFILLED CARRIES (B=1
:func:`bigdl_tpu.models.transformer.make_batch_decode_step` rows, K/V
positions ``0..n-1`` + ``pos = n``) keyed by the 0-based token sequence
that produced them, in a path-compressed radix tree — so a lookup finds
the LONGEST cached prefix of a new prompt in one walk, and the admission
path (``serving/admission.py``) clones that carry (jax arrays are
immutable — a clone is free) and prefills only the suffix via
``make_batch_prefill_step``'s nonzero start offsets. Matches need not
land on a stored boundary: because K/V is causal, a cached LONGER
prompt serves any shorter shared prefix as a zero-copy TRUNCATED hit
(same buffers, ``pos`` clamped — see :meth:`PrefixCache._walk`), so one
cached "system prompt + question" entry accelerates every later prompt
sharing the system prompt.

Lifecycle / invariants (pinned by tests/test_serving_admission.py):

* ``acquire(tokens)`` returns ``(carry, matched_len, lease)`` for the
  longest cached prefix (``(None, 0, None)`` on a miss) and bumps the
  lease node's refcount — a LEASED entry is never evicted;
* ``release(lease)`` drops the refcount (never below zero — a double
  release raises);
* ``insert(tokens, carry)`` stores a carry, splitting radix edges as
  needed; re-inserting an existing prefix just refreshes its LRU slot;
* capacity is counted in ENTRIES (each entry is one full B=1 carry —
  ``2 * n_layers * max_len * heads * head_dim`` cache elements — so
  entry count, not token count, is what bounds memory). When over
  ``max_entries``, the least-recently-used carry with ``refs == 0`` is
  dropped and carry-less leaf chains are pruned; if every entry is
  leased the cache temporarily overflows rather than evicting live
  state.

The stored carries are shared REFERENCES: callers must treat them as
immutable (every consumer here does — prefill returns fresh carries and
the pool scatter never donates its prefill argument).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class _Node:
    """One radix-tree node: ``edge`` tokens hang below ``parent``;
    ``n_tokens`` is the full prefix length from the root through this
    node; ``carry`` (when present) is the prefilled B=1 carry for
    exactly that prefix."""

    __slots__ = ("edge", "parent", "children", "carry", "n_tokens",
                 "refs", "last_used")

    def __init__(self, edge: Tuple[int, ...], parent: Optional["_Node"],
                 n_tokens: int) -> None:
        self.edge = edge
        self.parent = parent
        self.children: Dict[int, "_Node"] = {}
        self.carry = None
        self.n_tokens = n_tokens
        self.refs = 0
        self.last_used = 0


class PrefixCache:
    """Radix-tree cache of prefilled prompt prefixes (module docstring)."""

    def __init__(self, max_entries: int = 16, tier=None) -> None:
        if max_entries <= 0:
            raise ValueError(
                f"max_entries must be positive, got {max_entries}")
        self.max_entries = int(max_entries)
        # optional host spill tier (serving/kv_tier.py): capacity
        # eviction DEMOTES refs==0 carries there instead of deleting,
        # and acquire() PROMOTES the best stored prefix back as an
        # ordinary hit — warm-prefix capacity then scales with the
        # tier's host_budget_bytes, not max_entries of HBM. The
        # engine wires its tier in at construction; settable because
        # the cache may be built before the tier.
        self.tier = tier
        self.root = _Node((), None, 0)
        # the tree is NAMESPACED by adapter id (multi-tenant LoRA —
        # serving/lora.py): K/V prefilled under one tenant's factors is
        # only reusable under the SAME factors, so each adapter id gets
        # its own radix root and lookups never cross tenants. Id 0 (the
        # null adapter) is `self.root` — base-model traffic keeps
        # today's shared namespace, hit rate, and entry layout.
        # Capacity, LRU, and leases stay GLOBAL across namespaces: one
        # budget of cached carries, whoever owns them.
        self._roots: Dict[int, _Node] = {0: self.root}
        self._carry_nodes: set = set()
        self._clock = 0
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.evictions = 0

    # -- tree walk ---------------------------------------------------------

    @staticmethod
    def _common(a: Sequence[int], b: Sequence[int]) -> int:
        n = min(len(a), len(b))
        for i in range(n):
            if a[i] != b[i]:
                return i
        return n

    @staticmethod
    def _subtree_carry(node: _Node) -> Optional[_Node]:
        """Any carry-bearing node in ``node``'s subtree (or None). Every
        carry below ``node`` shares ``node``'s full prefix, so any one
        of them can serve a truncated hit for it."""
        stack = [node]
        while stack:
            n = stack.pop()
            if n.carry is not None:
                return n
            stack.extend(n.children.values())
        return None

    def _walk(self, tokens: Tuple[int, ...], root: _Node):
        """Longest usable cached prefix of ``tokens``: ``(node,
        matched_len)``, where ``matched_len <= node.n_tokens`` — a
        strict inequality means a TRUNCATED hit: the donor carry covers
        a longer prompt, but causal K/V at positions ``0..matched-1``
        depend only on tokens ``0..matched-1``, so the same arrays with
        ``pos`` clamped to ``matched_len`` ARE the prefix's prefill
        state (zero-copy — the stale tail is overwritten/masked by the
        suffix prefill and decode exactly like recycled pool rows)."""
        node, i, best, best_len = root, 0, None, 0
        while i < len(tokens):
            child = node.children.get(tokens[i])
            if child is None:
                break
            m = self._common(child.edge, tokens[i:])
            if m == len(child.edge):
                node = child
                i += m
                if node.carry is not None:
                    best, best_len = node, i
                continue
            # ran out mid-edge after m shared tokens: every carry under
            # child still shares tokens[:i+m]
            if m > 0:
                deep = self._subtree_carry(child)
                if deep is not None:
                    best, best_len = deep, i + m
            break
        # the walk fully matched tokens[:i] but the deepest stored carry
        # is shallower (carry-less interior node — e.g. the shared
        # system prompt after an edge split): any carry under it serves
        # a truncated hit at depth i
        if i > best_len:
            deep = self._subtree_carry(node)
            if deep is not None:
                best, best_len = deep, i
        return best, best_len

    # -- lease surface -----------------------------------------------------

    def acquire(self, tokens: Sequence[int], adapter_id: int = 0):
        """Longest-cached-prefix lookup with a lease: returns ``(carry,
        matched_len, lease)``; the lease pins the entry against eviction
        until :meth:`release`. Miss → ``(None, 0, None)``. The carry may
        be a truncated view of a longer cached prefill (see
        :meth:`_walk`) — callers treat it exactly like an exact hit.
        ``adapter_id`` selects the tenant namespace (0 = null adapter =
        today's shared tree); a lookup only ever sees entries inserted
        under the same id."""
        self.lookups += 1
        tokens = tuple(int(t) for t in tokens)
        root = self._roots.get(int(adapter_id))
        if root is None:
            best, matched = None, 0
        else:
            best, matched = self._walk(tokens, root)
        if self.tier is not None:
            # tier promotion: a demoted prefix sharing MORE of this
            # prompt than HBM serves comes back as a real entry (the
            # fresh insert is eviction-immune for its pass), then the
            # re-walk serves it as an ordinary — possibly truncated —
            # hit. The tier counts the fetch; the hit counts below.
            promo = self.tier.promote_prefix(tokens, matched,
                                             adapter_id=int(adapter_id))
            if promo is not None:
                ptoks, carry = promo
                self.insert(ptoks, carry, adapter_id=int(adapter_id))
                best, matched = self._walk(
                    tokens, self._roots[int(adapter_id)])
        if best is None:
            return None, 0, None
        best.refs += 1
        self._touch(best)
        self.hits += 1
        self.hit_tokens += matched
        carry = best.carry
        if best.n_tokens > matched:
            import jax.numpy as jnp

            # zero-copy truncation: same K/V buffers, clamped pos
            carry = dict(carry)
            carry["pos"] = jnp.full_like(carry["pos"], matched)
        return carry, matched, best

    def release(self, lease) -> None:
        """Drop an :meth:`acquire` lease (no-op for a miss's None)."""
        if lease is None:
            return
        if lease.refs <= 0:
            raise ValueError("release without a matching acquire")
        lease.refs -= 1

    # -- insertion / eviction ----------------------------------------------

    def insert(self, tokens: Sequence[int], carry,
               adapter_id: int = 0) -> None:
        """Store ``carry`` as the prefill state for exactly ``tokens``
        (0-based ids, non-empty), splitting edges as needed, under the
        ``adapter_id`` namespace (0 = null adapter)."""
        tokens = tuple(int(t) for t in tokens)
        if not tokens:
            raise ValueError("cannot cache an empty prefix")
        adapter_id = int(adapter_id)
        root = self._roots.get(adapter_id)
        if root is None:
            root = self._roots[adapter_id] = _Node((), None, 0)
        node, i = root, 0
        while i < len(tokens):
            child = node.children.get(tokens[i])
            if child is None:
                child = _Node(tokens[i:], node, len(tokens))
                node.children[tokens[i]] = child
                node, i = child, len(tokens)
                continue
            m = self._common(child.edge, tokens[i:])
            if m == len(child.edge):
                node, i = child, i + m
                continue
            # split the edge at the divergence point
            mid = _Node(child.edge[:m], node, node.n_tokens + m)
            node.children[tokens[i]] = mid
            child.edge = child.edge[m:]
            child.parent = mid
            mid.children[child.edge[0]] = child
            node, i = mid, i + m
        assert node.n_tokens == len(tokens)
        if node.carry is None:
            self._carry_nodes.add(node)
        node.carry = carry
        self._touch(node)
        self._evict_over_capacity(protect=node)

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_used = self._clock

    def _evict_over_capacity(self, protect: Optional[_Node] = None) -> None:
        # the freshly inserted node is immune for THIS pass — evicting
        # it would throw away the prefill just paid for; if everything
        # else is leased the cache temporarily overflows instead
        while len(self._carry_nodes) > self.max_entries:
            victims = [n for n in self._carry_nodes
                       if n.refs == 0 and n is not protect]
            if not victims:
                return                 # everything leased: overflow
            victim = min(victims, key=lambda n: n.last_used)
            self._drop(victim)
            self.evictions += 1

    def _path_of(self, node: _Node):
        """The full token path from ``node``'s namespace root plus the
        adapter id owning that root ((tokens, None) for a detached
        node) — what a demotion is keyed by."""
        parts = []
        n = node
        while n.parent is not None:
            parts.append(n.edge)
            n = n.parent
        tokens = tuple(t for e in reversed(parts) for t in e)
        for aid, root in self._roots.items():
            if root is n:
                return tokens, aid
        return tokens, None

    def _drop(self, node: _Node) -> None:
        # only capacity eviction reaches here, and it only ever picks
        # refs==0 victims — so a demoted carry never has a live lease
        if self.tier is not None and node.carry is not None:
            tokens, aid = self._path_of(node)
            if tokens and aid is not None:
                self.tier.demote_prefix(tokens, node.carry,
                                        adapter_id=aid)
        node.carry = None
        self._carry_nodes.discard(node)
        # prune now-useless structure: carry-less leaves up the path
        while (node.parent is not None and node.carry is None
               and not node.children and node.refs == 0):
            parent = node.parent
            del parent.children[node.edge[0]]
            node = parent

    # -- introspection -----------------------------------------------------

    @property
    def entries(self) -> int:
        return len(self._carry_nodes)

    def cached_prefixes(self) -> List[int]:
        """Lengths of every cached prefix (sorted; test/debug surface)."""
        return sorted(n.n_tokens for n in self._carry_nodes)

    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    def stats(self) -> Dict[str, float]:
        return {"entries": float(self.entries),
                "lookups": float(self.lookups), "hits": float(self.hits),
                "hit_tokens": float(self.hit_tokens),
                "evictions": float(self.evictions),
                "hit_rate": self.hit_rate()}
