"""SLO autopilot: the closed control loop over the serving engine.

PRs 8-18 built every sensor (goodput, decode-gap p99, accept rate,
occupancy, queue depth — ``serving/metrics.py``) and every actuator
(loss-free preemption, ``Degrade``, ``chunk_budget``, the speculative
draft budget, the pool autoscaler) but ran them all on static knobs.
This module closes the loop the BigDL way (SoCC'19: cluster behavior
driven from runtime-observed state, not operator constants): a
host-only controller — no jax imports, like ``health.py`` — sampled
ONCE per engine super-step on the ENGINE clock, so a VirtualClock
test drives the whole loop without sleeping.

Three pieces:

* :class:`Controller` — the dead-band / sustain / cooldown hysteresis
  discipline ``OccupancyAutoscaler`` shipped in PR 14, generalized so
  every knob's control loop shares ONE flap-freedom argument (the
  autoscaler is now a subclass — ``health.py``). A signal must sit
  past a waterline for ``sustain`` CONSECUTIVE samples before an
  action fires, the dead band between the waterlines resets both
  runs, and ``cooldown`` samples must pass after ANY action before
  the next — so a boundary-riding signal can never flap an actuator.

* :class:`ActuatorBus` — the ONE declared write surface for engine
  knobs. Every mutation the autopilot can make (``chunk_budget``, the
  per-class ``Degrade`` apply/restore, the speculative draft cap, the
  pool scale decision log) goes through a bus method listed in
  ``ACTUATION_SITES`` below; the analyzer's SRV208 rule flags any
  knob mutation OUTSIDE this vocabulary (the FENCE_SITES/CLOCK_SITES
  closed-vocabulary pattern applied to control authority). Every
  actuation is host bookkeeping over per-row runtime data — the
  compiled-program set is untouched by construction, and
  test-pinned (tests/test_serving_autopilot.py).

* :class:`Autopilot` — the per-step sample() that reads WINDOWED
  metrics (``ServingMetrics.window`` — bounded recency, not whole-run
  percentiles) and drives the controllers, plus the deadline-aware
  preemption policy: with a measured per-token service-time estimate
  in hand, a short-deadline FEASIBLE waiter that would miss while a
  long-deadline row holds its slot evicts that row — preemption is
  loss-free (``ServingEngine._preempt_row``), so this reorders
  latency, never tokens. The same estimate folds into the scheduler's
  priority key as a least-laxity term (``Scheduler.service_estimate``).

Wiring: ``ServingEngine(..., autopilot=Autopilot())`` attaches the
bus and samples the loop at the end of every ``step()``;
``DisaggregatedEngine(..., autopilot=...)`` registers its
``OccupancyAutoscaler`` on the bus so pool scale-up/down rides the
same actuation log.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

#: The closed actuation vocabulary — the ONLY units allowed to mutate
#: engine/scheduler knobs (`chunk_budget`, degrade fields, draft
#: budgets) or drive the pool lifecycle (`_activate_pool`,
#: `drain_pool`) outside `__init__`. The analyzer's SRV208 rule flags
#: knob mutations in the serving plane outside these units; a
#: genuinely new actuator must be added here FIRST — a reviewable
#: one-line diff (the FENCE_SITES / CLOCK_SITES discipline applied to
#: control authority).
ACTUATION_SITES = frozenset({
    "autopilot.ActuatorBus.set_chunk_budget",   # chunked pump budget
    "autopilot.ActuatorBus.set_draft_cap",      # speculative k ceiling
    "autopilot.ActuatorBus.degrade_waiting",    # per-class Degrade apply
    "autopilot.ActuatorBus.restore_waiting",    # per-class Degrade revert
    "engine.ServingEngine._apply_degrade",      # the one degrade writer
    "engine.ServingEngine._restore_degrade",    # the one degrade restorer
    "disagg.DisaggregatedEngine._autoscale",    # pool scale execution
    "disagg.DisaggregatedEngine._failover_pool",  # death rescue: standby activation
})


class Controller:
    """Dead-band / sustain / cooldown hysteresis over ONE scalar signal.

    The exact discipline :class:`~bigdl_tpu.serving.health.
    OccupancyAutoscaler` shipped (and the failover bench asserts
    flap-free), factored out so every autopilot knob shares it: a
    sample at or past ``high_water`` extends the high run, at or below
    ``low_water`` the low run, anywhere in the dead band between
    resets BOTH (hysteresis demands consecutive evidence). An action
    fires only after ``sustain`` consecutive same-side samples AND
    ``cooldown`` samples since the last action — born ready, so the
    first action needs no cooldown to expire. Pure host arithmetic:
    deterministic given the signal series, which is what lets tests
    assert flap-freedom instead of eyeballing it.

    ``observe`` returns ``"up"`` (signal high), ``"down"`` (signal
    low), or None; what "up" MEANS (shrink a budget, add a pool) is
    the caller's mapping — the controller only owns the debounce.
    """

    def __init__(self, high_water: float, low_water: float,
                 sustain: int = 3, cooldown: int = 8) -> None:
        if not low_water < high_water:
            raise ValueError(
                f"need low_water < high_water, got "
                f"{low_water}/{high_water}")
        if sustain < 1:
            raise ValueError(f"sustain must be >= 1, got {sustain}")
        if cooldown < 0:
            raise ValueError(f"cooldown must be >= 0, got {cooldown}")
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.sustain = int(sustain)
        self.cooldown = int(cooldown)
        self._hi_run = 0
        self._lo_run = 0
        # born ready: the first action needs no cooldown to expire
        self._since_action = self.cooldown

    def observe(self, signal: float, can_up: bool = True,
                can_down: bool = True,
                hold_down: bool = False) -> Optional[str]:
        """One control sample. ``can_up``/``can_down`` gate on what
        the actuator can actually do (a budget already at its bound, no
        standby pool); ``hold_down`` vetoes the LOW side only (the
        autoscaler's backlogged-lull case: a low signal with queued
        work means admission is catching up, not that capacity is
        idle)."""
        if signal >= self.high_water:
            self._hi_run += 1
            self._lo_run = 0
        elif signal <= self.low_water and not hold_down:
            self._lo_run += 1
            self._hi_run = 0
        else:
            # the dead band (or a vetoed lull): both runs restart —
            # hysteresis demands CONSECUTIVE evidence
            self._hi_run = 0
            self._lo_run = 0
        self._since_action += 1
        if self._since_action <= self.cooldown:
            return None
        if self._hi_run >= self.sustain and can_up:
            self._act()
            return "up"
        if self._lo_run >= self.sustain and can_down:
            self._act()
            return "down"
        return None

    def _act(self) -> None:
        self._hi_run = 0
        self._lo_run = 0
        self._since_action = 0


@dataclass(frozen=True)
class AutopilotConfig:
    """Setpoints for the closed loop — each controller's waterlines
    plus the shared debounce.

    Chunk-budget loop: signal = windowed decode-gap p99 over
    ``gap_target_s`` (ratio > ``gap_high`` sustained → halve the
    pump's budget toward ``chunk_min``; ratio < ``gap_low`` with
    prompts still queued → double it toward ``chunk_max``). Degrade
    loop: signal = live queue depth (past ``queue_high`` sustained →
    apply each WAITING row's submitted ``Degrade`` knob for classes at
    or below ``degrade_below_priority``; below ``queue_low`` → restore
    the recorded originals for rows still waiting). Draft loop
    (speculative engines): signal = windowed accept rate (below
    ``accept_low`` sustained → drop the engine-wide draft cap one
    toward 0, drafting that misses wastes verify width; above
    ``accept_high`` → raise it one toward the engine's k). Deadline
    preemption: ``preempt_margin_s`` pads the would-miss test so a
    waiter on the knife edge does not trigger an eviction its own
    seating latency would waste."""

    gap_target_s: float = 0.05
    gap_high: float = 2.0
    gap_low: float = 0.5
    chunk_min: int = 8
    chunk_max: int = 256
    queue_high: float = 6.0
    queue_low: float = 1.0
    degrade_below_priority: int = 0
    accept_high: float = 0.7
    accept_low: float = 0.3
    sustain: int = 3
    cooldown: int = 8
    window: int = 64
    preempt: bool = True
    preempt_margin_s: float = 0.0

    def __post_init__(self):
        if self.gap_target_s <= 0:
            raise ValueError(
                f"gap_target_s must be positive, got {self.gap_target_s}")
        for lo, hi, what in ((self.gap_low, self.gap_high, "gap"),
                             (self.queue_low, self.queue_high, "queue"),
                             (self.accept_low, self.accept_high,
                              "accept")):
            if not lo < hi:
                raise ValueError(
                    f"need {what}_low < {what}_high, got {lo}/{hi}")
        if not 1 <= self.chunk_min <= self.chunk_max:
            raise ValueError(
                f"need 1 <= chunk_min <= chunk_max, got "
                f"{self.chunk_min}/{self.chunk_max}")
        if self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window}")
        if self.sustain < 1:
            raise ValueError(f"sustain must be >= 1, got {self.sustain}")
        if self.cooldown < 0:
            raise ValueError(
                f"cooldown must be >= 0, got {self.cooldown}")
        if self.preempt_margin_s < 0:
            raise ValueError(
                f"preempt_margin_s must be >= 0, got "
                f"{self.preempt_margin_s}")


class ActuatorBus:
    """The declared write surface for engine knobs (module docstring).

    Every method here is listed in ``ACTUATION_SITES`` — SRV208 flags
    knob mutations anywhere else in the serving plane. Each actuation
    is appended to ``self.log`` as ``(sample_no, actuator, value)``
    and counted on the metrics plane (``serving/actuations``), so
    tests assert flap-freedom from the log instead of instrumenting
    the engine."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self.log: List[Tuple[int, str, object]] = []
        self._sample_no = 0

    def _record(self, actuator: str, value) -> None:
        self.log.append((self._sample_no, actuator, value))
        self.engine.metrics.on_actuation(actuator)

    def set_chunk_budget(self, n: int) -> bool:
        """Set the chunked pump's per-step prompt-token budget — read
        fresh each ``pump()``, so the new value takes effect next
        step. No-op (False) on non-chunked engines or when already
        there."""
        adm = self.engine.admitter
        if adm is None or not hasattr(adm, "chunk_budget"):
            return False
        n = max(1, int(n))
        if adm.chunk_budget == n:
            return False
        adm.chunk_budget = n
        self._record("chunk_budget", n)
        return True

    def set_draft_cap(self, n: Optional[int]) -> bool:
        """Set the engine-wide ceiling on the speculative draft count
        (None = the configured k). Per-row ``draft_tokens`` hints
        still apply below it — the cap is runtime data the next
        super-step's ``_draft_budget`` reads, never a recompile."""
        n = None if n is None else max(0, int(n))
        if self.engine.draft_cap == n:
            return False
        self.engine.draft_cap = n
        self._record("draft_cap", n)
        return True

    def degrade_waiting(self, below_priority: int = 0) -> int:
        """Apply each WAITING request's submitted ``Degrade`` knob for
        priority classes AT OR BELOW ``below_priority`` (per-class
        pressure relief: the interactive tier keeps its budget while
        the batch tier sheds decode work). Originals are recorded on
        the request — :meth:`restore_waiting` reverts them while the
        row still waits. Returns how many rows were degraded."""
        eng = self.engine
        n = 0
        for req in eng.scheduler.iter_waiting():
            if req.priority <= below_priority and \
                    eng._apply_degrade(req):
                n += 1
        if n:
            self._record("degrade", n)
        return n

    def restore_waiting(self, below_priority: Optional[int] = None) -> int:
        """Revert :meth:`degrade_waiting` (and the static
        ``degrade_at`` path) for rows STILL WAITING: each degraded
        waiter gets its recorded original ``max_new_tokens`` /
        ``draft_tokens`` back. Rows already seated keep their caps —
        their budget was already priced into admission. Returns how
        many rows were restored."""
        eng = self.engine
        n = 0
        for req in eng.scheduler.iter_waiting():
            if below_priority is not None and \
                    req.priority > below_priority:
                continue
            if eng._restore_degrade(req):
                n += 1
        if n:
            self._record("restore", n)
        return n

    def note_pool_scale(self, direction: str) -> None:
        """Log a pool scale decision executed by the disaggregated
        front end (``DisaggregatedEngine._autoscale`` remains the
        executing site — it owns the pool tables; the bus owns the
        record, so pool actuations and knob actuations share one
        audit stream)."""
        self._record("pool_scale", direction)


class Autopilot:
    """The per-step control loop (module docstring): windowed sensors
    → hysteresis controllers → bus actuations, plus the deadline-aware
    preemption policy the engine's ``_admit`` consults. Attach via
    ``ServingEngine(..., autopilot=Autopilot())``; one instance per
    engine (the bus binds to it)."""

    def __init__(self, config: Optional[AutopilotConfig] = None) -> None:
        self.config = cfg = config if config is not None \
            else AutopilotConfig()
        self.bus: Optional[ActuatorBus] = None
        # one Controller per knob — the shared flap-freedom argument
        self._chunk = Controller(cfg.gap_high, cfg.gap_low,
                                 cfg.sustain, cfg.cooldown)
        self._load = Controller(cfg.queue_high, cfg.queue_low,
                                cfg.sustain, cfg.cooldown)
        # accept-rate loop: HIGH accept = raise the cap, LOW = cut it
        self._draft = Controller(cfg.accept_high, cfg.accept_low,
                                 cfg.sustain, cfg.cooldown)
        #: externally registered controllers (the disagg front end
        #: registers its OccupancyAutoscaler here) — name -> Controller
        self.controllers: Dict[str, Controller] = {
            "chunk_budget": self._chunk, "degrade": self._load,
            "draft_cap": self._draft}
        self._n_samples = 0

    # -- wiring --------------------------------------------------------------

    def attach(self, engine) -> "Autopilot":
        """Bind the bus to ``engine`` and fold the measured service-
        time estimate into its scheduler's priority key (least-laxity
        EDF: a waiter's urgency is its deadline minus the time its
        remaining budget needs — ``Scheduler.service_estimate``)."""
        if self.bus is not None and self.bus.engine is not engine:
            raise ValueError(
                "this Autopilot is already attached to another engine "
                "— one instance per engine (the bus binds to it)")
        self.bus = ActuatorBus(engine)
        engine.scheduler.service_estimate = \
            engine.metrics.service_time_estimate
        return self

    def register_controller(self, name: str,
                            controller: Controller) -> None:
        """Adopt an externally built controller (the disagg pool
        scaler) so its hysteresis state shows up in the one
        controller registry the tests and reports read."""
        self.controllers[name] = controller

    # -- the per-step sample -------------------------------------------------

    def sample(self, engine) -> None:
        """ONE control sample, called by the engine at the end of
        every ``step()`` — engine clock, engine metrics, host
        bookkeeping only. Idle steps sample too: pressure RELIEF
        (degrade restore, budget re-growth) mostly happens in lulls,
        exactly when no decode dispatch lands new metric samples —
        which is why the degrade loop reads the LIVE queue depth
        rather than the step-sampled series."""
        cfg = self.config
        bus = self.bus
        if bus is None or bus.engine is not engine:
            raise ValueError("autopilot not attached to this engine "
                             "(pass autopilot= at engine construction)")
        bus._sample_no = self._n_samples
        m = engine.metrics

        # chunk budget <- windowed decode-gap p99 vs target: a gap
        # ratio sustained above gap_high means prefill chunks are
        # stalling decode (halve the pump's budget); sustained below
        # gap_low WITH prompts still queued means admission has
        # headroom (double it)
        adm = engine.admitter
        if adm is not None and hasattr(adm, "chunk_budget"):
            gap = m.window("decode_gap_s", cfg.window)
            if gap is not None:
                ratio = gap["p99"] / cfg.gap_target_s
                d = self._chunk.observe(
                    ratio,
                    can_up=adm.chunk_budget > cfg.chunk_min,
                    can_down=(adm.chunk_budget < cfg.chunk_max
                              and engine.scheduler.queue_depth > 0))
                if d == "up":
                    bus.set_chunk_budget(
                        max(cfg.chunk_min, adm.chunk_budget // 2))
                elif d == "down":
                    bus.set_chunk_budget(
                        min(cfg.chunk_max, adm.chunk_budget * 2))

        # per-class Degrade <- live queue depth (sustain IS the
        # window here — see the docstring)
        d = self._load.observe(float(engine.scheduler.queue_depth))
        if d == "up":
            bus.degrade_waiting(cfg.degrade_below_priority)
        elif d == "down":
            bus.restore_waiting()

        # draft cap <- windowed accept rate (speculative engines): a
        # rate sustained below accept_low means drafts are dying at
        # verify (cut the cap one), above accept_high means the cap is
        # leaving accepted tokens on the table (raise it one)
        spec = getattr(engine, "_spec", None)
        if spec is not None:
            drafted = m.window("draft_tokens", cfg.window)
            accepted = m.window("accepted_tokens", cfg.window)
            if drafted is not None and drafted["mean"] > 0:
                rate = (accepted["mean"] / drafted["mean"]
                        if accepted is not None else 0.0)
                cap = engine.draft_cap
                cur = spec.k if cap is None else cap
                d = self._draft.observe(rate,
                                        can_up=cur < spec.k,
                                        can_down=cur > 0)
                if d == "up":
                    bus.set_draft_cap(
                        None if cur + 1 >= spec.k else cur + 1)
                elif d == "down":
                    bus.set_draft_cap(cur - 1)

        self._n_samples += 1

    # -- deadline-aware preemption -------------------------------------------

    def deadline_victims(self, engine, now: float) -> List:
        """RUNNING rows to evict so short-deadline feasible waiters
        seat in time — consulted by the engine's ``_admit`` after the
        static priority-demand loop (so cross-CLASS preemption keeps
        its existing semantics; this adds the within/lower-class
        deadline trade).

        A waiter triggers only when ALL hold: it has a deadline; it is
        FEASIBLE if seated now (``now + est*rem <= deadline`` — an
        infeasible waiter is the shed path's problem, evicting for it
        wastes a replay); no free slot will seat it anyway; and
        waiting one victim-completion would make it miss (the
        would-otherwise-miss test, padded by ``preempt_margin_s``).
        The victim is the running row with the MOST deadline slack
        (no-deadline rows = infinite slack), never from a higher
        priority class, and only when the trade is strictly sound:
        the victim's slack after the detour still exceeds what the
        waiter has now. Deterministic: ties break by arrival order.
        Preemption is loss-free, so a mis-estimate costs latency,
        never tokens."""
        cfg = self.config
        if not cfg.preempt:
            return []
        est = engine.metrics.service_time_estimate()
        if est is None or est <= 0:
            return []
        sched = engine.scheduler
        running = list(sched.running.values())
        if not running:
            return []

        def rem(req) -> int:
            return max(1, req.max_new_tokens - len(req.output))

        free = engine.pool.free_slots
        victims: List = []
        taken = set()
        for w in sched.peek_waiting(len(running) + free):
            dl = w.deadline_time
            if dl is None:
                continue
            slack_w = dl - now - est * rem(w)
            if slack_w < 0:
                continue                    # infeasible even seated now
            if free > 0:
                free -= 1                   # this admit round seats it
                continue
            # would it still make its deadline after ONE victim
            # completion? the shortest-remaining running row bounds
            # the natural wait
            left = [rem(r) for r in running if id(r) not in taken]
            if not left:
                break                       # every row already traded
            wait = est * min(left)
            if slack_w - wait >= cfg.preempt_margin_s:
                continue                    # it can afford to wait
            best, best_slack = None, None
            for r in running:
                if id(r) in taken or r.priority > w.priority:
                    continue
                rdl = r.deadline_time
                slack_r = float("inf") if rdl is None \
                    else rdl - now - est * rem(r)
                # strictly sound: the victim, after waiting behind
                # the seated waiter, keeps more slack than the waiter
                # has now
                if slack_r - est * rem(w) <= slack_w:
                    continue
                if best is None or slack_r > best_slack or \
                        (slack_r == best_slack and r.seq < best.seq):
                    best, best_slack = r, slack_r
            if best is None:
                break                       # no sound trade for anyone
            taken.add(id(best))
            victims.append(best)
        return victims
