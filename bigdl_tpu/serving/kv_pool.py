"""Pooled, slot-indexed KV cache with a free-list allocator.

The serving analog of the reference's fixed executor pool (SoCC'19: work
is scheduled onto a FIXED set of executors instead of spawning per-job
state): decode capacity is ``n_slots`` rows of ONE pooled per-layer K/V
cache, allocated/freed per request through a free list, instead of the
per-call private carries ``generate()`` builds. One pool + one compiled
step means admission and eviction never change tensor shapes — the XLA
program is compiled once and reused for the engine's whole lifetime.

The pool's tensors ARE a :func:`make_batch_decode_step` carry (same
``pos``/``k{i}``/``v{i}`` layout), so the engine hands ``pool.carry``
straight to the step function and stores the returned carry back.
"""

from __future__ import annotations

from typing import Dict, List, Optional

_FREE_RESET = None


def _shared_free_reset():
    """Lazily-built process-wide jitted free-reset (see
    KVPool._make_free_reset for why it is shared)."""
    global _FREE_RESET
    if _FREE_RESET is None:
        import jax

        _FREE_RESET = jax.jit(KVPool._free_reset_impl,
                              donate_argnums=(0,))
    return _FREE_RESET


class KVPool:
    """Fixed-capacity pooled KV cache: ``n_slots`` independent rows.

    * :meth:`alloc` pops a slot id off the free list (None when full);
    * :meth:`free` zeroes the row's position and returns the slot;
    * :meth:`write_prefill` row-scatters one row of a prefilled carry
      (a ``make_prefill_step`` B=1 carry, or any row of a
      ``make_batch_prefill_step`` batched-admission carry) into a
      slot — the cheap admission path for mid-flight continuous
      batching.

    Invariants (pinned by tests/test_serving.py): a slot is never handed
    out twice without an intervening free (no aliasing), ``free`` of an
    unallocated slot raises, and after every request drains the free
    list holds all ``n_slots`` again (no leaks).

    ``kv_dtype`` (``"fp32"``/``"bf16"``/``"int8"``, None = infer) is
    the declarative storage-format knob: it must match what the carry
    actually stores (``make_batch_decode_step``'s ``kv_quant``/
    ``compute_dtype`` knobs decide that), and mismatches raise at
    construction. An int8 carry brings per-(slot, head) fp32 dequant
    scales (``k{i}_scale``/``v{i}_scale``) that ride the admission
    scatter with their rows and reset to zero on ``free`` (scales are
    grow-only mid-flight — a recycled slot must not inherit its
    previous occupant's range). ``kv_bytes_per_slot`` is the per-slot
    KV footprint in bytes (payload + scales) — the capacity
    denominator behind the serving metrics and the kv_quant bench.
    """

    def __init__(self, init_carry, n_slots: int,
                 kv_dtype: Optional[str] = None) -> None:
        import jax
        import numpy as np

        if n_slots <= 0:
            raise ValueError(f"n_slots must be positive, got {n_slots}")
        self.n_slots = int(n_slots)
        # one logical shard; the mesh-aware subclass (serving.sharded.
        # ShardedKVPool) overrides these with the slot-axis shard count
        # and per-shard row block
        self.n_shards = 1
        self.rows_per_shard = self.n_slots
        self.carry = init_carry(self.n_slots)
        # k0, k1, ... — NOT k0_scale (the int8 layout's dequant scales)
        self.n_layers = sum(1 for k in self.carry
                            if k.startswith("k") and k[1:].isdigit())
        self.max_len = int(self.carry["k0"].shape[1])
        self.quantized = "k0_scale" in self.carry
        # the storage-format knob is declarative: the carry (built by
        # make_batch_decode_step's init_carry) is the ground truth, and
        # a mismatched claim here would mean the engine wired its knobs
        # inconsistently — fail loudly at construction, not at serve
        stored = np.dtype(self.carry["k0"].dtype).name
        stored = {"float32": "fp32", "bfloat16": "bf16"}.get(stored, stored)
        if kv_dtype is not None and kv_dtype != stored:
            raise ValueError(
                f"kv_dtype={kv_dtype!r} but the carry stores K/V as "
                f"{stored!r} — build the carry with the matching "
                "make_batch_decode_step(kv_quant=...) knob")
        self.kv_dtype = stored
        # bytes of KV state ONE slot owns (int8 payload + its scales,
        # or the float cache): the capacity denominator the kv_quant
        # bench and serving/kv_bytes_per_slot metric report
        import re

        kv_key = re.compile(r"^[kv]\d+(_scale)?$")
        self.kv_bytes_per_slot = int(sum(
            v.dtype.itemsize * int(np.prod(v.shape[1:]))
            for k, v in self.carry.items() if kv_key.match(k)))
        # LIFO free list: the most recently freed row is the most likely
        # to still be resident in cache/HBM
        self._free: List[int] = list(range(self.n_slots - 1, -1, -1))
        self._in_use: set = set()
        # ONE jitted, donated scatter for admissions: copies every
        # layer's full B=1 prefill row into the slot in place. Op-by-op
        # eager updates would allocate 2*n_layers full-pool output
        # buffers per admission (hundreds of MB of HBM traffic at LM
        # scale); donation updates the pool buffers in place, and
        # copying the FULL max_len row (tail zeros included — masked by
        # pos anyway) keeps the program length-independent, so it
        # compiles exactly once per pool. (_make_scatter is the subclass
        # hook: the sharded pool pins the output shardings so scattered
        # carries keep their mesh placement.)
        self._scatter = self._make_scatter()
        # ONE jitted, donated reset for free(): pos plus, on the int8
        # layout, every (slot, head) dequant-scale row. Op-by-op eager
        # .at[].set would be 1 + 2*n_layers separate device dispatches
        # (each allocating a fresh buffer) on the request-completion hot
        # path; the slot id is a traced scalar so the program compiles
        # once per pool. (_make_free_reset is the subclass hook — the
        # sharded pool pins output shardings, same as the scatter.)
        self._reset_keys = ["pos"]
        if self.quantized:
            self._reset_keys += [f"{kind}{i}_scale"
                                 for i in range(self.n_layers)
                                 for kind in ("k", "v")]
        self._free_reset = self._make_free_reset()
        # CHUNK-PROGRESS tracking (chunked streaming admission —
        # serving/chunked.py): host-side mirrors of how much of a
        # slot's prompt is resident (`chunk_done`, kept in lockstep
        # with the device `pos` by write_prefill/set_pos) and how much
        # it ultimately needs (`chunk_target`, set by begin_chunks;
        # 0 = no chunk plan). Host ints, so the chunk pump never reads
        # the device back mid-stream. Both RESET with their slot in
        # free() — the same recycled-slot contract the int8 scales
        # follow: a new occupant must never inherit its predecessor's
        # progress (a stale target would make a fresh row look
        # mid-prefill and stall its activation forever).
        self.chunk_done = np.zeros((self.n_slots,), np.int64)
        self.chunk_target = np.zeros((self.n_slots,), np.int64)
        # per-slot ADAPTER id (multi-tenant LoRA — serving/lora.py):
        # a host-side int mirror the engine feeds to the compiled steps
        # as per-row runtime data. 0 = the null adapter (base model).
        # Host ints like the chunk mirrors, and reset with the slot in
        # free() under the same recycled-slot contract — a leaked id
        # would serve the next occupant through the wrong tenant's
        # factors.
        self.adapter_ids = np.zeros((self.n_slots,), np.int32)
        # optional DRAFT carry (speculative decoding): a second,
        # slot-aligned pooled carry for the draft model — see
        # attach_draft()
        self.draft_carry = None

    def _make_scatter(self):
        import jax

        return jax.jit(self._scatter_impl, donate_argnums=(0,))

    def _make_free_reset(self):
        # ONE process-wide jitted wrapper (module cache): pools come and
        # go with engines, and a per-instance jax.jit would re-trace the
        # same-shaped reset for every new engine — inside a timed serve
        # for benches that construct engines per pass. Shapes/dtypes key
        # jit's own cache, so unrelated pool layouts still coexist. (The
        # sharded subclass overrides with a per-instance wrapper — its
        # output shardings are mesh-specific.)
        return _shared_free_reset()

    @staticmethod
    def _free_reset_impl(leaves, slot):
        return {k: v.at[slot].set(0) for k, v in leaves.items()}

    @staticmethod
    def _scatter_impl(carry, prefill_carry, slot, pos, row):
        # layer keys derive from the CARRY (static under trace), so one
        # impl serves both the target pool and an attached draft carry
        # (different layer counts/shapes key jit's own cache)
        import re

        from jax import lax

        out = dict(carry)
        for key in carry:
            if not re.fullmatch(r"[kv]\d+", key):
                continue
            src = lax.dynamic_slice_in_dim(
                prefill_carry[key], row, 1, axis=0
            ).astype(carry[key].dtype)
            out[key] = lax.dynamic_update_slice(
                carry[key], src, (slot, 0, 0, 0))
            # int8 layout: the row's (1, heads) dequant scales land
            # with it — a quantized row is meaningless without them
            skey = f"{key}_scale"
            if skey in carry:
                ssrc = lax.dynamic_slice_in_dim(
                    prefill_carry[skey], row, 1, axis=0)
                out[skey] = lax.dynamic_update_slice(
                    carry[skey], ssrc, (slot, 0))
        out["pos"] = carry["pos"].at[slot].set(pos)
        return out

    # -- allocator ---------------------------------------------------------

    def alloc(self) -> Optional[int]:
        """A free slot id, or None when the pool is saturated."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._in_use.add(slot)
        return slot

    def free(self, slot: int) -> None:
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        self._in_use.remove(slot)
        self._free.append(slot)
        # reset the row's position so a recycled slot starts fresh; the
        # stale K/V rows are harmless (masked by pos) and zeroing them
        # would be pure HBM traffic. On the int8 layout the dequant
        # scales reset too: scales are grow-only in-step, so a recycled
        # slot MUST drop its previous occupant's scale — a stale large
        # scale would quantize the next request's (smaller) values
        # coarsely for its whole lifetime. One donated jitted dispatch
        # covers pos + all scale rows (see _make_free_reset).
        import jax.numpy as jnp

        self.carry.update(self._free_reset(
            {k: self.carry[k] for k in self._reset_keys},
            jnp.int32(slot)))
        # chunk-progress fields reset with the slot (recycled-slot
        # contract): a leaked done/target pair would make the next
        # occupant look mid-prefill
        self.chunk_done[slot] = 0
        self.chunk_target[slot] = 0
        self.adapter_ids[slot] = 0
        if self.draft_carry is not None:
            # the draft carry frees WITH its slot: same pos-reset rule
            # (stale draft K/V behind pos are masked, like the target's)
            self.draft_carry.update(self._draft_reset(
                {"pos": self.draft_carry["pos"]}, jnp.int32(slot)))

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def used_slots(self) -> int:
        return len(self._in_use)

    def occupancy(self) -> float:
        # guard n_slots == 0 rather than divide: the constructor forbids
        # it today, but subclasses/metrics must never turn an empty pool
        # into a ZeroDivisionError mid-serving
        return self.used_slots / self.n_slots if self.n_slots else 0.0

    def used_per_shard(self) -> List[int]:
        """Allocated-slot count per shard (one logical shard here; the
        mesh-aware subclass reports per-device counts — the imbalance
        signal ServingMetrics surfaces)."""
        return [self.used_slots]

    def __repr__(self) -> str:
        shards = "" if self.n_shards == 1 else f", n_shards={self.n_shards}"
        kv = "" if not self.quantized else f", kv_dtype={self.kv_dtype}"
        return (f"{type(self).__name__}(n_slots={self.n_slots}, "
                f"used={self.used_slots}, free={self.free_slots}"
                f"{shards}{kv})")

    # -- prefill admission -------------------------------------------------

    def write_prefill(self, slot: int, prefill_carry: Dict,
                      prompt_len: int, row: int = 0) -> None:
        """Row-scatter row ``row`` of a prefilled carry into ``slot``:
        per-layer K/V positions ``0..prompt_len-1`` land in the pooled
        row and the slot's ``pos`` becomes ``prompt_len`` — after this
        the slot decodes exactly as if it had been stepped
        ``prompt_len`` times. ``prefill_carry`` may be the old B=1
        per-request carry (``row=0``) or a multi-row batched-admission
        carry (``make_batch_prefill_step`` output — ``row`` picks the
        request's row). The full ``max_len`` row is copied — the tail
        beyond ``prompt_len`` is invisible behind ``pos`` — via the
        jitted donated scatter built in ``__init__`` (one trace per
        prefill-carry row count; ``row`` rides as a traced argument)."""
        import jax.numpy as jnp

        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        if not 0 < prompt_len <= self.max_len:
            raise ValueError(
                f"prompt_len {prompt_len} outside 1..{self.max_len}")
        if not 0 <= row < prefill_carry["pos"].shape[0]:
            raise ValueError(
                f"row {row} outside the prefill carry's "
                f"{prefill_carry['pos'].shape[0]} rows")
        self.carry = self._scatter(self.carry, prefill_carry,
                                   jnp.int32(slot), jnp.int32(prompt_len),
                                   jnp.int32(row))
        # host mirror of the slot's device pos: the chunk pump plans
        # the next chunk from this without a device readback
        self.chunk_done[slot] = prompt_len

    def read_row(self, slot: int) -> Dict:
        """One allocated slot's carry as a B=1 slice, every leaf (K/V
        layers + scales, pos, sampling lanes) — the carry half of the
        :meth:`row_state` payload a PREEMPTED or handed-off row leaves
        behind. The slices are fresh device arrays (jax
        arrays are immutable), so they survive the slot's ``free()``
        and later scatter BACK via :meth:`restore_row` bitwise — the
        loss-free half of the eviction + readmission contract
        (``ServingEngine._preempt_row``). The dict is also a valid
        :class:`~bigdl_tpu.serving.prefix_cache.PrefixCache` entry (the
        cache stores exactly such B=1 carries), so preempted state can
        be shared with other requests on the same prefix."""
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        return self._fresh_rows(self.carry, slot)

    def _fresh_rows(self, carry: Dict, slot: int) -> Dict:
        """B=1 slices of ``carry`` at ``slot`` that are guaranteed
        FRESH buffers. The guarantee matters on an n_slots == 1 pool:
        jax returns the array ITSELF for a full-window slice, so the
        "stash" would alias the live pool buffers and die with the
        next donated scatter/reset — the latent single-slot stash bug
        the unified row_state API exists to close (pinned by
        tests/test_serving_disagg.py)."""
        import jax.numpy as jnp

        rows = {k: v[slot:slot + 1] for k, v in carry.items()}
        if self.n_slots == 1:
            rows = {k: jnp.array(v, copy=True) for k, v in rows.items()}
        return rows

    def set_pos(self, slot: int, pos: int) -> None:
        """Set one slot's position counter (the no-prefill admission path:
        a 1-token prompt starts decoding at pos 0)."""
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        self.carry["pos"] = self.carry["pos"].at[slot].set(int(pos))
        self.chunk_done[slot] = int(pos)

    # -- unified row serialization (stash + handoff) -----------------------

    def row_state(self, slot: int) -> Dict:
        """EVERYTHING one allocated slot carries, as the canonical row
        payload (``serving/disagg.py``'s ``ROW_PAYLOAD_KEYS`` schema
        minus the request metadata): the B=1 target-carry slice from
        :meth:`read_row` (K/V layers, int8 dequant scales, ``pos``, and
        — on sampling carries — the RNG lane, penalty counts, and
        prompt mask), the ``chunk_done``/``chunk_target``/``adapter``
        host mirrors,
        and the attached DRAFT carry's B=1 slice (``None`` without
        one). This is THE row-serialization API: the engine's
        preemption stash, the disaggregated prefill→decode handoff,
        AND the host spill tier (``serving/kv_tier.py`` packs exactly
        this payload through ``pack_payload`` before it leaves HBM —
        the SRV207 codec discipline) all speak it, so a per-slot field
        added to the carry can never again be captured by one path and
        silently dropped by another (the latent-bug class the old
        carry-only stash invited). :meth:`restore_row` is the inverse —
        byte-identical, pinned by tests/test_serving_disagg.py and
        tests/test_serving_tiered.py."""
        payload = {"carry": self.read_row(slot),
                   "chunk_done": int(self.chunk_done[slot]),
                   "chunk_target": int(self.chunk_target[slot]),
                   "adapter": int(self.adapter_ids[slot]),
                   "draft": None}
        if self.draft_carry is not None:
            payload["draft"] = self._fresh_rows(self.draft_carry, slot)
        return payload

    def restore_row(self, slot: int, payload: Dict) -> None:
        """Scatter a :meth:`row_state` payload into an allocated slot,
        byte-identically: K/V + scales + ``pos`` through the donated
        admission scatter, sampling lanes/counts/mask by direct row
        set (the :meth:`write_sampling` leaves, restored verbatim
        instead of rebuilt), the chunk mirrors from the payload's own
        values, and the draft slice through the draft scatter when both
        sides carry one. Accepts device arrays (in-process stash) and
        the numpy arrays a deserialized transfer payload holds alike —
        and never reads the device back (ASY301): the scatter's ``pos``
        rides as the payload's own traced scalar, so a hot-path restore
        costs dispatches, not syncs. A pos == 0 row (a 1-token prompt
        that never prefilled) scatters harmlessly — its K/V bytes are
        zeros/stale behind pos, like any recycled slot's."""
        import jax.numpy as jnp

        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        carry = payload["carry"]
        # one donated scatter restores K/V + scales and sets pos from
        # the payload's own (traced) value
        self.carry = self._scatter(
            self.carry, carry, jnp.int32(slot),
            jnp.asarray(carry["pos"])[0], jnp.int32(0))
        # sampling lanes ride the payload (write_sampling's leaves):
        # restored verbatim, not rebuilt — the handoff receiver must
        # reproduce the sender's lane state without knowing its seed
        for key in ("rng", "tok_counts", "prompt_mask"):
            if key in carry and key in self.carry:
                self.carry[key] = self.carry[key].at[slot].set(
                    jnp.asarray(carry[key])[0])
        # host mirrors from the payload's own values (SRV203 lockstep):
        # a completed prefill hands off done == pos, target == 0 or pos
        self.chunk_done[slot] = int(payload["chunk_done"])
        self.chunk_target[slot] = int(payload["chunk_target"])
        # adapter id rides the payload (absent in pre-adapter payloads
        # → null adapter, today's behavior)
        self.adapter_ids[slot] = int(payload.get("adapter", 0))
        draft = payload.get("draft")
        if draft is not None and self.draft_carry is not None:
            self.draft_carry = self._draft_scatter(
                self.draft_carry, draft, jnp.int32(slot),
                jnp.asarray(draft["pos"])[0], jnp.int32(0))

    # -- chunk progress (chunked streaming admission) ----------------------

    def begin_chunks(self, slot: int, done: int, target: int) -> None:
        """Open a chunk plan on an allocated slot: ``done`` prompt
        tokens are already resident (0 for a fresh row, the matched
        length after a prefix-cache head write), ``target`` is the full
        prefill length the row needs before it may decode. The chunk
        pump (``serving/chunked.py``) advances ``chunk_done`` through
        ``write_prefill`` until it reaches ``target``."""
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        if not 0 <= done <= target <= self.max_len:
            raise ValueError(
                f"chunk plan done={done}..target={target} outside "
                f"0..{self.max_len}")
        self.chunk_done[slot] = int(done)
        self.chunk_target[slot] = int(target)

    def chunk_remaining(self, slot: int) -> int:
        """Prompt tokens still to stream for a slot's chunk plan
        (0 = complete or no plan)."""
        return int(max(0, self.chunk_target[slot] - self.chunk_done[slot]))

    # -- sampling lanes ----------------------------------------------------

    def write_sampling(self, slot: int, key, prompt_ids,
                       output_ids=()) -> None:
        """Seed one slot's SAMPLING state at admission (requires a
        sampling-enabled carry — ``make_batch_decode_step(...,
        sampling=True)``): the row's RNG lane becomes ``key`` (derived
        from the REQUEST's seed, never from the slot — so a request
        readmitted into a different slot after an eviction continues
        the exact same lane), its generated-token counts are rebuilt
        from ``output_ids`` (empty for a fresh request — zero counts;
        the tokens emitted so far for a preempted/fault-evicted request
        being READMITTED mid-stream, reproducing exactly the counts the
        in-flight row accumulated one draw at a time), and its
        prompt-membership mask is rebuilt from ``prompt_ids`` (1-based;
        feeds the repetition penalty — the ORIGINAL prompt only, never
        the emitted continuation, matching the in-flight state). Stale
        state from the slot's previous occupant is fully overwritten —
        recycled slots leak nothing into the new request's
        distribution."""
        import jax.numpy as jnp
        import numpy as np

        if "rng" not in self.carry:
            raise ValueError(
                "this pool's carry has no sampling state — build it "
                "from make_batch_decode_step(..., sampling=True)")
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        V = self.carry["tok_counts"].shape[1]
        mask = np.zeros((V,), bool)
        if len(prompt_ids):
            mask[np.clip(np.asarray(prompt_ids, np.int64) - 1,
                         0, V - 1)] = True
        counts = np.zeros((V,), np.int32)
        if len(output_ids):
            ids, reps = np.unique(
                np.clip(np.asarray(output_ids, np.int64) - 1, 0, V - 1),
                return_counts=True)
            counts[ids] = reps
        self.carry["rng"] = self.carry["rng"].at[slot].set(
            jnp.asarray(key, jnp.uint32))
        self.carry["tok_counts"] = self.carry["tok_counts"].at[slot].set(
            jnp.asarray(counts))
        self.carry["prompt_mask"] = self.carry["prompt_mask"].at[slot].set(
            jnp.asarray(mask))

    # -- draft carry (speculative decoding) --------------------------------

    def attach_draft(self, init_carry, specs=None) -> None:
        """Attach a DRAFT model's pooled carry alongside the target K/V
        (``bigdl_tpu.serving.speculative``): slot ``s`` of the draft
        carry always belongs to the same request as slot ``s`` here —
        one allocator, two caches. The draft carry is a plain
        :func:`make_batch_decode_step` carry (no sampling state: the
        draft proposes greedily; the REQUEST's lane lives in the target
        carry) and frees/resets with its slot. ``specs`` is ignored on
        the single-device pool (the sharded subclass uses it to pin the
        draft leaves' mesh placement)."""
        if self.draft_carry is not None:
            raise ValueError("a draft carry is already attached")
        self.draft_carry = self._place_draft(init_carry(self.n_slots),
                                             specs)
        self.draft_max_len = int(self.draft_carry["k0"].shape[1])
        self._draft_reset = self._make_draft_reset(specs)
        # last: SPMD104 reads a donating factory's call-site args as the
        # jitted fn's — keep this the final `specs` read in the method
        self._draft_scatter = self._make_draft_scatter(specs)

    def _place_draft(self, carry, specs):
        return carry

    def _make_draft_scatter(self, specs):
        import jax

        # same impl as the admission scatter — layer keys derive from
        # the carry, so the draft's (different) depth/geometry just
        # retraces
        return jax.jit(self._scatter_impl, donate_argnums=(0,))

    def _make_draft_reset(self, specs):
        return _shared_free_reset()

    def write_draft_prefill(self, slot: int, prefill_carry: Dict,
                            prompt_len: int, row: int = 0) -> None:
        """Row-scatter one row of a DRAFT prefill carry into ``slot`` —
        :meth:`write_prefill`'s twin for the attached draft cache."""
        import jax.numpy as jnp

        if self.draft_carry is None:
            raise ValueError("no draft carry attached (attach_draft)")
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        if not 0 < prompt_len <= self.draft_max_len:
            raise ValueError(
                f"prompt_len {prompt_len} outside 1..{self.draft_max_len}")
        self.draft_carry = self._draft_scatter(
            self.draft_carry, prefill_carry, jnp.int32(slot),
            jnp.int32(prompt_len), jnp.int32(row))

    def set_draft_pos(self, slot: int, pos: int) -> None:
        """Set one slot's DRAFT position counter (the no-prefill
        admission path, mirroring :meth:`set_pos`)."""
        if self.draft_carry is None:
            raise ValueError("no draft carry attached (attach_draft)")
        if slot not in self._in_use:
            raise ValueError(f"slot {slot} is not allocated")
        self.draft_carry["pos"] = \
            self.draft_carry["pos"].at[slot].set(int(pos))
