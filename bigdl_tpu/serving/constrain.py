"""Per-row token-mask constrained decoding (structured output).

Structured-output requests (JSON mode, tool-call grammars, fixed
templates) need the sampler restricted to the tokens a grammar allows
AT THIS POSITION — a constraint that changes every step. The engine's
discipline for per-step, per-row state is already settled: it rides the
knob arrays as RUNTIME data of the one compiled step (the min-token ban
rows are the precedent). This module follows it exactly:

* a host-side automaton (:class:`TokenDFA`) advances one state per
  EMITTED token, and
* its current state's allow-set is rendered into the row of a pooled
  ``(n_slots, vocab)`` bool ``allow`` knob
  (``sampling.make_knob_rows(n_slots, vocab=...)``), which
  ``sample_rows`` applies as a hard mask (disallowed logits → ``-1e30``)
  BEFORE the greedy argmax and the sampled draw.

Shape discipline: the mask array's shape is fixed by ``(n_slots,
vocab)``, so constrained and unconstrained rows mix freely in one
program with ZERO extra compiles — an unconstrained row's mask is
all-True, and masking with all-True is the identity, which keeps
unconstrained streams token-identical to the pre-constraint engine
(pinned by tests/test_serving_constrain.py).

Replay: the automaton state is a PURE function of (the request's
constraint, the emitted prefix). The engine therefore never checkpoints
cursor state — preemption, disagg handoff, and pool failover rebuild
the cursor by replaying ``request.output`` through
:meth:`TokenDFA.cursor` (see ``ServingEngine._configure_slot``), and a
fixed-seed constrained stream replays draw-for-draw because the mask a
row sees at step ``t`` depends only on its own first ``t`` tokens.

Token ids are 1-based throughout (the ``submit()`` convention); the
mask is written 0-based (column ``id - 1``), matching the logit layout.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class ConstraintError(ValueError):
    """An emitted token the current automaton state does not allow —
    replaying a prefix through a DIFFERENT constraint, or a mask row
    that was never written. Raised loudly: silently resynchronizing
    would emit grammar-violating output."""


class TokenDFA:
    """A deterministic token automaton: ``states[i]`` is ``(allow,
    edges, default)`` where

    * ``allow`` — the set of 1-based token ids permitted in this state,
      or ``None`` = unconstrained (every token permitted);
    * ``edges`` — ``{token_id: next_state}`` explicit transitions;
    * ``default`` — the next state for a permitted token with no
      explicit edge (``None`` = stay in this state).

    Prefer the builders (:func:`fixed_sequence`,
    :func:`from_token_sets`) over hand-writing state tuples.
    """

    def __init__(self, states: Sequence[Tuple[Optional[frozenset],
                                              Dict[int, int],
                                              Optional[int]]],
                 start: int = 0) -> None:
        if not states:
            raise ValueError("a TokenDFA needs at least one state")
        norm = []
        for allow, edges, default in states:
            allow = None if allow is None else frozenset(
                int(t) for t in allow)
            if allow is not None and any(t <= 0 for t in allow):
                raise ValueError("allow-sets hold 1-based positive ids")
            edges = {int(t): int(s) for t, s in (edges or {}).items()}
            for t, s in edges.items():
                if not 0 <= s < len(states):
                    raise ValueError(f"edge {t}->{s} leaves the DFA")
                if allow is not None and t not in allow:
                    raise ValueError(
                        f"edge on token {t} not in the state's allow-set")
            if default is not None and not 0 <= default < len(states):
                raise ValueError(f"default state {default} out of range")
            norm.append((allow, edges, default))
        self.states = tuple(norm)
        if not 0 <= start < len(self.states):
            raise ValueError(f"start state {start} out of range")
        self.start = int(start)

    def cursor(self, prefix: Sequence[int] = ()) -> "ConstraintCursor":
        """A fresh cursor, optionally advanced over an already-emitted
        ``prefix`` — THE replay rule (state = f(constraint, prefix))."""
        cur = ConstraintCursor(self)
        for tok in prefix:
            cur.advance(tok)
        return cur

    # -- disagg wire -------------------------------------------------------

    def to_meta(self) -> dict:
        """JSON-safe description (ints/lists/dicts only) — what rides a
        disagg row handoff; the cursor itself never travels (it is
        rebuilt from the output prefix on the receiving pool)."""
        return {
            "start": self.start,
            "states": [
                [None if allow is None else sorted(allow),
                 {str(t): s for t, s in sorted(edges.items())},
                 default]
                for allow, edges, default in self.states],
        }

    @classmethod
    def from_meta(cls, meta: dict) -> "TokenDFA":
        states = [
            (None if allow is None else frozenset(allow),
             {int(t): int(s) for t, s in edges.items()},
             default)
            for allow, edges, default in meta["states"]]
        return cls(states, start=meta["start"])


class ConstraintCursor:
    """One row's live position in its :class:`TokenDFA` (host-side,
    engine-owned; advanced once per emitted token)."""

    __slots__ = ("dfa", "state")

    def __init__(self, dfa: TokenDFA) -> None:
        self.dfa = dfa
        self.state = dfa.start

    @property
    def allow(self) -> Optional[frozenset]:
        return self.dfa.states[self.state][0]

    def advance(self, token: int) -> None:
        token = int(token)
        allow, edges, default = self.dfa.states[self.state]
        if allow is not None and token not in allow:
            raise ConstraintError(
                f"token {token} not allowed in state {self.state} "
                f"(allowed: {sorted(allow)})")
        nxt = edges.get(token, default)
        if nxt is not None:
            self.state = nxt

    def mask_row(self, vocab: int, out=None):
        """The state's ``(vocab,)`` bool allow-mask (0-based columns);
        writes into ``out`` when given (the engine passes its knob row
        — one in-place write, no per-step allocation)."""
        import numpy as np

        row = np.empty((vocab,), bool) if out is None else out
        allow = self.allow
        if allow is None:
            row[:] = True
        else:
            row[:] = False
            for t in allow:
                if t <= vocab:
                    row[t - 1] = True
        return row


# -- builders ---------------------------------------------------------------


def fixed_sequence(ids: Sequence[int]) -> TokenDFA:
    """Force exactly ``ids`` (1-based), then unconstrained — the
    template / canned-reply constraint, and the sharpest replay test
    (the output IS the constraint)."""
    ids = [int(t) for t in ids]
    if not ids or any(t <= 0 for t in ids):
        raise ValueError(
            f"fixed_sequence needs non-empty 1-based ids, got {ids}")
    states = []
    for i, t in enumerate(ids):
        states.append((frozenset((t,)), {t: i + 1}, None))
    states.append((None, {}, None))      # exhausted: unconstrained
    return TokenDFA(states)


def from_token_sets(sets: Sequence[Optional[Sequence[int]]]) -> TokenDFA:
    """Position-indexed allow-sets: step ``i`` may emit any id in
    ``sets[i]`` (``None`` = unconstrained at that position), then the
    constraint exhausts to unconstrained. The straight-line table form
    of a grammar whose choices don't branch the FOLLOW sets."""
    if not sets:
        raise ValueError("from_token_sets needs at least one position")
    states: List[tuple] = []
    for i, s in enumerate(sets):
        allow = None if s is None else frozenset(int(t) for t in s)
        states.append((allow, {}, i + 1))
    states.append((None, {}, None))
    return TokenDFA(states)
