"""Chunked-prefill streaming admission: overlap prompt ingestion with
decode.

Batched admission (``serving/admission.py``) bounded the COMPILE cost of
ragged prompt ingestion, but its wall cost still lands in one lump: the
whole admission wave prefills between two decode steps, so a burst of
long-prompt arrivals stalls every in-flight decode row for the full
prefill of the bucket. That is the classic chunked-prefill problem, and
the fix is the MLPerf-TPU-pod playbook (arXiv:1909.09756, PAPERS.md)
applied to admission: keep the one compiled decode program busy and
stream the prompt work in underneath it, a bounded slice at a time.

The machinery already exists. :func:`make_batch_prefill_step` takes
per-row START OFFSETS from ``carry['pos']`` — a suffix continuation,
which IS a prefill chunk. So :class:`ChunkedAdmissionController`
(``ServingEngine(admission="chunked")``) admits a request by binding it
to a KV slot immediately (scheduler state PARTIAL — slot-owning but not
yet decoding) and then, each engine super-step, feeds at most
``chunk_budget`` prompt tokens of chunk prefills BEFORE the decode step
runs for the rows already streaming. A row whose last chunk lands is
``activate()``-d into the running set and decodes from the next step.

Contracts (all pinned by tests/test_serving_chunked.py):

* **token identity** — chunked output is token-identical to
  ``admission="batched"`` (greedy test-pinned; fixed-seed sampled
  streams replay draw-for-draw, including evict/readmit and
  preemption). Per-row streams are independent and each chunk's query
  attends over the SAME ``max_len`` cache window the one-shot prefill
  reduces over — chunking changes when K/V bytes are written, not what
  any position computes — so this is the same float-round-off contract
  every admission mode already meets. (int8 KV: the grow-only scale
  merge reaches the same FINAL scale — max over chunk amaxes = amax
  over the prompt — but early chunks quantized under a smaller interim
  scale requantize on growth, bounded by half a quantum; same honest
  scoping as the speculative int8 note in docs/serving.md.)
* **bounded compiles** — chunk calls are ``(1, L)`` bucket shapes with
  ``L`` riding the existing power-of-two set (capped by the budget's
  bucket), the same shapes the prefix-cache suffix path traces. The
  decode path adds ZERO compiles: PARTIAL rows simply aren't in
  ``running``, and activation is host bookkeeping.
* **bounded stalls** — each super-step spends at most ``chunk_budget``
  prompt tokens (one chunk may finish exactly at the budget; the next
  waits), so the decode-stall gap is bounded by one chunk + one decode
  step instead of one admission wave (``serving/decode_gap_s``;
  ``serving_bench --scenario chunked`` asserts the p99 shrinks on a
  bursty long-prompt trace).
* **composition** — priority scheduling (PARTIAL rows are never
  preemption victims: they progress every step and their replay cost
  is pure loss), prefix cache (a cached prefix writes straight into
  the slot and its tokens SKIP the chunk plan entirely), fault
  recovery (a chunk dispatch that faults evicts exactly its row, which
  replays its chunks at readmission; a decode-step fault never touches
  PARTIAL rows — they keep their progress), speculative decoding (the
  draft cache ingests at activation, like any admission), and the
  sharded plane (chunks route to the owning shard through the pool's
  mesh-pinned scatter, same as batched rows).

Progress lives in the POOL (``KVPool.chunk_done`` / ``chunk_target``,
host mirrors of the device ``pos``), reset with the slot like the int8
scales — the pump never reads the device back mid-stream.

Cost honesty: a chunk call reads the slot's row (``pool.read_row``) and
scatters it back (``write_prefill``) — two full-row copies per chunk on
top of the prefill itself, and per-call dispatch overhead batched
admission amortizes over the bucket. Chunked admission spends MORE total
prefill wall time to bound the per-step stall; it is a latency shaper,
not a throughput optimization (the bench reports both sides).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from bigdl_tpu.serving.admission import AdmissionController, bucket_len
from bigdl_tpu.serving.scheduler import Request


class ChunkedAdmissionController(AdmissionController):
    """Streaming admission: bind slots immediately, feed prompts in
    ``chunk_budget``-bounded chunks between decode steps (module
    docstring). Owned by :class:`ServingEngine` under
    ``admission="chunked"``; shares the batched controller's bucket
    ledger, prefix cache plumbing, and the engine's one cached
    batch-prefill step."""

    def __init__(self, engine, chunk_budget: int = 32,
                 prefix_cache=None) -> None:
        super().__init__(engine, prefix_cache=prefix_cache)
        if int(chunk_budget) < 1:
            raise ValueError(
                f"chunk_budget must be >= 1, got {chunk_budget}")
        # read FRESH each pump(), so the autopilot's declared actuator
        # (ActuatorBus.set_chunk_budget — the ONE sanctioned writer
        # outside this __init__; SRV208 flags any other) retunes the
        # budget between steps without touching compiled programs
        self.chunk_budget = int(chunk_budget)
        # slot -> (request, full fed-token list); admission order decides
        # pump order (earliest-admitted row completes first — the TTFT-
        # fair choice, and the one that matches batched admission's
        # effective ordering)
        self._plans: Dict[int, Tuple[Request, List[int]]] = {}
        self._order: List[int] = []

    # -- admission: bind now, stream later ----------------------------------

    def admit(self, n: int) -> None:
        """Admit ``n`` scheduler-approved requests as PARTIAL rows with
        chunk plans. Rows that need no streaming — empty prefill,
        byte-exact preemption resume, or a FULL prefix-cache hit —
        activate immediately (they are exactly as ready as a batched
        admission would have made them)."""
        eng = self.engine
        for _ in range(n):
            # the shared admission prologue (AdmissionController.
            # _bind_next): empty prefills and byte-exact preemption
            # resumes come back with pf=None — nothing to stream
            slot, req, pf = self._bind_next(partial=True)
            if pf is None:
                eng.scheduler.activate(slot)
                continue
            done = 0
            if self.prefix_cache is not None:
                done = self._prefix_head(slot, req, pf)
            if done >= len(pf):                # full hit: zero chunks
                eng.scheduler.activate(slot)
                continue
            eng.pool.begin_chunks(slot, done, len(pf))
            self._plans[slot] = (req, pf)
            self._order.append(slot)

    def _prefix_head(self, slot: int, req, pf: List[int]) -> int:
        """Prefix-cache head write: the longest cached prefix lands in
        the slot in one scatter and its tokens SKIP the chunk plan —
        returns the matched length (0 on a miss). Unlike the batched
        path, the remaining suffix is NOT prefilled here; it becomes
        the chunk plan. Namespaced by the request's adapter id, like
        every prefix-cache touch."""
        eng = self.engine
        carry, matched, lease = self.prefix_cache.acquire(
            pf, adapter_id=req.adapter_id)
        eng.metrics.on_prefix_lookup(matched, len(pf))
        if matched == 0:
            return 0
        try:
            # no phase timer: the head write is a device scatter whose
            # completion the step's decode fence absorbs, like every
            # un-fenced prefill (the prefill_s phase is gone — PR 15)
            eng.pool.write_prefill(slot, carry, matched)
        finally:
            self.prefix_cache.release(lease)
        return matched

    # -- the pump: one budget of chunks per super-step -----------------------

    def pump(self) -> None:
        """Feed at most ``chunk_budget`` prompt tokens of chunk
        prefills, earliest-admitted row first, then hand control back
        so the decode step runs. The first chunk always fits (chunk
        width is capped by the budget); a later chunk that would
        overflow the remaining budget waits for the next super-step.
        Rows whose last chunk lands are activated into the running set
        (and inserted into the prefix cache, like a completed batched
        prefill). A chunk dispatch that faults evicts exactly its own
        row for loss-free replay; other rows keep streaming."""
        from bigdl_tpu.serving.faults import FaultError

        if not self._plans:
            return
        eng = self.engine
        budget, spent, full = self.chunk_budget, 0, False
        for slot in list(self._order):
            if slot not in self._plans:
                continue                       # dropped mid-round
            req, pf = self._plans[slot]
            while slot in self._plans:
                done = int(eng.pool.chunk_done[slot])
                if done >= len(pf):
                    self._plans.pop(slot, None)
                    eng.scheduler.activate(slot)
                    break
                if full:
                    break
                n = min(budget, len(pf) - done)
                if spent and spent + n > budget:
                    full = True
                    break
                try:
                    self._feed_chunk(slot, req, pf, done, n)
                except FaultError:
                    # evicts this row only (drops its plan via the
                    # engine's recovery hook); the round continues
                    eng._recover_admission([(slot, req)])
                    break
                spent += n
                if spent >= budget:
                    full = True                # completion check still runs
            if full:
                break
        self._order = [s for s in self._order if s in self._plans]
        eng.metrics.on_partial_rows(len(self._plans))

    def _feed_chunk(self, slot: int, req, pf: List[int], done: int,
                    n: int) -> None:
        """ONE suffix-continuation prefill of ``pf[done:done+n]`` for a
        slot: the slot's current row is the input carry (its ``pos`` is
        the start offset), the chunk lands through the donated scatter,
        and the completed prompt is shared into the prefix cache."""
        import jax.numpy as jnp
        import numpy as np

        eng = self.engine
        L = bucket_len(n, eng.max_len)
        toks = np.zeros((1, L), np.int32)
        toks[0, :n] = pf[done:done + n]
        row = eng.pool.read_row(slot)          # pos[0] == done
        self._note_shape(1, L)
        # NO completion fence, no phase timer: the chunk prefill now
        # dispatches and RETURNS — it overlaps the decode step (the
        # very overlap chunked admission exists to create) and the
        # step's decode fence absorbs its completion. A timer here
        # would measure only the launch (the ASY305 lie); the PR 12
        # worksheet marked this site deletable
        # (docs/async_readiness.md).
        _, out = eng._dispatch("prefill", eng._batch_prefill_fn,
                               eng.params, jnp.asarray(toks),
                               np.asarray([n], np.int32), row,
                               *eng._prefill_adapter_args(
                                   [req.adapter_id]))
        eng.metrics.on_prefill_batch(1, 1)
        eng.pool.write_prefill(slot, out, done + n)
        if done + n == len(pf) and self.prefix_cache is not None:
            self.prefix_cache.insert(pf, out, adapter_id=req.adapter_id)
        eng.metrics.on_chunk(n)

    # -- teardown hooks (cancel / fault / preempt paths) --------------------

    def drop(self, slot: int) -> None:
        """Forget a slot's chunk plan AND its pump-order position
        (cancellation, fault eviction — the engine frees the slot,
        which resets the pool's progress fields). The order entry must
        go with the plan: a freed slot's next occupant would otherwise
        inherit this row's queue position and stream ahead of
        earlier-admitted rows. Idempotent; a readmitted request replans
        from its replay stream."""
        self._plans.pop(slot, None)
        if slot in self._order:
            self._order.remove(slot)

    @property
    def partial_slots(self) -> List[int]:
        """Slots currently mid-stream, in pump order (introspection)."""
        return [s for s in self._order if s in self._plans]
