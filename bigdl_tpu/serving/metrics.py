"""Serving observability counters.

Layered on :class:`bigdl_tpu.optim.metrics.Metrics` (the reference's
``Metrics.scala`` analog, already exercised by the observability suite)
so serving counters ride the same set/add/mean surface the training plane
uses — a ``TrainSummary``-style consumer can read either.

Counters (all under the ``serving/`` prefix in the backing Metrics):

* ``queue_depth``       — sampled every engine step
* ``slot_occupancy``    — used/total slots, sampled every engine step
* ``batch_active``      — active rows per decode step
* ``ttft_s``            — per-request time-to-first-token (submit →
  first GENERATED token on host; includes queueing + prefill)
* ``latency_s``         — per-request submit → finish
* ``tokens_out``        — generated tokens per request (recorded at
  finish; sum = total tokens served)
* ``decode_step_s``     — the fenced decode/verify dispatch window
  (prefill dispatches are no longer completion-fenced — they overlap
  the decode step and their device time lands inside this window; the
  former ``prefill_s``/``draft_prefill_s`` phase timers went with the
  fences, see docs/async_readiness.md)
* ``cancelled``         — requests cancelled while WAITING

Chunked-admission counters (``serving/chunked.py``):

* ``chunks`` / ``chunk_tokens`` — chunk-prefill calls fed by the pump
  and the prompt tokens they carried (sums = total chunk traffic;
  ``chunk_tokens``/``chunks`` mean = effective chunk width)
* ``partial_rows``     — mid-prefill PARTIAL rows, sampled per pump
* ``decode_gap_s``     — wall gap between consecutive decode (or
  verify) dispatches while rows were in flight across the gap: the
  DECODE-STALL signal chunked admission exists to shrink (a batched
  admission burst shows up as one huge gap; chunked bounds it by the
  chunk budget). ``decode_gap_percentiles()`` summarizes;
  ``summary()`` reports the p99
* ``host_step_s``      — per-super-step HOST time: step wall minus the
  fenced device phase windows (decode/verify dispatch, draft chain)
  timed inside it — the Python the device pipeline waits on between
  dispatches, i.e. the async dispatch-ahead refactor's before-number
  (``host_step_percentiles()``; ``summary()`` reports p50/p99)

Feasibility admission control (``ServingEngine(deadline_feasibility=
True)``):

* ``infeasible``       — waiting requests deadline-dropped because the
  running ``decode_step_s`` median says they cannot finish inside their
  deadline (each also counts as shed + deadline_missed; the EDF-with-
  admission-control step beyond dropping only already-expired work)

Batched-admission counters (``serving/admission.py``):

* ``prefill_batch``     — true rows per batched prefill call (mean =
  admission batching factor; count = number of prefill calls)
* ``prefill_batch_padded`` — padded rows per call (bucketing overhead)
* ``prefill_bucket_compiles`` — novel (B, L) prefill shapes traced
  (sum = the bounded compiled-program count the bucket scheme enforces)
* ``prefix_lookups`` / ``prefix_hits`` / ``prefix_hit_tokens`` —
  prefix-cache traffic; ``summary()`` derives ``prefix_hit_rate``

Sampling counters (``serving/sampling.py``):

* ``rows_sampled`` / ``rows_greedy`` — active rows per decode step that
  drew from a sampled distribution (temperature > 0) vs took argmax;
  ``summary()`` derives ``sampled_row_frac``
* ``mean_logprob``        — per-request mean chosen-token raw model
  log-prob (recorded at finish; a cheap generation-quality signal)

Speculative-decoding counters (``serving/speculative.py``):

* ``draft_tokens`` / ``accepted_tokens`` — drafts proposed per
  super-step vs landed in request outputs (verify-confirmed and not
  discarded by a mid-chunk stop); ``summary()`` derives
  ``accept_rate`` (accepted/drafted)
* ``spec_rows``          — active rows per super-step (row-steps);
  ``summary()`` derives ``tokens_per_step`` ((accepted + rows)/rows —
  emitted tokens per row per target invocation, 1.0 = plain decode)
* ``draft_s``            — draft-chain phase timing (the verify
  dispatch lands in ``decode_step_s``; the draft PREFILL is un-fenced
  and overlaps the step like every prefill)

Sharded-plane counters (``serving/sharded.py``):

* ``mesh_data_shards`` / ``mesh_model_shards`` — the engine's mesh
  shape (set once at construction; 1/1 for an unsharded engine)
* ``shard_occupancy_min`` / ``shard_occupancy_max`` — per-shard slot
  occupancy extremes, sampled every engine step
* ``shard_imbalance`` — cross-shard admission imbalance in ROWS
  (max − min allocated slots across shards; 0 = perfectly balanced —
  the balanced allocator keeps it ≤ 1 under drain-style traffic)

Resilience counters (``serving/scheduler.py`` + ``serving/faults.py``):

* ``preempted``         — RUNNING rows evicted loss-free by priority
  preemption (their streams resume byte-identically at readmission)
* ``shed``              — requests load-shed without running: queue-full
  rejections at submit plus deadline-drops of expired waiting requests
* ``deadline_missed``   — deadline-dropped requests plus FINISHED
  requests that completed after their deadline
* ``retries``           — row evictions by fault recovery (a failed /
  garbage / timed-out step evicts its rows and replays them)
* ``recovered_rows``    — retried requests that went on to FINISH
  successfully (the loss-free-recovery success count)
* ``degraded``          — requests whose ``degrade`` knob was applied
  at admission under pressure
* ``finished_in_slo``   — finished requests that met their deadline
  (no-deadline requests count as met); ``summary()`` derives
  ``goodput`` = finished_in_slo / submitted — the overload bench's
  headline (``serving_bench --scenario slo``)

Disaggregated-plane counters (``serving/disagg.py`` — recorded on the
front end's metrics; each pool's engine keeps its own full set):

* ``handoffs``         — prefill→decode KV-row handoffs (sum)
* ``transfer_bytes``   — serialized payload bytes per handoff (sum =
  total wire traffic; ``summary()`` derives
  ``transfer_bytes_per_handoff``)
* ``transfer_s``       — per-handoff transfer wall (pack + send +
  deliver on the in-process path); ``transfer_percentiles()``
  summarizes, ``summary()`` reports the p99
* ``prefill_occupancy`` / ``decode_occupancy`` — per-step pool slot
  occupancies (one decode sample per pool per step) — the
  pool-sizing signal

Pool-lifecycle counters (``serving/health.py`` + the failover /
autoscaler machinery in ``serving/disagg.py``):

* ``pool_deaths``       — decode pools classified DEAD (missed
  heartbeats, consecutive transfer failures, or an operator
  ``kill_pool``)
* ``failovers``         — completed pool failovers (one per death
  that had rows to reconstruct or a state to retire)
* ``failover_s``        — wall time of each failover (detect →
  every stranded row re-routed); ``failover_percentiles()``
  summarizes, ``summary()`` reports p50/p99
* ``migrated_rows``     — rows moved pool-to-pool LOSS-FREE via a
  ``row_state`` payload (graceful drain, wire re-routes, and
  stash-current failover rows)
* ``replayed_rows``     — rows reconstructed by byte-identical
  prefill replay of ``prompt + emitted`` (failover of rows whose
  handoff stash was stale — the PR 8 recovery contract lifted to
  pool scope)
* ``transfer_timeouts`` — sends past the configured
  ``send_timeout_s`` (treated as failed-unconfirmed and resent;
  the receiver deduplicates)
* ``autoscale_up`` / ``autoscale_down`` — standby-pool activations /
  drain-and-retire actions by the occupancy autoscaler

KV-format counters (``serving/kv_pool.py`` — set once at construction):

* ``kv_bits``            — bits per stored K/V element (32/16/8)
* ``kv_bytes_per_slot``  — one slot's KV footprint in bytes (int8
  payload + per-(slot, head) scales on the quantized path)
* ``kv_slots_per_gib``   — derived effective capacity: concurrent
  slots per GiB of HBM at this format (the int8 path's ~2x headline)
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from bigdl_tpu.optim.metrics import Metrics


class ServingMetrics:
    """Queue/latency/throughput counters for :class:`ServingEngine`."""

    #: THE closed finish-reason vocabulary. Every string a request can
    #: finish with has a per-reason counter (``serving/finish_<reason>``
    #: via :meth:`on_finish_reason`), so dashboards/goodput math can
    #: never silently miss a disposition class. Adding a reason means
    #: adding it HERE first — the static analyzer's SRV205 rule reads
    #: this frozenset (cross-module) and flags any reason string the
    #: serving plane uses that is not in it.
    FINISH_REASONS = frozenset({
        "eos",         # the request's private eos token appeared
        "stop",        # stop-token / stop-sequence hit
        "length",      # max_new_tokens reached
        "shed",        # queue-full backpressure at submit
        "deadline",    # expired while WAITING (deadline-drop)
        "infeasible",  # feasibility admission control drop
        "error",       # fault-recovery retry budget exhausted
        "cancelled",   # caller cancel() — state-carried, so
                       # Request.finish_reason stays None for these
    })

    def __init__(self, backing: Optional[Metrics] = None) -> None:
        from collections import deque

        self.metrics = backing if backing is not None else Metrics()
        self._t_start: Optional[float] = None
        self._t_last: Optional[float] = None
        # bounded recent-decode-step window for the feasibility
        # estimator: the full-history sample list grows forever and
        # _admit consults the estimate EVERY step, so the estimator
        # must be O(window), not O(lifetime) — and a recent window
        # also tracks drift (load changes, thermal throttling) where
        # a lifetime median would lag
        self._step_window: "deque" = deque(maxlen=512)
        # the draft-phase twin: on speculative engines the k+1 draft
        # dispatches per super-step land in the "draft" phase, not in
        # "decode_step" (which times only the verify dispatch) — a
        # service-time estimate that ignored them would understate
        # true per-token wall by the whole draft share
        self._draft_window: "deque" = deque(maxlen=512)
        # running sums of the speculative counters: the estimator needs
        # lifetime accepted/rows every step, and re-summing the backing
        # Metrics sample lists would be O(lifetime) per call
        self._spec_acc = 0.0
        self._spec_rows = 0.0
        # running sum of the DEVICE phase windows (decode/verify
        # dispatch, draft chain): the engine's per-step
        # host-vs-device split subtracts this across a step
        # (serving/host_step_s — the async refactor's before-number),
        # plus the decode/verify SAMPLE COUNT so the engine can pair
        # one host_step sample with every decode_step sample — on
        # recovery paths too — without re-summing the backing lists
        self._device_s = 0.0
        self._n_decode_steps = 0

    # -- engine hooks ------------------------------------------------------

    def on_submit(self) -> None:
        self.metrics.add("serving/submitted", 1.0)

    def on_step(self, queue_depth: int, occupancy: float,
                batch_active: int) -> None:
        # a declared CLOCK_SITES unit (serving/faults.py): the serve-
        # duration anchor timestamps (_t_start/_t_last span the whole
        # serve for summary()'s wall number) deliberately read the raw
        # wall clock — they are observability, never a lockstep
        # decision. Everything decision-bearing runs on the engine
        # clock; MH403 pins any NEW raw read to this vocabulary.
        now = time.perf_counter()
        if self._t_start is None:
            self._t_start = now
        self._t_last = now
        self.metrics.add("serving/queue_depth", float(queue_depth))
        self.metrics.add("serving/slot_occupancy", float(occupancy))
        self.metrics.add("serving/batch_active", float(batch_active))

    def on_first_token(self, ttft_s: float) -> None:
        self.metrics.add("serving/ttft_s", float(ttft_s))

    def on_finish(self, latency_s: float, n_tokens: int,
                  mean_logprob: Optional[float] = None,
                  met_deadline: Optional[bool] = None) -> None:
        self.metrics.add("serving/finished", 1.0)
        self.metrics.add("serving/latency_s", float(latency_s))
        self.metrics.add("serving/tokens_out", float(n_tokens))
        if mean_logprob is not None:
            self.metrics.add("serving/mean_logprob", float(mean_logprob))
        if met_deadline is not None:
            if met_deadline:
                self.metrics.add("serving/finished_in_slo", 1.0)
            else:
                self.metrics.add("serving/deadline_missed", 1.0)

    # -- resilience hooks (scheduler preemption + fault recovery) ----------

    def on_finish_reason(self, reason: str) -> None:
        """Per-reason disposition counter (``serving/finish_<reason>``),
        recorded for EVERY request leaving the engine — finished,
        shed, deadline-dropped, or errored out. The vocabulary is
        closed (:data:`FINISH_REASONS`): an unknown reason raises here
        rather than minting an unaccounted counter name, and SRV205
        catches the same drift statically before it ever runs."""
        if reason not in self.FINISH_REASONS:
            raise ValueError(
                f"unknown finish_reason {reason!r} — add it to "
                f"ServingMetrics.FINISH_REASONS (and a counter "
                f"consumer) first; known: {sorted(self.FINISH_REASONS)}")
        self.metrics.add(f"serving/finish_{reason}", 1.0)

    def on_preempt(self) -> None:
        """A RUNNING row evicted loss-free to make room for a
        higher-priority request."""
        self.metrics.add("serving/preempted", 1.0)

    def on_shed(self, deadline: bool = False) -> None:
        """A request load-shed without ever running: queue-full
        rejection at submit, or (``deadline=True``) a deadline-drop of
        an expired waiting request — the latter also counts as a
        deadline miss."""
        self.metrics.add("serving/shed", 1.0)
        if deadline:
            self.metrics.add("serving/deadline_missed", 1.0)

    def on_retry(self) -> None:
        """One row evicted by fault recovery (step failure, garbage
        outputs, or a watchdog timeout) and requeued for replay."""
        self.metrics.add("serving/retries", 1.0)

    def on_recovered(self) -> None:
        """A previously fault-evicted request FINISHED successfully —
        the recovery path's success counter."""
        self.metrics.add("serving/recovered_rows", 1.0)

    def on_degrade(self) -> None:
        """A request's ``degrade`` knob applied at admission under
        queue pressure."""
        self.metrics.add("serving/degraded", 1.0)

    def on_degrade_restored(self) -> None:
        """A still-WAITING degraded request got its recorded original
        limits back after pressure dropped (the revertible-Degrade
        contract: a burst's clamp must not outlive the burst)."""
        self.metrics.add("serving/degrade_restored", 1.0)

    def on_actuation(self, actuator: str) -> None:
        """One autopilot bus actuation (``serving/autopilot.py``):
        counted in total and per actuator, so a flapping controller is
        visible on the metrics plane, not just in the bus log."""
        self.metrics.add("serving/actuations", 1.0)
        self.metrics.add(f"serving/actuation_{actuator}", 1.0)

    def on_sample_rows(self, n_sampled: int, n_greedy: int) -> None:
        """Per decode step: how many active rows drew from a sampled
        distribution (temperature > 0) vs took the argmax."""
        self.metrics.add("serving/rows_sampled", float(n_sampled))
        self.metrics.add("serving/rows_greedy", float(n_greedy))

    def on_spec_step(self, n_drafted: int, n_accepted: int,
                     n_rows: int) -> None:
        """Per speculative super-step (``serving/speculative.py``):
        draft tokens proposed across active rows, how many LANDED in
        request outputs (confirmed by the verify step AND not discarded
        by a mid-chunk stop truncation), and the active row count
        (row-steps). Every row also emits one non-draft draw per step,
        so emitted tokens = accepted + rows; ``summary()`` derives
        ``accept_rate`` = accepted/drafted and ``tokens_per_step`` =
        emitted/rows (the per-row speedup denominator — 1.0 is the
        plain decode floor)."""
        self.metrics.add("serving/draft_tokens", float(n_drafted))
        self.metrics.add("serving/accepted_tokens", float(n_accepted))
        self.metrics.add("serving/spec_rows", float(n_rows))
        self._spec_acc += float(n_accepted)
        self._spec_rows += float(n_rows)

    def on_cancel(self) -> None:
        self.metrics.add("serving/cancelled", 1.0)

    def set_mesh_shape(self, data_shards: int, model_shards: int) -> None:
        """Record the engine's mesh shape (once, at construction)."""
        self.metrics.set("serving/mesh_data_shards", float(data_shards))
        self.metrics.set("serving/mesh_model_shards", float(model_shards))

    def set_kv_format(self, kv_dtype: str, bytes_per_slot: int) -> None:
        """Record the pooled cache's storage format (once, at
        construction): bits per stored K/V element, the per-slot KV
        footprint in bytes (int8 payload + dequant scales, or the float
        cache), and the derived effective capacity — concurrent slots
        one GiB of HBM holds at this format. The capacity number is the
        kv_quant headline: int8 runs ~2x the fp16-cache slots."""
        bits = {"fp32": 32.0, "bf16": 16.0, "int8": 8.0}.get(kv_dtype, 0.0)
        self.metrics.set("serving/kv_bits", bits)
        self.metrics.set("serving/kv_bytes_per_slot", float(bytes_per_slot))
        self.metrics.set("serving/kv_slots_per_gib",
                         float((1 << 30) // max(int(bytes_per_slot), 1)))

    def on_shard_slots(self, used_per_shard, rows_per_shard: int) -> None:
        """Per-shard occupancy + cross-shard admission imbalance
        (max−min allocated rows), sampled per engine step on sharded
        pools."""
        if not used_per_shard or not rows_per_shard:
            return
        lo, hi = min(used_per_shard), max(used_per_shard)
        self.metrics.add("serving/shard_occupancy_min", lo / rows_per_shard)
        self.metrics.add("serving/shard_occupancy_max", hi / rows_per_shard)
        self.metrics.add("serving/shard_imbalance", float(hi - lo))

    # -- chunked admission + feasibility hooks -----------------------------

    def on_chunk(self, n_tokens: int) -> None:
        """One chunk-prefill call fed by the streaming-admission pump,
        carrying ``n_tokens`` true prompt tokens."""
        self.metrics.add("serving/chunks", 1.0)
        self.metrics.add("serving/chunk_tokens", float(n_tokens))

    def on_partial_rows(self, n: int) -> None:
        """Mid-prefill PARTIAL rows after one pump pass."""
        self.metrics.add("serving/partial_rows", float(n))

    def on_decode_gap(self, gap_s: float) -> None:
        """Wall gap between consecutive decode dispatches while rows
        stayed in flight — the decode-stall sample (admission work in
        the gap is what stretches it)."""
        self.metrics.add("serving/decode_gap_s", float(gap_s))

    def on_infeasible(self) -> None:
        """A waiting request dropped by feasibility admission control:
        the service-time estimate says it cannot finish in time."""
        self.metrics.add("serving/infeasible", 1.0)

    # -- disaggregated-plane hooks (serving/disagg.py) ---------------------

    def on_handoff(self, n_bytes: int, seconds: float) -> None:
        """One prefill→decode KV-row handoff: the serialized payload's
        size on the wire and the transfer wall (pack + send on the
        sending clock; the in-process engine's sample covers the full
        pack→deliver path). ``summary()`` derives the per-handoff byte
        mean and the transfer_s p99."""
        self.metrics.add("serving/handoffs", 1.0)
        self.metrics.add("serving/transfer_bytes", float(n_bytes))
        self.metrics.add("serving/transfer_s", float(seconds))

    def on_pool_occupancy(self, prefill_occ: float, decode_occs) -> None:
        """Per-front-end-step pool occupancies: the prefill pool's
        slot usage and each decode pool's (one sample per pool per
        step). A prefill pool pinned at 1.0 while decode pools idle
        says the split is prefill-bound — resize the pools, not the
        engine (the interference signal disaggregation turns into a
        CAPACITY signal)."""
        self.metrics.add("serving/prefill_occupancy", float(prefill_occ))
        for occ in decode_occs:
            self.metrics.add("serving/decode_occupancy", float(occ))

    def transfer_percentiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        """Percentiles of the per-handoff transfer wall (seconds)."""
        return self._pctl("transfer_s", qs)

    # -- pool-lifecycle hooks (serving/health.py + disagg failover) --------

    def on_pool_death(self) -> None:
        """A decode pool classified DEAD (heartbeat silence,
        consecutive transfer failures, or an operator kill)."""
        self.metrics.add("serving/pool_deaths", 1.0)

    def on_failover(self, n_migrated: int, n_replayed: int,
                    seconds: float) -> None:
        """One completed pool failover: rows reconstructed loss-free
        from a current ``row_state`` payload (wire re-routes + stash
        restores) vs by prefill replay of ``prompt + emitted``, and
        the detect→done wall time."""
        self.metrics.add("serving/failovers", 1.0)
        if n_migrated:
            self.metrics.add("serving/migrated_rows", float(n_migrated))
        if n_replayed:
            self.metrics.add("serving/replayed_rows", float(n_replayed))
        self.metrics.add("serving/failover_s", float(seconds))

    def on_migrated(self, n_rows: int) -> None:
        """Rows moved pool-to-pool loss-free via the ``row_state``
        handoff payload (graceful drain)."""
        if n_rows:
            self.metrics.add("serving/migrated_rows", float(n_rows))

    def on_transfer_timeout(self) -> None:
        """A handoff send exceeded ``send_timeout_s`` on the engine
        clock: delivery unconfirmed, the request resends (the
        receiver deduplicates by request id)."""
        self.metrics.add("serving/transfer_timeouts", 1.0)

    def on_autoscale(self, direction: str) -> None:
        """One autoscaler action: ``"up"`` (standby pool activated)
        or ``"down"`` (cold pool drained and retired)."""
        if direction not in ("up", "down"):
            raise ValueError(
                f"autoscale direction must be 'up' or 'down', "
                f"got {direction!r}")
        self.metrics.add(f"serving/autoscale_{direction}", 1.0)

    def failover_percentiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        """Percentiles of the per-failover wall time (seconds)."""
        return self._pctl("failover_s", qs)

    # -- host KV tier hooks (serving/kv_tier.py) ---------------------------

    def on_spill(self, n_bytes: int) -> None:
        """One row/prefix entry written into the host tier (packed
        through the ``row_state``/``pack_payload`` codec). ``summary()``
        surfaces the count and total bytes as sums and derives the
        per-spill byte mean."""
        self.metrics.add("serving/spills", 1.0)
        self.metrics.add("serving/spill_bytes", float(n_bytes))

    def on_fetch(self, n_bytes: int, seconds: float) -> None:
        """One tier entry read back (row readmission or prefix
        promotion): the blob size and the host-side unpack wall.
        ``summary()`` derives the fetch_s p99 — the number to hold
        against the re-prefill wall it replaces."""
        self.metrics.add("serving/fetches", 1.0)
        self.metrics.add("serving/fetch_bytes", float(n_bytes))
        self.metrics.add("serving/fetch_s", float(seconds))

    def on_tier_bytes(self, n_bytes: int) -> None:
        """Resident tier footprint (a gauge, not a counter): the bytes
        currently held against ``host_budget_bytes``."""
        self.metrics.set("serving/tier_bytes", float(n_bytes))

    def on_tier_evict(self) -> None:
        """A tier entry evicted by the byte budget (LRU): the copy is
        gone — a row readmission downgrades to prefill replay, a
        prefix lookup to a miss. Loss-free either way; this counter
        rising is the 'raise host_budget_bytes' signal."""
        self.metrics.add("serving/tier_evictions", 1.0)

    def on_resume_without_prefill(self) -> None:
        """A mid-stream row (tokens already emitted) re-seated from a
        stashed/spilled ``row_state`` payload instead of replaying
        prefill — the capacity win the tier exists for."""
        self.metrics.add("serving/resumed_without_prefill", 1.0)

    def fetch_percentiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        """Percentiles of the per-fetch host wall (seconds)."""
        return self._pctl("fetch_s", qs)

    def decode_step_estimate(self, n: int = 64) -> Optional[float]:
        """MEDIAN of the last ``n`` decode-step samples (seconds), or
        None before the first decode step — the per-step service-time
        estimate feasibility admission control builds on. Median, not
        mean: the engine's first dispatch carries the one-time XLA
        compile (multi-second at LM scale — the same cold-start
        outlier the watchdog's arming grace exists for) and
        fault-injected stalls are outliers too; a mean polluted by
        either would spuriously shed early traffic as infeasible. A
        bounded RECENT window (the :meth:`window` discipline), not
        full history: _admit consults this every engine step, so the
        cost must stay O(window) for the engine's whole lifetime — and
        a whole-run median goes stale across traffic phases (a warm
        lull's fast steps would understate a burst's service time and
        admit guaranteed misses)."""
        if not self._step_window:
            return None
        return self._window_stats(
            list(self._step_window)[-int(n):])["p50"]

    def service_time_estimate(self) -> Optional[float]:
        """Estimated seconds per EMITTED TOKEN — what feasibility
        admission control multiplies a request's remaining tokens by.
        Per super-step wall = the decode-step median PLUS the draft-
        phase median (zero on plain engines; on speculative engines
        "decode_step" times only the verify dispatch, and skipping the
        k+1 draft dispatches would understate service time and admit
        guaranteed misses), divided by the measured tokens-per-step
        (1.0 plain; a speculative engine emits 1..k+1 tokens per
        super-step, and dividing by the lifetime rate keeps the
        estimate from overstating service time by up to (k+1)x and
        shedding requests that would have met their deadline — the
        lifetime rate lags a mid-flight Degrade(draft_tokens=0) shift,
        an accepted coarseness)."""
        est = self.decode_step_estimate()
        if est is None:
            return None
        if self._draft_window:
            est += self._window_stats(
                list(self._draft_window)[-64:])["p50"]
        # running sums, not Metrics.get (which re-sums the full
        # per-step sample lists — O(lifetime) on a hot path)
        if self._spec_rows:
            est /= (self._spec_acc + self._spec_rows) / self._spec_rows
        return est

    def decode_gap_percentiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        """Percentiles of the decode-stall samples (seconds)."""
        return self._pctl("decode_gap_s", qs)

    def on_prefill_batch(self, n_rows: int, n_padded: int) -> None:
        self.metrics.add("serving/prefill_batch", float(n_rows))
        self.metrics.add("serving/prefill_batch_padded", float(n_padded))

    def on_bucket_compile(self) -> None:
        self.metrics.add("serving/prefill_bucket_compiles", 1.0)

    def on_prefix_lookup(self, matched_tokens: int, total_tokens: int) -> None:
        self.metrics.add("serving/prefix_lookups", 1.0)
        if matched_tokens > 0:
            self.metrics.add("serving/prefix_hits", 1.0)
            self.metrics.add("serving/prefix_hit_tokens",
                             float(matched_tokens))

    #: phases during which the host is genuinely BLOCKED on device
    #: completion — everything else a step spends is host Python
    #: (scheduling, admission bookkeeping, per-token accounting).
    #: The prefill/draft_prefill phases left this set when their
    #: completion fences were deleted (the PR 12 worksheet's cashed-in
    #: "deletable" entries). The dispatch-ahead refactor (PR 20) moved
    #: ``decode_step`` out too: under a window the dispatch→consume
    #: elapsed OVERLAPS host work on other in-flight steps, so summing
    #: it as "device" would double-count against the step wall and the
    #: host_step residue would lie at W>0. What remains is exactly the
    #: blocked time: ``fence_wait`` (the bracket around each fence
    #: readback — the delayed consumer's actual stall) and ``draft``
    #: (the chain's completion pin). ``decode_step`` samples still
    #: land (the service-time estimator and the step windows read
    #: them); they just stop feeding ``device_seconds``.
    DEVICE_PHASES = frozenset({"fence_wait", "draft"})

    def add_phase(self, name: str, seconds: float) -> None:
        self.metrics.add(f"serving/{name}_s", float(seconds))
        if name == "decode_step":
            self._step_window.append(float(seconds))
            self._n_decode_steps += 1
        elif name == "draft":
            self._draft_window.append(float(seconds))
        if name in self.DEVICE_PHASES:
            self._device_s += float(seconds)

    @property
    def device_seconds(self) -> float:
        """Lifetime sum of the device phase windows (the fenced
        dispatch timings) — the engine snapshots this around a step to
        derive ``serving/host_step_s``."""
        return self._device_s

    @property
    def decode_step_count(self) -> int:
        """Lifetime count of decode/verify dispatch samples — the
        engine pairs exactly one ``host_step_s`` sample with each (a
        recovered step's discarded outputs still cost real host time),
        so the split series stay comparable sample for sample."""
        return self._n_decode_steps

    def host_step_percentiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        """Percentiles of the per-step host-side time (seconds) — the
        Python the device pipeline waits on between dispatches."""
        return self._pctl("host_step_s", qs)

    # -- derived views -----------------------------------------------------

    def _values(self, name: str) -> List[float]:
        return self.metrics.values(f"serving/{name}")

    def tokens_per_sec(self) -> float:
        """Aggregate generated-token throughput over the engine's active
        window (first step → last step)."""
        total, _ = self.metrics.get("serving/tokens_out")
        if self._t_start is None or self._t_last is None \
                or self._t_last <= self._t_start:
            return 0.0
        return total / (self._t_last - self._t_start)

    def _pctl(self, name: str, qs) -> Dict[str, float]:
        """Percentiles of one counter's raw samples (0.0 when empty)."""
        import numpy as np

        vals = self._values(name)
        if not vals:
            return {f"p{q}": 0.0 for q in qs}
        arr = np.asarray(vals)
        return {f"p{q}": float(np.percentile(arr, q)) for q in qs}

    @staticmethod
    def _window_stats(vals) -> Dict[str, float]:
        """mean/p50/p99 over one bounded sample window — the shared
        math behind :meth:`window` and the feasibility estimators."""
        import numpy as np

        arr = np.asarray(vals, dtype=float)
        return {"n": int(arr.size),
                "mean": float(arr.mean()),
                "p50": float(np.percentile(arr, 50)),
                "p99": float(np.percentile(arr, 99))}

    def window(self, name: str, n: int) -> Optional[Dict[str, float]]:
        """Rolling-window view of one serving counter: mean/p50/p99
        (plus the actual sample count ``n``) over the LAST ``n``
        samples of ``serving/<name>`` — the bounded-recency signal the
        autopilot's controllers read. A whole-run percentile goes
        stale across traffic phases (an hour of lull poisons the
        burst's p99 for the rest of the run); a window follows the
        phase. None before the first sample, so controllers never act
        on a guess."""
        if n < 1:
            raise ValueError(f"window size must be >= 1, got {n}")
        vals = self._values(name)
        if not vals:
            return None
        return self._window_stats(vals[-int(n):])

    def ttft_percentiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        return self._pctl("ttft_s", qs)

    def summary(self) -> Dict[str, float]:
        """Means of every serving counter plus derived throughput/TTFT
        percentiles — one flat dict for logging/asserting."""
        out = {k: v for k, v in self.metrics.summary().items()
               if k.startswith("serving/")}
        out["serving/tokens_per_sec"] = self.tokens_per_sec()
        n_look, _ = self.metrics.get("serving/prefix_lookups")
        if n_look:
            n_hit, _ = self.metrics.get("serving/prefix_hits")
            out["serving/prefix_hit_rate"] = n_hit / n_look
        n_s, _ = self.metrics.get("serving/rows_sampled")
        n_g, _ = self.metrics.get("serving/rows_greedy")
        if n_s + n_g > 0:
            out["serving/sampled_row_frac"] = n_s / (n_s + n_g)
        # count-like resilience counters surface as SUMS (the backing
        # Metrics means each add-series; "preempted 0.97 mean" is
        # useless where "preempted 13 rows" is the operational number)
        for name in ("preempted", "shed", "deadline_missed", "retries",
                     "recovered_rows", "degraded", "degrade_restored",
                     "actuations", "finished_in_slo",
                     "infeasible", "chunks", "chunk_tokens",
                     "handoffs", "transfer_bytes",
                     "pool_deaths", "failovers", "migrated_rows",
                     "replayed_rows", "transfer_timeouts",
                     "autoscale_up", "autoscale_down",
                     "spills", "fetches", "spill_bytes", "fetch_bytes",
                     "tier_evictions", "resumed_without_prefill",
                     *(f"finish_{r}" for r in sorted(self.FINISH_REASONS))):
            total, n = self.metrics.get(f"serving/{name}")
            if n:
                out[f"serving/{name}"] = total
        n_sub, _ = self.metrics.get("serving/submitted")
        if n_sub:
            n_slo, _ = self.metrics.get("serving/finished_in_slo")
            # goodput: requests that finished USEFULLY (met their
            # deadline; no-deadline finishes count as met, error
            # finishes never do) over everything submitted —
            # shed/dropped/late/errored all count against it
            out["serving/goodput"] = n_slo / n_sub
        n_draft, _ = self.metrics.get("serving/draft_tokens")
        n_acc, _ = self.metrics.get("serving/accepted_tokens")
        n_rows, _ = self.metrics.get("serving/spec_rows")
        if n_draft:
            out["serving/accept_rate"] = n_acc / n_draft
        if n_rows:
            out["serving/tokens_per_step"] = (n_acc + n_rows) / n_rows
        _, n_gap = self.metrics.get("serving/decode_gap_s")
        if n_gap:
            out["serving/decode_gap_p99_s"] = \
                self.decode_gap_percentiles()["p99"]
        n_hand, n_hand_n = self.metrics.get("serving/handoffs")
        if n_hand_n:
            nb, _ = self.metrics.get("serving/transfer_bytes")
            out["serving/transfer_bytes_per_handoff"] = nb / n_hand
            out["serving/transfer_p99_s"] = \
                self.transfer_percentiles()["p99"]
        n_sp, n_sp_n = self.metrics.get("serving/spills")
        if n_sp_n:
            sb, _ = self.metrics.get("serving/spill_bytes")
            out["serving/spill_bytes_per_row"] = sb / n_sp
        _, n_fe = self.metrics.get("serving/fetch_s")
        if n_fe:
            out["serving/fetch_p99_s"] = self.fetch_percentiles()["p99"]
        _, n_fo = self.metrics.get("serving/failover_s")
        if n_fo:
            fp = self.failover_percentiles()
            out["serving/failover_p50_s"] = fp["p50"]
            out["serving/failover_p99_s"] = fp["p99"]
        _, n_host = self.metrics.get("serving/host_step_s")
        if n_host:
            hp = self.host_step_percentiles()
            out["serving/host_step_p50_s"] = hp["p50"]
            out["serving/host_step_p99_s"] = hp["p99"]
        for k, v in self.ttft_percentiles().items():
            out[f"serving/ttft_{k}_s"] = v
        return out
