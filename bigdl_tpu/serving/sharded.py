"""Sharded serving plane: the pooled prefill/decode/sample programs on a
device mesh.

PRs 1–3 made the serving stack SHAPE-STABLE end to end — bucketed batch
prefill, pooled per-row decode, per-row sampling, all runtime data of a
bounded compiled-program set. That is exactly the property that lets the
same programs scale ACROSS chips (the BigDL thesis transplanted to
inference: partition one logical job over workers with explicit
collectives, arXiv:1804.05839; and the MLPerf-on-TPU-pods recipe: keep
ONE compiled program and grow the mesh, arXiv:1909.09756). This module
is that step. Two composable axes over one
``jax.sharding.Mesh(("data", "model"))``:

* **slot data parallelism** (``data`` axis) — the pooled KV carry
  shards along its SLOT axis: with N data shards each device owns
  ``n_slots/N`` decode rows, and the engine's one
  ``get_batch_decode_step`` invocation steps the whole fleet. Rows
  never interact (per-row attention over the row's own cache; per-row
  sampling lanes, penalty counts, and knob arrays shard with their
  rows for free), so the partitioned program computes BITWISE the same
  per-row math as the single-device engine — sharded serving is
  token-identical, not merely close (pinned by
  tests/test_serving_sharded.py). XLA's SPMD partitioner does the
  splitting: no shard_map, no new program per occupancy, ONE compiled
  step per engine regardless of mesh size.
* **tensor parallelism** (``model`` axis) — attention heads + MLP
  hidden shard Megatron-style through
  :mod:`bigdl_tpu.parallel.tensor_parallel`'s column/row-parallel
  layout, lowered under ``utils.compat.shard_map`` (so it runs on jax
  0.4.37 and on jax.shard_map-era releases alike) with the paper-
  canonical TWO collectives per block: one psum closing the attention
  output projection, one closing the MLP. The per-layer K/V cache
  shards on its HEAD axis; embeddings, LayerNorms, the LM head, and
  the sampling epilogue stay replicated. See
  ``models/transformer.py`` (``mesh=`` on the step builders).

The subsystem owns mesh construction (:func:`make_mesh`, including the
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` CPU emulation
recipe via :func:`emulate_cpu_devices`, so everything here is testable
on a single-host box), the sharded pool
(:class:`ShardedKVPool` — slot→(shard, row) mapping, balanced
cross-shard allocation, mesh-pinned admission scatter), and the
:class:`ShardedEngine` front end. The stock
:class:`~bigdl_tpu.serving.engine.ServingEngine` swaps the plane in via
its ``mesh=``/``parallelism=`` knobs; admission
(:class:`~bigdl_tpu.serving.admission.AdmissionController`) and the
:class:`~bigdl_tpu.serving.prefix_cache.PrefixCache` are UNCHANGED —
their output rows route to the owning shard through the pool's
mesh-aware scatter.

    from bigdl_tpu.serving.sharded import ShardedEngine, emulate_cpu_devices

    emulate_cpu_devices(8)               # CPU box: 8 virtual devices
    eng = ShardedEngine(lm, parallelism={"data": 4, "model": 2},
                        n_slots=8)
    rid = eng.submit([3, 7, 2], max_new_tokens=32)
    outs = eng.drain()                   # token-identical to unsharded
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.serving.kv_pool import KVPool

#: Axis names of every mesh this plane builds: requests shard over
#: ``data`` (slot rows), weights over ``model`` (heads / MLP hidden).
DATA_AXIS = "data"
MODEL_AXIS = "model"


def emulate_cpu_devices(n: int = 8) -> int:
    """Make this host expose ``n`` virtual CPU devices (the
    distributed-in-one-process pattern the test suite uses): sets
    ``XLA_FLAGS=--xla_force_host_platform_device_count=n`` and pins the
    platform to CPU. Must run BEFORE jax initializes its backend — if
    the backend is already up with fewer devices, raises with the
    recipe (re-exec with the flag in the environment). Returns the
    device count. No-op when enough devices already exist."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    n_dev = jax.device_count()           # initializes the backend
    if n_dev < n:
        raise RuntimeError(
            f"only {n_dev} device(s) visible but {n} requested — the "
            "jax backend initialized before emulate_cpu_devices() could "
            "set XLA_FLAGS. Set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n} in the "
            "environment (before python starts) and retry.")
    return n_dev


def make_mesh(data: int = 1, model: int = 1, devices=None):
    """A ``jax.sharding.Mesh`` of shape ``(data, model)`` with the
    plane's canonical axis names, built from ``devices`` (default: all
    of ``jax.devices()``, first ``data*model`` taken). Raises with the
    CPU-emulation recipe when the host has too few devices."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if data < 1 or model < 1:
        raise ValueError(f"axis sizes must be >= 1, got data={data} "
                         f"model={model}")
    devs = list(devices) if devices is not None else list(jax.devices())
    need = data * model
    if len(devs) < need:
        raise ValueError(
            f"mesh ({data} data x {model} model) needs {need} devices, "
            f"host has {len(devs)} — on a CPU box call "
            f"emulate_cpu_devices({need}) before any jax computation "
            "(or set XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{need})")
    return Mesh(np.asarray(devs[:need]).reshape(data, model),
                (DATA_AXIS, MODEL_AXIS))


def _axis_size(mesh, name: str) -> int:
    """Size of a mesh axis by name, 1 when the mesh lacks the axis
    (``Mesh.shape`` is a name→size mapping on every jax this repo
    supports)."""
    return int(dict(mesh.shape).get(name, 1))


def named_sharding(mesh, spec):
    """``NamedSharding(mesh, spec)`` with the spec NORMALIZED the way
    jit reports output shardings: axes of size 1 drop to ``None`` and
    trailing ``None`` dims are stripped. Placement must use the same
    spelling the step's outputs will carry — ``P('data')`` over a
    size-1 data axis hashes differently from ``P()``, and one mismatch
    makes every engine step recompile."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sizes = dict(mesh.shape)
    ent = [None if (isinstance(e, str) and sizes.get(e, 1) == 1) else e
           for e in tuple(spec)]
    while ent and ent[-1] is None:
        ent.pop()
    return NamedSharding(mesh, P(*ent))


def _sharding_tree(mesh, specs):
    """Mirror a nested-dict PartitionSpec tree as (normalized)
    NamedShardings (a hand-rolled recursion: PartitionSpec subclasses
    tuple on older jax, so tree_map would flatten INTO the specs)."""
    if isinstance(specs, dict):
        return {k: _sharding_tree(mesh, v) for k, v in specs.items()}
    return named_sharding(mesh, specs)


class ShardedKVPool(KVPool):
    """A :class:`KVPool` whose pooled carry lives sharded on a mesh.

    Slot rows shard over the mesh's data axis in CONTIGUOUS blocks —
    device ``d`` owns slots ``d*rows_per_shard ..
    (d+1)*rows_per_shard - 1`` (:meth:`slot_shard` is the
    slot → (shard, local row) mapping); per-layer K/V additionally
    shard their head axis over the model axis when ``carry_specs`` says
    so. Two behavioral deltas from the base pool:

    * **balanced allocation** — :meth:`alloc` pops a free slot from the
      LEAST-LOADED shard (ties → lowest shard id, LIFO within a shard)
      instead of global LIFO, so admissions spread across devices and
      no shard hoards active rows while others idle (the
      ``serving/shard_imbalance`` metric watches this);
    * **mesh-pinned scatter** — the donated admission scatter compiles
      with explicit output shardings, so every ``write_prefill`` keeps
      the pool's placement bit-stable (a drifting spec spelling would
      silently double-compile the decode program).

    Slot ids, invariants, and every public method are unchanged —
    admission/eviction code cannot tell the pools apart (that is the
    point: the AdmissionController routes rows to the owning shard
    without knowing shards exist).
    """

    def __init__(self, init_carry, n_slots: int, mesh, carry_specs: Dict,
                 data_axis: str = DATA_AXIS,
                 kv_dtype: Optional[str] = None) -> None:
        import jax

        n_shards = _axis_size(mesh, data_axis)
        if n_slots % n_shards:
            raise ValueError(
                f"n_slots {n_slots} not divisible by the data-axis size "
                f"{n_shards} — every shard must own the same number of "
                "decode rows (one program shape)")
        self.mesh = mesh
        self.data_axis = data_axis
        self._shardings = {k: named_sharding(mesh, spec)
                           for k, spec in carry_specs.items()}
        super().__init__(init_carry, n_slots, kv_dtype=kv_dtype)
        self.n_shards = n_shards
        self.rows_per_shard = self.n_slots // n_shards
        # shard the freshly-built carry (init_carry returns host-fresh
        # leaves; one device_put per leaf pins the layout the step's
        # out_specs will preserve forever after)
        self.carry = {k: jax.device_put(v, self._shardings[k])
                      for k, v in self.carry.items()}
        # per-shard LIFO free lists, mirroring the base free list
        self._free_by_shard: List[List[int]] = [
            list(range((s + 1) * self.rows_per_shard - 1,
                       s * self.rows_per_shard - 1, -1))
            for s in range(n_shards)]

    def _make_scatter(self):
        import jax

        return jax.jit(self._scatter_impl, donate_argnums=(0,),
                       out_shardings=self._shardings)

    def _make_free_reset(self):
        import jax

        # pin the reset outputs to the carry's placements — a follower
        # sharding with a drifted spelling would double-compile the one
        # decode program (the PR-4 lesson)
        return jax.jit(self._free_reset_impl, donate_argnums=(0,),
                       out_shardings={k: self._shardings[k]
                                      for k in self._reset_keys})

    # -- draft carry (speculative decoding) --------------------------------

    def _draft_shardings(self, specs):
        if specs is None:
            raise ValueError(
                "a sharded pool needs the draft carry's PartitionSpecs "
                "(ShardPlane.draft_carry_specs) — an unpinned draft "
                "placement would drift from the step outputs and "
                "double-compile")
        return {k: named_sharding(self.mesh, s) for k, s in specs.items()}

    def _place_draft(self, carry, specs):
        import jax

        sh = self._draft_shardings(specs)
        return {k: jax.device_put(v, sh[k]) for k, v in carry.items()}

    def _make_draft_scatter(self, specs):
        import jax

        return jax.jit(self._scatter_impl, donate_argnums=(0,),
                       out_shardings=self._draft_shardings(specs))

    def _make_draft_reset(self, specs):
        import jax

        sh = self._draft_shardings(specs)
        return jax.jit(self._free_reset_impl, donate_argnums=(0,),
                       out_shardings={"pos": sh["pos"]})

    # -- slot → shard routing ---------------------------------------------

    def slot_shard(self, slot: int) -> Tuple[int, int]:
        """(owning shard, row within that shard) for a slot id — the
        contiguous-block layout of the data-axis sharding."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} outside 0..{self.n_slots - 1}")
        return slot // self.rows_per_shard, slot % self.rows_per_shard

    def used_per_shard(self) -> List[int]:
        return [self.rows_per_shard - len(f) for f in self._free_by_shard]

    # -- balanced allocator ------------------------------------------------

    def alloc(self) -> Optional[int]:
        """A free slot from the least-loaded shard (None when full)."""
        best, best_used = None, None
        for s, free in enumerate(self._free_by_shard):
            if not free:
                continue
            used = self.rows_per_shard - len(free)
            if best_used is None or used < best_used:
                best, best_used = s, used
        if best is None:
            return None
        slot = self._free_by_shard[best].pop()
        self._free.remove(slot)
        self._in_use.add(slot)
        return slot

    def free(self, slot: int) -> None:
        super().free(slot)
        self._free_by_shard[self.slot_shard(slot)[0]].append(slot)


class ShardPlane:
    """The engine's view of its mesh: axis sizes, row placement, pool
    and step construction. Built by
    :class:`~bigdl_tpu.serving.engine.ServingEngine` when its
    ``mesh=``/``parallelism=`` knob is set; owns nothing stateful
    beyond the mesh itself.

    ``parallelism`` is a ``{"data": N, "model": M}`` dict (either key
    optional) used to build a mesh from the host's devices when no
    explicit ``mesh`` is given. An explicit mesh must carry BOTH of
    this plane's axis names (``data`` and ``model`` — a size-1 axis is
    fine, :func:`make_mesh` always produces both): the step programs'
    partition specs name both axes, so a mesh missing one would only
    fail later, at the first decode step, with an opaque KeyError."""

    def __init__(self, mesh=None, parallelism: Optional[Dict] = None,
                 data_axis: str = DATA_AXIS,
                 model_axis: str = MODEL_AXIS) -> None:
        if mesh is None:
            parallelism = dict(parallelism or {})
            unknown = set(parallelism) - {"data", "model"}
            if unknown:
                raise ValueError(
                    f"unknown parallelism axes {sorted(unknown)} "
                    "(expected 'data' and/or 'model')")
            mesh = make_mesh(data=int(parallelism.get("data", 1)),
                             model=int(parallelism.get("model", 1)))
        missing = [a for a in (data_axis, model_axis)
                   if a not in mesh.axis_names]
        if missing:
            raise ValueError(
                f"mesh axes {mesh.axis_names} lack {missing} — the "
                f"serving plane's partition specs name both "
                f"'{data_axis}' and '{model_axis}' (size 1 is fine; "
                "build the mesh with serving.sharded.make_mesh)")
        self.mesh = mesh
        self.data_axis = data_axis
        self.model_axis = model_axis
        self.data_shards = _axis_size(mesh, data_axis)
        self.model_shards = _axis_size(mesh, model_axis)
        if self.data_shards == 1 and self.model_shards == 1:
            raise ValueError(
                "a 1x1 mesh is the unsharded engine — drop the "
                "mesh/parallelism knob instead")
        from jax.sharding import PartitionSpec as P

        # leading-axis row sharding for tokens/active/knob arrays
        # (normalized: the spec spelling must match the step's output
        # specs or every call double-compiles)
        self.row_sharding = named_sharding(self.mesh, P(data_axis))

    @property
    def tensor_parallel(self) -> bool:
        return self.model_shards > 1

    def place_rows(self, x):
        """Commit a per-slot array (leading slot axis) to the mesh."""
        import jax

        return jax.device_put(x, self.row_sharding)

    def place_params(self, model, params):
        """Commit a serving params tree to the mesh: Megatron-sharded
        over the model axis for tensor-parallel planes, left on the
        default device (GSPMD replicates it) otherwise. ``model`` is
        the architecture the spec tree mirrors; ``params`` the
        (pre-cast) tree to place."""
        import jax

        if not self.tensor_parallel:
            return jax.device_put(params)
        from bigdl_tpu.models.transformer import tp_param_specs

        return jax.device_put(
            params, _sharding_tree(self.mesh,
                                   tp_param_specs(model, self.model_axis)))

    def carry_specs(self, model, sampling: bool = True,
                    kv_quant: bool = False) -> Dict:
        from bigdl_tpu.models.transformer import serving_carry_specs

        return serving_carry_specs(
            model, sampling=sampling, data_axis=self.data_axis,
            model_axis=self.model_axis if self.tensor_parallel else None,
            kv_quant=kv_quant)

    def draft_carry_specs(self, draft_model) -> Dict:
        """PartitionSpec tree for a speculative DRAFT carry: slot rows
        shard over the data axis like the target's, but K/V heads stay
        UNSHARDED even on tensor-parallel meshes — the draft's weights
        are replicated (a model small enough to draft with is small
        enough to replicate), so its cache heads are whole per chip."""
        from bigdl_tpu.models.transformer import serving_carry_specs

        return serving_carry_specs(draft_model, sampling=False,
                                   data_axis=self.data_axis,
                                   model_axis=None)

    def make_pool(self, model, pool_init, n_slots: int,
                  sampling: bool = True, kv_quant: bool = False,
                  kv_dtype: Optional[str] = None) -> ShardedKVPool:
        return ShardedKVPool(pool_init, n_slots, self.mesh,
                             self.carry_specs(model, sampling=sampling,
                                              kv_quant=kv_quant),
                             data_axis=self.data_axis, kv_dtype=kv_dtype)


class ShardedEngine:
    """Convenience front end: a
    :class:`~bigdl_tpu.serving.engine.ServingEngine` with the sharded
    plane on by default — ``parallelism`` defaults to all visible
    devices data-parallel (``{"data": jax.device_count()}``; on a
    single-device host this degrades to the plain unsharded engine).
    Every other knob passes through. Prefer the plain engine's
    ``mesh=``/``parallelism=`` arguments when you already hold an
    engine construction site; this class exists so one import gives a
    whole-fleet engine:

        eng = ShardedEngine(lm, n_slots=8)                 # all devices
        eng = ShardedEngine(lm, parallelism={"data": 2, "model": 4})
    """

    def __new__(cls, model, mesh=None, parallelism=None, **kwargs):
        import jax

        from bigdl_tpu.serving.engine import ServingEngine

        if mesh is None and parallelism is None:
            n = jax.device_count()
            # one visible device = nothing to shard over: degrade to the
            # plain engine rather than erroring about a knob the caller
            # never set (the ShardPlane 1x1 guard targets explicit use)
            parallelism = {"data": n} if n > 1 else None
        return ServingEngine(model, mesh=mesh, parallelism=parallelism,
                             **kwargs)
