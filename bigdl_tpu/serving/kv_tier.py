"""Host-RAM KV tier: spill cold rows and prefix entries below HBM.

BigDL leaned on Spark's BlockManager as the storage tier below executor
heaps; the serving plane needs the same tier below HBM. The pooled KV
cache holds ``n_slots`` rows of device state, and everything that falls
out of it today is either replayed (re-prefill of ``prompt + output``)
or pinned as a per-request host blob: the preemption stash
(``Request.resume_carry``), the disaggregated front end's last-handoff
stash (``_stash``), and the failover re-route copy were three spellings
of the same bytes. :class:`TieredKVStore` is the one subsystem behind
all of them: a budgeted host tier over any
:class:`~bigdl_tpu.parallel.block_store.BlockStore` (in-process dict by
default — same-host DRAM; ``FsBlockStore``/``CoordServiceBlockStore``
for cross-process deployments) holding two entry kinds under ONE
global LRU byte budget:

* **rows** — full ``KVPool.row_state()`` payloads packed through the
  disagg wire codec (:func:`~bigdl_tpu.serving.disagg.pack_payload`:
  JSON header + self-describing array leaves, bf16/int8 bitwise), keyed
  by request id. Spilled at preemption, handoff staging, and transfer
  requeue; fetched — currency-checked against the request's emitted
  stream — at readmission, where ``restore_row()`` makes the resume
  byte-exact. A fetched row entry is KEPT (LRU-touched): it doubles as
  the failover stash until the request finishes, when every terminal
  disposition drops it (no lingering blobs — the old stash-hygiene
  sweep's job, done eagerly);
* **prefixes** — :class:`~bigdl_tpu.serving.prefix_cache.PrefixCache`
  carries demoted at HBM-capacity eviction instead of deleted, keyed by
  (adapter id, token path) so tenant namespaces never cross. A later
  lookup PROMOTES the best stored prefix back into the radix tree as an
  ordinary (possibly truncated) hit — warm-prefix capacity is bounded
  by ``host_budget_bytes``, not by the cache's HBM entry count.

The byte budget is enforced by LRU eviction over BOTH kinds (the entry
just written is immune for its own pass, mirroring the prefix cache's
``protect`` rule). Evicting a row entry is loss-free by construction:
the readmission fetch misses and the row replays through prefill —
the tier only ever upgrades the replay baseline, never replaces it.
Meta-only (replay-form) blobs ride the row API too so the failover and
cancel-sweep bookkeeping stay uniform, but count no spill bytes.

Codec discipline (analyzer rule SRV207): row state enters a block
store ONLY as ``pack_payload`` bytes and leaves ONLY through
``unpack_payload``/``payload_header`` — a raw ``row_state`` dict
written to a store, or a ``row_state`` read of an already-freed slot,
is machine-caught. Fetches can be BATCHED off the step path
(:meth:`TieredKVStore.prefetch` decodes the next admission wave's
blobs in one pass), so the decode gap never absorbs a payload decode.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

from bigdl_tpu.parallel.block_store import BlockStore, MemBlockStore
from bigdl_tpu.serving.faults import default_clock


class TieredKVStore:
    """One host spill tier shared by an engine (or a whole
    disaggregated plane): row payloads + demoted prefix carries under
    a global LRU byte budget (module docstring).

    ``store`` is any :class:`BlockStore` (default an in-process
    :class:`MemBlockStore`); ``host_budget_bytes`` bounds the resident
    bytes (None = unbounded — the legacy stash semantics). The tier
    keeps its own key index (block stores expose no iteration), so a
    shared Fs/coord store still needs one tier OBJECT per serving
    plane — the index, like the scheduler, is per-plane state.
    ``clock`` times fetches (the engine attaches its own — a
    VirtualClock plane stays sleep-free)."""

    def __init__(self, store: Optional[BlockStore] = None,
                 host_budget_bytes: Optional[int] = None,
                 clock=None) -> None:
        if host_budget_bytes is not None and host_budget_bytes <= 0:
            raise ValueError(
                f"host_budget_bytes must be positive or None, got "
                f"{host_budget_bytes}")
        self.store = store if store is not None else MemBlockStore()
        self.host_budget_bytes = host_budget_bytes
        self._clock = clock if clock is not None else default_clock
        # ONE LRU over every resident entry (rows AND prefixes):
        # key -> nbytes, oldest first; doubles as the key index
        self._lru: "OrderedDict[str, int]" = OrderedDict()
        self._bytes = 0
        # prefix index: adapter id -> {token tuple -> store key}
        self._prefixes: Dict[int, Dict[Tuple[int, ...], str]] = {}
        self._pf_seq = 0
        # batched-fetch staging: req_id -> decoded payload
        # (prefetch() fills it off the step path; fetch_row drains it)
        self._hot: Dict[int, dict] = {}
        self._metrics = None
        self.spills = 0
        self.fetches = 0
        self.evictions = 0
        self.spill_bytes = 0
        self.fetch_bytes = 0

    # -- metrics plumbing --------------------------------------------------

    def attach_metrics(self, metrics, clock=None) -> None:
        """Bind ONE metrics sink (first caller wins — a disaggregated
        plane attaches the front end's metrics before its pool engines
        construct, so spills/fetches land in one summary)."""
        if self._metrics is None and metrics is not None:
            self._metrics = metrics
            if clock is not None:
                self._clock = clock
            self._note_bytes()

    def _note_bytes(self) -> None:
        if self._metrics is not None:
            self._metrics.on_tier_bytes(self._bytes)

    def _spilled(self, n_bytes: int) -> None:
        self.spills += 1
        self.spill_bytes += n_bytes
        if self._metrics is not None:
            self._metrics.on_spill(n_bytes)

    def _fetched(self, n_bytes: int, seconds: float) -> None:
        self.fetches += 1
        self.fetch_bytes += n_bytes
        if self._metrics is not None:
            self._metrics.on_fetch(n_bytes, seconds)

    # -- budget / LRU core -------------------------------------------------

    @staticmethod
    def _row_key(req_id: int) -> str:
        return f"tier/row/{int(req_id)}"

    def _put_blob(self, key: str, blob: bytes, count: bool) -> None:
        old = self._lru.pop(key, None)
        if old is not None:
            self._bytes -= old
        self.store.put(key, blob)
        self._lru[key] = len(blob)
        self._bytes += len(blob)
        if count:
            self._spilled(len(blob))
        self._evict_over_budget(protect=key)
        self._note_bytes()

    def _remove(self, key: str) -> bool:
        n = self._lru.pop(key, None)
        if n is None:
            return False
        self._bytes -= n
        self.store.delete(key)
        if key.startswith("tier/prefix/"):
            for idx in self._prefixes.values():
                for toks, k in list(idx.items()):
                    if k == key:
                        del idx[toks]
        self._note_bytes()
        return True

    def _evict_over_budget(self, protect: Optional[str] = None) -> None:
        # the entry just paid for is immune for its own pass (it sits
        # newest in the LRU, so it is only ever the scan head when it
        # is the LAST entry — a single over-budget blob stays resident
        # rather than thrashing, the prefix cache's overflow rule)
        if self.host_budget_bytes is None:
            return
        while self._bytes > self.host_budget_bytes and self._lru:
            victim = next(iter(self._lru))
            if victim == protect:
                return
            self._remove(victim)
            self.evictions += 1
            if self._metrics is not None:
                self._metrics.on_tier_evict()

    # -- row entries (preemption / handoff / failover stash) ---------------

    def put_row(self, req, payload: dict) -> None:
        """Spill one live row: pack its ``row_state`` payload through
        the wire codec (THE sanctioned serialization — SRV207) under
        the request's id. Overwrites any older copy — a re-preempted
        row's fresher bytes supersede."""
        from bigdl_tpu.serving.disagg import pack_payload, request_meta

        self._hot.pop(int(req.req_id), None)
        blob = pack_payload(request_meta(req), payload)
        self._put_blob(self._row_key(req.req_id), blob, count=True)

    def put_packed(self, blob: bytes, req_id: Optional[int] = None) -> None:
        """Stage an ALREADY-packed handoff blob (the disagg front end's
        confirmed-delivery stash, a decode worker's ingest, a failover
        replay form). Meta-only blobs are tracked for bookkeeping but
        count no spill — they carry no row bytes."""
        from bigdl_tpu.serving.disagg import payload_header

        head = payload_header(blob)
        if req_id is None:
            req_id = int(head["request"]["req_id"])
        self._hot.pop(int(req_id), None)
        self._put_blob(self._row_key(req_id), blob,
                       count=head["carry_keys"] is not None)

    def has_row(self, req_id: int) -> bool:
        return self._row_key(req_id) in self._lru

    def get_blob(self, req_id: int) -> Optional[bytes]:
        """The raw packed blob for a request (or None) — the failover
        path's read: it needs the bytes as-is to re-route, and does its
        own header currency check. LRU-touches the entry."""
        key = self._row_key(req_id)
        if key not in self._lru:
            return None
        blob = self.store.try_get(key)
        if blob is None:                  # backing store lost it
            self._remove(key)
            return None
        self._lru.move_to_end(key)
        return blob

    def pop_blob(self, req_id: int) -> Optional[bytes]:
        """:meth:`get_blob` + drop — the cancel sweep's consume."""
        blob = self.get_blob(req_id)
        if blob is not None:
            self.drop_row(req_id)
        return blob

    def header(self, req_id: int) -> Optional[Dict]:
        """Header-only cheap read of a stored row blob (no array
        decode), or None."""
        from bigdl_tpu.serving.disagg import payload_header

        blob = self.get_blob(req_id)
        return None if blob is None else payload_header(blob)

    def drop_row(self, req_id: int) -> None:
        """Forget a request's row entry (terminal dispositions, fault
        recovery — a suspect carry is never trusted). Idempotent."""
        self._hot.pop(int(req_id), None)
        self._remove(self._row_key(req_id))

    def _load_row(self, req) -> Optional[dict]:
        """Decode one stored row payload for ``req`` if the copy is
        CURRENT (its header's emitted stream equals the request's —
        a row that decoded past its spill must replay instead; the
        stale entry drops). Meta-only replay forms also load as None:
        there is no state to restore."""
        from bigdl_tpu.serving.disagg import payload_header, unpack_payload

        t0 = self._clock()
        key = self._row_key(req.req_id)
        if key not in self._lru:
            return None
        blob = self.store.try_get(key)
        if blob is None:
            self._remove(key)
            return None
        head = payload_header(blob)
        if head["carry_keys"] is None or \
                head["request"]["output"] != [int(t) for t in req.output]:
            self._remove(key)
            return None
        _, payload = unpack_payload(blob)
        # KEEP the entry, freshly touched: until the request finishes
        # it remains the failover/currency copy (drop-at-finish is the
        # other half of this contract)
        self._lru.move_to_end(key)
        self._fetched(len(blob), self._clock() - t0)
        return payload

    def fetch_row(self, req) -> Optional[dict]:
        """The readmission fetch: the request's spilled payload with
        numpy leaves (what ``restore_row`` accepts), or None when no
        current copy exists (budget-evicted, stale, or never spilled)
        and the row must replay via prefill."""
        payload = self._hot.pop(int(req.req_id), None)
        if payload is not None:
            return payload
        return self._load_row(req)

    def prefetch(self, reqs: Iterable) -> int:
        """Decode the blobs for an upcoming admission wave in one pass
        OFF the step path, so each :meth:`fetch_row` inside the
        admission loop is a dict pop, not a payload decode. Returns
        how many rows were staged."""
        n = 0
        for req in reqs:
            rid = int(req.req_id)
            if rid in self._hot or req.resume_carry is not None:
                continue
            payload = self._load_row(req)
            if payload is not None:
                self._hot[rid] = payload
                n += 1
        return n

    # -- prefix entries (PrefixCache demote/promote) ------------------------

    @staticmethod
    def _common(a: Tuple[int, ...], b: Tuple[int, ...]) -> int:
        n = min(len(a), len(b))
        for i in range(n):
            if a[i] != b[i]:
                return i
        return n

    def demote_prefix(self, tokens, carry, adapter_id: int = 0) -> None:
        """Store an HBM-evicted prefix carry instead of deleting it:
        packed through the same wire codec (a synthetic header — no
        request rides it), keyed by (adapter id, token path) so tenant
        namespaces never cross. Only refs==0 entries ever reach here
        (the cache's eviction rule), so no lease dangles."""
        from bigdl_tpu.serving.disagg import pack_payload

        tokens = tuple(int(t) for t in tokens)
        if not tokens or carry is None:
            return
        aid = int(adapter_id)
        idx = self._prefixes.setdefault(aid, {})
        key = idx.get(tokens)
        if key is None:
            self._pf_seq += 1
            key = f"tier/prefix/{aid}/{self._pf_seq}"
        meta = {"kind": "prefix", "adapter": aid, "tokens": list(tokens)}
        blob = pack_payload(meta, {"carry": carry, "draft": None,
                                   "chunk_done": 0, "chunk_target": 0,
                                   "adapter": aid})
        idx[tokens] = key
        self._put_blob(key, blob, count=True)

    def promote_prefix(self, tokens, matched: int,
                       adapter_id: int = 0) -> Optional[Tuple[Tuple[int, ...],
                                                              dict]]:
        """The lookup-side promotion: the stored prefix (same adapter)
        sharing the LONGEST common prefix with ``tokens`` — strictly
        longer than the ``matched`` tokens HBM already serves — decoded
        and returned as ``(its token path, device carry)`` for the
        cache to re-insert (causal K/V makes a longer stored entry
        serve any shorter shared prefix as a truncated hit, exactly
        the radix walk's rule). The entry leaves the tier: it lives in
        HBM again. None when nothing stored beats ``matched``."""
        import jax.numpy as jnp

        from bigdl_tpu.serving.disagg import unpack_payload

        idx = self._prefixes.get(int(adapter_id))
        if not idx:
            return None
        tokens = tuple(int(t) for t in tokens)
        best, best_use = None, int(matched)
        for p in idx:
            use = self._common(p, tokens)
            if use > best_use:
                best, best_use = p, use
        if best is None:
            return None
        t0 = self._clock()
        key = idx[best]
        blob = self.store.try_get(key)
        if blob is None:                  # backing store lost it
            self._remove(key)
            return None
        _, decoded = unpack_payload(blob)
        self._remove(key)                 # promotion consumes the entry
        self._fetched(len(blob), self._clock() - t0)
        carry = {k: jnp.asarray(v) for k, v in decoded["carry"].items()}
        return best, carry

    # -- introspection -----------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        return self._bytes

    @property
    def entries(self) -> int:
        return len(self._lru)

    @property
    def prefix_entries(self) -> int:
        return sum(len(v) for v in self._prefixes.values())

    @property
    def row_entries(self) -> int:
        return self.entries - self.prefix_entries

    def stats(self) -> Dict[str, float]:
        return {"entries": float(self.entries),
                "rows": float(self.row_entries),
                "prefixes": float(self.prefix_entries),
                "bytes": float(self._bytes),
                "spills": float(self.spills),
                "fetches": float(self.fetches),
                "evictions": float(self.evictions),
                "spill_bytes": float(self.spill_bytes),
                "fetch_bytes": float(self.fetch_bytes)}
