"""bigdl_tpu.api — pyspark-BigDL-shaped import surface.

Reference role (UNVERIFIED, SURVEY.md §0): the ``pyspark/bigdl`` Python
package (``bigdl.nn.layer``, ``bigdl.optim.optimizer``, ``bigdl.util.common``)
whose names mirror the Scala API 1:1 over py4j (SURVEY.md §2.7 Python
bridge).

Here the bridge vanishes — this package is a NAMESPACE SHIM so reference
user scripts port with an import swap:

    from bigdl.nn.layer import Linear, Sequential          # reference
    from bigdl_tpu.api.nn.layer import Linear, Sequential  # this framework

Everything resolves to the same TPU-native classes as ``bigdl_tpu.nn``.
"""
