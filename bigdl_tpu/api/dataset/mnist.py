"""``bigdl.dataset.mnist`` equivalent (``read_data_sets``)."""

from bigdl_tpu.dataset.mnist import read_data_sets  # noqa: F401
