"""``bigdl.dataset.news20`` equivalent (``get_news20``/``get_glove_w2v``)."""

from bigdl_tpu.dataset.news20 import get_news20, get_glove_w2v  # noqa: F401
