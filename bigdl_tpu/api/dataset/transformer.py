"""``bigdl.dataset.transformer`` equivalent (normalizer helpers)."""

import numpy as np


def normalizer(data, mean: float, std: float):
    """Elementwise (x - mean) / std (pyspark ``normalizer``)."""
    return (np.asarray(data) - mean) / std
