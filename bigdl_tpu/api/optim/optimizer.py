"""``bigdl.optim.optimizer`` equivalent: Optimizer + OptimMethods + the
pyspark trigger-constructor names (``MaxEpoch(5)`` etc. construct Triggers)."""

from bigdl_tpu.optim import (  # noqa: F401
    Adadelta, Adagrad, Adam, Adamax, DistriOptimizer, Evaluator, Ftrl, LBFGS,
    LocalOptimizer, Loss, MAE, Metrics, OptimMethod, Optimizer, Predictor,
    RMSprop, SGD, Top1Accuracy, Top5Accuracy, Trigger, ValidationMethod,
)
from bigdl_tpu.visualization import TrainSummary, ValidationSummary  # noqa: F401


def MaxEpoch(max_epoch: int) -> Trigger:
    return Trigger.max_epoch(max_epoch)


def MaxIteration(max_iteration: int) -> Trigger:
    return Trigger.max_iteration(max_iteration)


def EveryEpoch() -> Trigger:
    return Trigger.every_epoch()


def SeveralIteration(interval: int) -> Trigger:
    return Trigger.several_iteration(interval)


def MinLoss(min_loss: float) -> Trigger:
    return Trigger.min_loss(min_loss)


def MaxScore(max_score: float) -> Trigger:
    return Trigger.max_score(max_score)
