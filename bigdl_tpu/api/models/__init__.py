"""``bigdl.models`` equivalent (pyspark zoo namespace)."""
