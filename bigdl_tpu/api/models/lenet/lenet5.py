"""``bigdl.models.lenet.lenet5`` equivalent — ``build_model(class_num)``."""

from bigdl_tpu.models.lenet import LeNet5


def build_model(class_num: int):
    return LeNet5(class_num)
