"""``bigdl.models.textclassifier`` equivalent — ``build_model`` plus the
news20/GloVe helpers the pyspark script imports."""

from bigdl_tpu.dataset.news20 import get_glove_w2v, get_news20  # noqa: F401
from bigdl_tpu.models.textclassifier import TextClassifier


def build_model(class_num: int, token_length: int = 200,
                sequence_len: int = 500, encoder: str = "lstm",
                encoder_output_dim: int = 128):
    """pyspark signature (token_length = embedding dim); the lstm/gru
    encoder choice maps onto the BiRecurrent LSTM classifier front."""
    return TextClassifier(class_num, embedding_dim=token_length,
                          hidden_size=encoder_output_dim,
                          embedding_input=True)
