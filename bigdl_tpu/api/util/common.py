"""``bigdl.util.common`` equivalent.

The py4j plumbing (``callBigDlFunc``, ``JavaCreator``) has no meaning here;
what remains is the user-facing surface: ``init_engine``, ``Sample``, and
``JTensor`` (a plain ndarray wrapper kept for source compatibility)."""

from typing import Any

import numpy as np

from bigdl_tpu.dataset.sample import Sample as _Sample
from bigdl_tpu.utils.engine import Engine


def init_engine(*_args, **_kw) -> None:
    """Reference ``init_engine()``: initialize the runtime singleton."""
    Engine.init()


class JTensor:
    """pyspark's ndarray carrier; ``from_ndarray``/``to_ndarray`` kept."""

    def __init__(self, storage, shape, bigdl_type: str = "float") -> None:
        self.storage = np.asarray(storage, np.float32)
        self.shape = tuple(shape)

    @classmethod
    def from_ndarray(cls, a) -> "JTensor":
        a = np.asarray(a)
        return cls(a.reshape(-1), a.shape)

    def to_ndarray(self) -> np.ndarray:
        return self.storage.reshape(self.shape)


class Sample(_Sample):
    """pyspark Sample with its ``from_ndarray`` constructor."""

    @classmethod
    def from_ndarray(cls, features: Any, labels: Any) -> "Sample":
        return cls(features, labels)
