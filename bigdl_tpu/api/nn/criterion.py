"""``bigdl.nn.criterion`` equivalent."""

from bigdl_tpu.nn import (  # noqa: F401
    AbsCriterion, AbstractCriterion, BCECriterion, ClassNLLCriterion,
    CosineDistanceCriterion, CosineEmbeddingCriterion, CrossEntropyCriterion,
    DiceCoefficientCriterion, DistKLDivCriterion, GaussianCriterion,
    HingeEmbeddingCriterion, KLDCriterion, L1Cost, MarginCriterion,
    MarginRankingCriterion, MSECriterion, MultiCriterion,
    MultiLabelMarginCriterion, MultiLabelSoftMarginCriterion,
    MultiMarginCriterion, ParallelCriterion, SmoothL1Criterion,
    SoftmaxWithCriterion, TimeDistributedCriterion,
)

Criterion = AbstractCriterion  # pyspark base-class name
