"""``bigdl.nn.layer`` equivalent: every layer/container under one module,
plus the pyspark-style ``Model`` alias for the functional Graph."""

from bigdl_tpu.nn import *  # noqa: F401,F403
from bigdl_tpu.nn import Graph as Model  # pyspark name for Graph
from bigdl_tpu.nn import AbstractModule as Layer  # pyspark base-class name
