"""SPMD hygiene + serving-contract analyzer — whole-program AST lint.

The serving/optim/parallel planes all rest on invariants XLA never
checks: one compiled program per engine, one spelling per PartitionSpec
axis, every version-moved jax API routed through ``utils/compat.py``,
every serving dispatch routed through ``engine._dispatch``, one closed
schema for the pooled-carry keys and the finish-reason vocabulary.
This package makes those invariants machine-checked — as a CLI
(``python -m bigdl_tpu.analysis``) and as a tier-1 test
(``tests/test_static_analysis.py``).  Per-file rules (SPMD1xx) ride a
single parsed-tree index; cross-module rules (SRV2xx) ride the
ProjectContext fact table (import-graph-qualified class hierarchy,
step-cache bindings, donation call-graph lifting, declared schemas)
plus embedded string-program units.  Pure stdlib ``ast``; never imports jax.  Rule catalog and war
stories: ``docs/analysis.md``.
"""

from bigdl_tpu.analysis.core import (
    DEFAULT_EXCLUDE_DIRS,
    FileContext,
    Finding,
    ProjectContext,
    Rule,
    all_rules,
    analyze_paths,
    analyze_source,
    format_baseline_entry,
    load_baseline,
    prune_baseline_text,
    rule_codes,
    scan,
    split_baselined,
    stale_entries,
)
# importing the rules module populates the registry
from bigdl_tpu.analysis import rules as _rules  # noqa: F401
from bigdl_tpu.analysis.cli import DEFAULT_PATHS, main, to_sarif

__all__ = [
    "DEFAULT_EXCLUDE_DIRS",
    "DEFAULT_PATHS",
    "FileContext",
    "Finding",
    "ProjectContext",
    "Rule",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "format_baseline_entry",
    "load_baseline",
    "main",
    "prune_baseline_text",
    "rule_codes",
    "scan",
    "split_baselined",
    "stale_entries",
    "to_sarif",
]
