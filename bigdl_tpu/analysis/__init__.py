"""SPMD hygiene analyzer — AST lint for recompilation, sharding-spec,
and jax-compat drift.

The serving/optim/parallel planes all rest on invariants XLA never
checks: one compiled program per engine, one spelling per PartitionSpec
axis, every version-moved jax API routed through ``utils/compat.py``.
This package makes those invariants machine-checked — as a CLI
(``python -m bigdl_tpu.analysis``) and as a tier-1 test
(``tests/test_static_analysis.py``).  Pure stdlib ``ast``; never
imports jax.  Rule catalog and war stories: ``docs/analysis.md``.
"""

from bigdl_tpu.analysis.core import (
    DEFAULT_EXCLUDE_DIRS,
    FileContext,
    Finding,
    Rule,
    all_rules,
    analyze_paths,
    analyze_source,
    format_baseline_entry,
    load_baseline,
    rule_codes,
    split_baselined,
)
# importing the rules module populates the registry
from bigdl_tpu.analysis import rules as _rules  # noqa: F401
from bigdl_tpu.analysis.cli import DEFAULT_PATHS, main

__all__ = [
    "DEFAULT_EXCLUDE_DIRS",
    "DEFAULT_PATHS",
    "FileContext",
    "Finding",
    "Rule",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "format_baseline_entry",
    "load_baseline",
    "main",
    "rule_codes",
    "split_baselined",
]
