"""``python -m bigdl_tpu.analysis`` entry point."""

import sys

from bigdl_tpu.analysis import main

sys.exit(main())
