"""The six SPMD hygiene rules.

Every rule here encodes a bug class this repo has actually shipped (see
docs/analysis.md for the war stories):

==========  ==============================================================
SPMD101     compat drift — version-moved jax APIs spelled directly
SPMD102     PartitionSpec spelling drift (the PR-4 double-compile)
SPMD103     recompile hazards in/around jitted programs
SPMD104     donated buffer reused after the donating call
SPMD105     Python control flow on traced values
SPMD106     shard_map specs naming axes the mesh does not have
==========  ==============================================================

All rules are import-resolution based, not textual: ``lax.pvary`` is
flagged under ``from jax import lax`` and not when ``lax`` is someone's
local variable, and docstrings/comments never trigger (the historical
reason the repo could not just grep for these).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from bigdl_tpu.analysis.core import FileContext, Finding, Rule, register

# --------------------------------------------------------------------------
# shared machinery
# --------------------------------------------------------------------------

#: wrappers whose function argument becomes a traced body
_JIT_QUALNAMES = {"jax.jit", "jax.pmap"}
_SHARD_MAP_QUALNAMES = {
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "bigdl_tpu.utils.compat.shard_map",
    "bigdl_tpu.utils.compat.resolve_shard_map",
}
#: control-flow combinators: (qualname -> positions of traced callees)
_COMBINATOR_FN_ARGS = {
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": None,       # every arg from 1 on is a branch
    "jax.lax.associative_scan": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
}

#: attributes of a traced array that are static at trace time — branching
#: or formatting on these is fine
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "weak_type", "itemsize", "nbytes"}
#: calls whose result on a tracer is static / python-level
_STATIC_CALLS = {"len", "isinstance", "callable", "hasattr", "getattr",
                 "type", "id", "repr"}


def _const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """(1, 2) / 1 / [0] as a tuple of ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _const_str_set(node: ast.AST) -> Optional[Set[str]]:
    """Set of string constants in a str / tuple/list-of-str literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
            else:
                return None
        return out
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in
             list(getattr(a, "posonlyargs", [])) + list(a.args)
             + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class _TracedFn:
    """A function object the analyzer believes gets traced, plus which of
    its parameters are dynamic (non-static) there."""

    def __init__(self, fn: ast.AST, via: str,
                 static_argnums: Tuple[int, ...] = (),
                 static_argnames: Sequence[str] = ()) -> None:
        self.fn = fn                      # FunctionDef / Lambda
        self.via = via                    # "jax.jit", "compat.shard_map", ...
        names = _param_names(fn)
        drop = set(static_argnames)
        for i in static_argnums:
            if 0 <= i < len(names):
                drop.add(names[i])
        self.dynamic_params = {n for n in names if n not in drop
                               and n != "self"}


def _local_defs(ctx: FileContext) -> Dict[str, List[ast.AST]]:
    """name -> FunctionDefs in the file (all scopes), in source order."""
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def _resolve_fn_arg(ctx: FileContext, node: ast.AST,
                    defs: Dict[str, List[ast.AST]],
                    before_line: int) -> Optional[ast.AST]:
    """The function object an argument refers to: a Lambda/def literal,
    or the nearest preceding local def with that name."""
    if isinstance(node, ast.Lambda):
        return node
    if isinstance(node, ast.Name) and node.id in defs:
        cands = [d for d in defs[node.id] if d.lineno <= before_line]
        return cands[-1] if cands else defs[node.id][0]
    return None


def _is_partial(ctx: FileContext, call: ast.Call) -> bool:
    q = ctx.qualname(call.func)
    return q in {"functools.partial", "partial"} or \
        (isinstance(call.func, ast.Name) and call.func.id == "partial")


def _jit_info(ctx: FileContext, value: ast.AST,
              ) -> Optional[Tuple[ast.Call, Tuple[int, ...], List[str]]]:
    """If ``value`` is a (possibly partial-wrapped) ``jax.jit(...)`` call,
    -> (the jit Call, static_argnums, static_argnames)."""
    if not isinstance(value, ast.Call):
        return None
    call = value
    q = ctx.qualname(call.func)
    if q in {"functools.partial", "partial"} and call.args:
        inner_q = ctx.qualname(call.args[0])
        if inner_q in _JIT_QUALNAMES:
            q = inner_q
        else:
            return None
    if q not in _JIT_QUALNAMES:
        return None
    nums = _kwarg(call, "static_argnums")
    names = _kwarg(call, "static_argnames")
    return (call,
            _const_int_tuple(nums) or () if nums is not None else (),
            sorted(_const_str_set(names) or set()) if names is not None
            else [])


def _traced_functions(ctx: FileContext) -> List[_TracedFn]:
    """Every local def/lambda the file hands to jit / shard_map / a lax
    control-flow combinator, plus defs decorated with them."""
    defs = _local_defs(ctx)
    traced: List[_TracedFn] = []
    seen: Set[int] = set()

    def add(fn: Optional[ast.AST], via: str,
            static_argnums: Tuple[int, ...] = (),
            static_argnames: Sequence[str] = ()) -> None:
        if fn is None or id(fn) in seen:
            return
        seen.add(id(fn))
        traced.append(_TracedFn(fn, via, static_argnums, static_argnames))

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            q = ctx.qualname(node.func)
            if q in _JIT_QUALNAMES or q in _SHARD_MAP_QUALNAMES:
                info = _jit_info(ctx, node)
                nums, names = (info[1], info[2]) if info else ((), [])
                if node.args:
                    add(_resolve_fn_arg(ctx, node.args[0], defs,
                                        node.lineno), q or "jit",
                        nums, names)
            elif q in _COMBINATOR_FN_ARGS:
                poss = _COMBINATOR_FN_ARGS[q]
                if poss is None:                       # lax.switch
                    poss = tuple(range(1, len(node.args)))
                for i in poss:
                    if i < len(node.args):
                        add(_resolve_fn_arg(ctx, node.args[i], defs,
                                            node.lineno), q)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    q = ctx.qualname(dec.func)
                    if q in _JIT_QUALNAMES:
                        nums = _kwarg(dec, "static_argnums")
                        names = _kwarg(dec, "static_argnames")
                        add(node, q, _const_int_tuple(nums) or ()
                            if nums is not None else (),
                            sorted(_const_str_set(names) or set())
                            if names is not None else [])
                    elif _is_partial(ctx, dec) and dec.args and \
                            ctx.qualname(dec.args[0]) in _JIT_QUALNAMES:
                        nums = _kwarg(dec, "static_argnums")
                        names = _kwarg(dec, "static_argnames")
                        add(node, "jax.jit", _const_int_tuple(nums) or ()
                            if nums is not None else (),
                            sorted(_const_str_set(names) or set())
                            if names is not None else [])
                else:
                    q = ctx.qualname(dec)
                    if q in _JIT_QUALNAMES:
                        add(node, q)
    return traced


def _dynamic_uses(expr: ast.AST, tainted: Set[str]) -> List[ast.Name]:
    """Name nodes in ``expr`` bound to tainted (traced) values that are
    used *dynamically* — i.e. NOT behind a trace-time-static accessor
    (``x.shape``/``x.ndim``/``x.dtype``..., ``len(x)``, ``isinstance``,
    ``x is None``).  These are the uses that concretize a tracer."""
    offending: List[ast.Name] = []

    def visit(node: ast.AST, static: bool) -> None:
        if isinstance(node, ast.Name):
            if node.id in tainted and not static:
                offending.append(node)
            return
        if isinstance(node, ast.Attribute):
            visit(node.value, static or node.attr in _STATIC_ATTRS)
            return
        if isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) else None
            inner_static = static or fname in _STATIC_CALLS
            for child in list(node.args) + [kw.value for kw in node.keywords]:
                visit(child, inner_static)
            if not isinstance(node.func, ast.Name):
                visit(node.func, static)
            return
        if isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            for child in [node.left] + list(node.comparators):
                visit(child, True)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, static)

    visit(expr, False)
    return offending


# --------------------------------------------------------------------------
# SPMD101 — compat drift
# --------------------------------------------------------------------------

#: qualified names that moved between jax releases and therefore must be
#: spelled only inside utils/compat.py; value = the shim to use instead
_COMPAT_ONLY = {
    "jax.shard_map": "utils.compat.shard_map",
    "jax.experimental.shard_map": "utils.compat.shard_map",
    "jax.typeof": "utils.compat.varying_axes",
    "jax.lax.pvary": "utils.compat.device_varying_marker",
    "jax.lax.pcast": "utils.compat.device_varying_marker",
}
#: getattr-probe spellings of the same drift ({module qualname: attrs})
_COMPAT_ONLY_PROBES = {
    "jax": {"shard_map": "utils.compat.shard_map",
            "typeof": "utils.compat.varying_axes"},
    "jax.lax": {"pvary": "utils.compat.device_varying_marker",
                "pcast": "utils.compat.device_varying_marker"},
}


def _compat_match(qual: str) -> Optional[Tuple[str, str]]:
    """-> (matched banned prefix, replacement shim) or None."""
    for banned, shim in _COMPAT_ONLY.items():
        if qual == banned or qual.startswith(banned + "."):
            return banned, shim
    return None


@register
class CompatDriftRule(Rule):
    code = "SPMD101"
    name = "compat-drift"
    summary = ("version-moved jax API (shard_map / typeof / pvary / pcast) "
               "spelled directly instead of through utils.compat")
    hint = ("route through bigdl_tpu.utils.compat — shard_map for "
            "jax.shard_map/jax.experimental.shard_map, varying_axes for "
            "jax.typeof(...).vma, device_varying_marker for lax.pvary/"
            "lax.pcast; the shim resolves the right spelling per jax "
            "generation")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.is_compat:
            return
        flagged: Set[Tuple[int, int]] = set()

        def emit(node: ast.AST, qual: str, shim: str) -> Optional[Finding]:
            key = (node.lineno, node.col_offset)
            if key in flagged:
                return None
            flagged.add(key)
            return ctx.finding(
                node, self.code,
                f"direct use of `{qual}` outside utils/compat.py "
                f"— this API moved between jax releases",
                hint=f"use `{shim}` — {self.hint}")

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    m = _compat_match(a.name)
                    if m:
                        f = emit(node, a.name, m[1])
                        if f:
                            yield f
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    m = _compat_match(f"{node.module}.{a.name}")
                    if m:
                        f = emit(node, f"{node.module}.{a.name}", m[1])
                        if f:
                            yield f
            elif isinstance(node, ast.Attribute):
                qual = ctx.qualname(node)
                if qual:
                    m = _compat_match(qual)
                    if m and not isinstance(ctx.parents.get(node),
                                            ast.Attribute):
                        f = emit(node, qual, m[1])
                        if f:
                            yield f
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "getattr" and len(node.args) >= 2:
                mod = ctx.qualname(node.args[0])
                attr = node.args[1]
                if mod in _COMPAT_ONLY_PROBES and \
                        isinstance(attr, ast.Constant) and \
                        attr.value in _COMPAT_ONLY_PROBES[mod]:
                    shim = _COMPAT_ONLY_PROBES[mod][attr.value]
                    f = emit(node, f'getattr({mod}, "{attr.value}")', shim)
                    if f:
                        yield f


# --------------------------------------------------------------------------
# SPMD102 — PartitionSpec spelling drift
# --------------------------------------------------------------------------

_PSPEC_QUALNAMES = {"jax.sharding.PartitionSpec",
                    "jax.experimental.pjit.PartitionSpec"}


@register
class SpecSpellingRule(Rule):
    code = "SPMD102"
    name = "spec-spelling"
    summary = ("PartitionSpec single-axis tuple spelling `P((\"a\",))` — "
               "hashes differently from `P(\"a\")` and double-compiles")
    hint = ("spell single-axis entries as the bare string: "
            "`P(\"data\")`, never `P((\"data\",))` — jit cache keys and "
            "NamedSharding equality treat them as DIFFERENT specs even "
            "though they place identically, so one drifted spelling "
            "silently compiles every program twice (the PR-4 bug); for "
            "placement specs, build through "
            "bigdl_tpu.serving.sharded.named_sharding which also drops "
            "size-1 axes")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.qualname(node.func) not in _PSPEC_QUALNAMES:
                continue
            for arg in node.args:
                if isinstance(arg, (ast.Tuple, ast.List)) and \
                        len(arg.elts) == 1:
                    spelled = ast.unparse(arg)
                    yield ctx.finding(
                        arg, self.code,
                        f"single-axis tuple spelling `{spelled}` in "
                        f"PartitionSpec — equivalent placement to the bare "
                        f"string but a DIFFERENT hash/compile key",
                        hint=self.hint)


# --------------------------------------------------------------------------
# SPMD103 — recompile hazards
# --------------------------------------------------------------------------

_BLOCKSPEC_QUALNAMES = {"jax.experimental.pallas.BlockSpec"}


def _own_scope_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested
    def/lambda subtrees — their assignment targets are locals of a
    DIFFERENT scope and must not count as this function's bindings."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        sub = stack.pop()
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        yield sub
        stack.extend(ast.iter_child_nodes(sub))


def _scope_local_names(ctx: FileContext, node: ast.AST) -> Set[str]:
    """Names bound in the enclosing function/lambda scope chain of
    ``node`` (params + assignment/loop/with targets) — the values a
    closure at ``node`` could capture per call, as opposed to
    module-level constants."""
    names: Set[str] = set()
    cur = ctx.enclosing_function(node)
    while cur is not None:
        a = cur.args
        for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
            names.add(p.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
        if not isinstance(cur, ast.Lambda):
            for sub in _own_scope_nodes(cur):
                targets: List[ast.AST] = []
                if isinstance(sub, ast.Assign):
                    targets = list(sub.targets)
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign,
                                      ast.For)):
                    targets = [sub.target]
                elif isinstance(sub, ast.withitem) and \
                        sub.optional_vars is not None:
                    targets = [sub.optional_vars]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            names.add(n.id)
        cur = ctx.enclosing_function(cur)
    return names


@register
class RecompileHazardRule(Rule):
    code = "SPMD103"
    name = "recompile-hazard"
    summary = ("f-string/.format on traced values inside jitted bodies; "
               "structure-varying containers passed to jitted callables; "
               "Pallas BlockSpec index-map closures over per-call values")
    hint = ("traced values cannot be formatted (concretization error, or "
            "a retrace per shape via `.shape` interpolation) — format "
            "outside the traced function, e.g. in the caller or via "
            "jax.debug.print; containers built by comprehension change "
            "their pytree STRUCTURE with the data, and structure is part "
            "of the jit cache key — pad to a fixed layout or bucket it "
            "(see serving/admission.py); a BlockSpec index map that "
            "closes over an enclosing function's local bakes that value "
            "into the kernel trace — every distinct value is a NEW "
            "compiled kernel; pass per-call offsets as operands "
            "(scalar prefetch) or fold them into the grid "
            "(see ops/decode_attention.py for the closure-free pattern)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # (a) formatting on traced values inside traced bodies
        for tf in _traced_functions(ctx):
            tainted = set(tf.dynamic_params)
            for node in ast.walk(tf.fn):
                if isinstance(node, ast.JoinedStr):
                    offs: List[ast.Name] = []
                    for part in node.values:
                        if isinstance(part, ast.FormattedValue):
                            offs.extend(_dynamic_uses(part.value, tainted))
                    if offs:
                        yield ctx.finding(
                            node, self.code,
                            f"f-string interpolates traced value "
                            f"`{offs[0].id}` inside a body traced via "
                            f"{tf.via}",
                            hint=self.hint)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "format":
                    offs = []
                    for a in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        offs.extend(_dynamic_uses(a, tainted))
                    if offs:
                        yield ctx.finding(
                            node, self.code,
                            f".format() on traced value `{offs[0].id}` "
                            f"inside a body traced via {tf.via}",
                            hint=self.hint)

        # (c) Pallas BlockSpec index maps that close over per-call
        # values: the index map is traced into the kernel's program, so
        # a captured enclosing-scope local (a per-request offset, a
        # data-derived start) keys a NEW pallas compile per distinct
        # value. Index maps should be pure functions of the grid
        # indices; per-call data belongs in operands. (Module-level
        # constants and the lambda's own params are fine — only names
        # bound in an enclosing function scope fire.)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    ctx.qualname(node.func) not in _BLOCKSPEC_QUALNAMES:
                continue
            im = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "index_map":
                    im = kw.value
            if not isinstance(im, ast.Lambda):
                continue
            a = im.args
            own = {p.arg for p in
                   list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}
            if a.vararg:
                own.add(a.vararg.arg)
            if a.kwarg:
                own.add(a.kwarg.arg)
            outer = _scope_local_names(ctx, im)
            for n in ast.walk(im.body):
                if isinstance(n, ast.Name) and n.id not in own and \
                        n.id in outer:
                    yield ctx.finding(
                        im, self.code,
                        f"BlockSpec index map closes over enclosing-"
                        f"scope value `{n.id}` — the closure is baked "
                        f"into the kernel trace, so every distinct "
                        f"value compiles a new pallas program",
                        hint=self.hint)
                    break

        # (b) structure-varying container literally built at the call
        # site of a known-jitted callable
        jitted_names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and _jit_info(ctx, node.value):
                for t in node.targets:
                    d = ctx.dotted(t)
                    if d:
                        jitted_names.add(d)
            elif isinstance(node, ast.Return) and node.value is not None \
                    and _jit_info(ctx, node.value):
                fn = ctx.enclosing_function(node)
                if isinstance(fn, ast.FunctionDef):
                    # e.g. a cached_property returning jax.jit(...) —
                    # call sites spell it self.<name>
                    jitted_names.add(f"self.{fn.name}")
        if not jitted_names:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.dotted(node.func) not in jitted_names:
                continue
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, (ast.DictComp, ast.ListComp, ast.SetComp,
                                  ast.GeneratorExp)):
                    yield ctx.finding(
                        a, self.code,
                        "container built by comprehension flows into "
                        f"jitted callable `{ctx.dotted(node.func)}` — its "
                        "pytree structure varies with the data, so every "
                        "new structure is a new compile",
                        hint=self.hint)


# --------------------------------------------------------------------------
# SPMD104 — donation misuse
# --------------------------------------------------------------------------

@register
class DonationReuseRule(Rule):
    code = "SPMD104"
    name = "donation-reuse"
    summary = ("argument donated via donate_argnums read again after the "
               "donating call")
    hint = ("a donated buffer is INVALID after the call (XLA reuses its "
            "memory for the outputs) — rebind the name to the call's "
            "result (`carry = step(carry, x)`) or drop donation for "
            "buffers you must keep")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # donated callable name -> donated positional indices
        donated: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(ctx.tree):
            info = None
            if isinstance(node, ast.Assign):
                info = _jit_info(ctx, node.value)
                targets = [ctx.dotted(t) for t in node.targets]
            elif isinstance(node, ast.Return) and node.value is not None:
                info = _jit_info(ctx, node.value)
                fn = ctx.enclosing_function(node)
                targets = [f"self.{fn.name}"] \
                    if isinstance(fn, ast.FunctionDef) else []
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    j = _jit_info(ctx, dec) if isinstance(dec, ast.Call) \
                        else None
                    if j:
                        info, targets = j, [node.name]
                        break
                else:
                    continue
            else:
                continue
            if not info:
                continue
            nums = _kwarg(info[0], "donate_argnums")
            pos = _const_int_tuple(nums) if nums is not None else None
            if pos:
                for t in targets:
                    if t:
                        donated[t] = pos

        if not donated:
            return

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = ctx.dotted(node.func)
            if callee not in donated:
                continue
            scope = ctx.enclosing_function(node) or ctx.tree
            for i in donated[callee]:
                if i >= len(node.args):
                    continue
                buf = ctx.dotted(node.args[i])
                if buf is None or buf == "self":
                    continue
                reuse = self._first_reuse(ctx, scope, buf, node)
                if reuse is not None:
                    yield ctx.finding(
                        reuse, self.code,
                        f"`{buf}` was donated to `{callee}` on line "
                        f"{node.lineno} (donate_argnums includes position "
                        f"{i}) and is read again here",
                        hint=self.hint)

    @staticmethod
    def _first_reuse(ctx: FileContext, scope: ast.AST, buf: str,
                     call: ast.Call) -> Optional[ast.AST]:
        """First Load of ``buf`` after the donating ``call`` in ``scope``
        (same function only — closures and other functions are out of
        this linear approximation) with no intervening rebinding."""
        call_line = getattr(call, "end_lineno", call.lineno)
        scope_fn = scope if isinstance(
            scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                    ast.Lambda)) else None
        loads: List[ast.AST] = []
        stores: List[int] = []
        for n in ast.walk(scope):
            if isinstance(n, ast.AugAssign):
                # `cache += 1` reads the old buffer before rebinding —
                # the target carries Store ctx only, so surface the
                # implicit read here
                if ctx.dotted(n.target) == buf and \
                        ctx.enclosing_function(n) is scope_fn and \
                        n.lineno > call_line:
                    loads.append(n.target)
                continue
            d = ctx.dotted(n) if isinstance(n, (ast.Name, ast.Attribute)) \
                else None
            if d != buf:
                continue
            if ctx.enclosing_function(n) is not scope_fn:
                continue
            ic = getattr(n, "ctx", None)
            if isinstance(ic, ast.Load):
                # strictly after the donating call's last line — the
                # call's own argument loads never count
                if n.lineno > call_line:
                    loads.append(n)
            elif isinstance(ic, (ast.Store, ast.Del)):
                stores.append(n.lineno)
        for n in sorted(loads, key=lambda x: (x.lineno, x.col_offset)):
            # a store masks only loads on LATER lines: in
            # `cache = cache + 1` the RHS reads the (dead) buffer before
            # the same-statement rebind takes effect
            if not any(call.lineno <= s < n.lineno for s in stores):
                return n
        return None


# --------------------------------------------------------------------------
# SPMD105 — tracer leaks
# --------------------------------------------------------------------------

@register
class TracerLeakRule(Rule):
    code = "SPMD105"
    name = "tracer-leak"
    summary = ("Python `if`/`while` on a traced value inside a "
               "jitted/shard_mapped/scanned body")
    hint = ("Python control flow runs at TRACE time and needs a concrete "
            "bool — on a tracer this raises (or silently bakes in one "
            "branch). Use lax.cond / lax.select / jnp.where for value-"
            "dependent branches; branching on static facts "
            "(`x is None`, `x.ndim`, `x.shape[0]`, `len(xs)`) is fine "
            "and not flagged")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        reported: Set[Tuple[int, int]] = set()
        for tf in _traced_functions(ctx):
            params = set(tf.dynamic_params)
            if not params:
                continue
            for node in ast.walk(tf.fn):
                if not isinstance(node, (ast.If, ast.While, ast.IfExp,
                                         ast.Assert)):
                    continue
                test = node.test
                offs = _dynamic_uses(test, params)
                if not offs:
                    continue
                key = (node.lineno, node.col_offset)
                if key in reported:
                    continue
                reported.add(key)
                kind = {ast.If: "if", ast.While: "while",
                        ast.IfExp: "conditional expression",
                        ast.Assert: "assert"}[type(node)]
                yield ctx.finding(
                    node, self.code,
                    f"`{kind}` on traced value `{offs[0].id}` inside a "
                    f"body traced via {tf.via}",
                    hint=self.hint)


# --------------------------------------------------------------------------
# SPMD106 — mesh-axis consistency
# --------------------------------------------------------------------------

_MESH_QUALNAMES = {"jax.sharding.Mesh", "jax.experimental.maps.Mesh"}
#: mesh factories with FIXED axis names (bigdl_tpu.serving.sharded.make_mesh
#: always builds ("data", "model"))
_MESH_FACTORIES = {
    "bigdl_tpu.serving.sharded.make_mesh": {"data", "model"},
    "bigdl_tpu.serving.make_mesh": {"data", "model"},
}


def _mesh_axes_from_call(ctx: FileContext,
                         call: ast.Call) -> Optional[Set[str]]:
    q = ctx.qualname(call.func)
    if q in _MESH_FACTORIES:
        return set(_MESH_FACTORIES[q])
    if q in _MESH_QUALNAMES:
        ax = _kwarg(call, "axis_names")
        if ax is None and len(call.args) >= 2:
            ax = call.args[1]
        if ax is None:
            return None
        return _const_str_set(ax)
    return None


@register
class MeshAxisRule(Rule):
    code = "SPMD106"
    name = "mesh-axis"
    summary = ("in_specs/out_specs naming an axis the shard_map's mesh "
               "does not define")
    hint = ("every axis name in in_specs/out_specs must be one of the "
            "Mesh's axis_names — a misspelled axis fails at trace time "
            "at best, silently replicates at worst; fix the spec or the "
            "Mesh construction")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # mesh variable name -> [(enclosing scope, lineno, axes-or-None)];
        # axes is None for assignments whose provenance the analyzer
        # cannot see (helper calls, parameters...) — those SHADOW
        # literal constructions rather than being skipped over
        mesh_vars: Dict[str, List[Tuple[Optional[ast.AST], int,
                                        Optional[Set[str]]]]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                axes = _mesh_axes_from_call(ctx, node.value) \
                    if isinstance(node.value, ast.Call) else None
                scope = ctx.enclosing_function(node)
                for t in node.targets:
                    d = ctx.dotted(t)
                    if d:
                        mesh_vars.setdefault(d, []).append(
                            (scope, node.lineno, axes))

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            q = ctx.qualname(node.func)
            if q not in _SHARD_MAP_QUALNAMES:
                continue
            mesh_arg = _kwarg(node, "mesh")
            if mesh_arg is None:
                continue
            axes: Optional[Set[str]] = None
            mesh_label = ast.unparse(mesh_arg)
            if isinstance(mesh_arg, ast.Call):
                axes = _mesh_axes_from_call(ctx, mesh_arg)
            else:
                d = ctx.dotted(mesh_arg)
                if d in mesh_vars:
                    axes = self._resolve_var(ctx, mesh_vars[d], node)
            if axes is None:
                continue           # provenance unknown — stay silent
            for kw_name in ("in_specs", "out_specs"):
                specs = _kwarg(node, kw_name)
                if specs is None:
                    continue
                for f in self._check_specs(ctx, specs, axes, kw_name,
                                           mesh_label):
                    yield f

    @staticmethod
    def _resolve_var(ctx: FileContext,
                     cands: List[Tuple[Optional[ast.AST], int,
                                       Optional[Set[str]]]],
                     call: ast.Call) -> Optional[Set[str]]:
        """Axes of the nearest preceding assignment to the mesh variable,
        searching the call's lexical scope chain innermost-out.  Returns
        None (silence) when the binding that actually wins is one the
        analyzer cannot see into."""
        scope: Optional[ast.AST] = ctx.enclosing_function(call)
        while True:
            in_scope = [(ln, axes) for (s, ln, axes) in cands
                        if s is scope and ln <= call.lineno]
            if in_scope:
                # nearest preceding; its axes may be None -> silence
                return max(in_scope, key=lambda t: t[0])[1]
            if scope is None:
                return None
            scope = ctx.enclosing_function(scope)

    def _check_specs(self, ctx: FileContext, specs: ast.AST,
                     axes: Set[str], kw_name: str,
                     mesh_label: str) -> Iterator[Finding]:
        for node in ast.walk(specs):
            if not isinstance(node, ast.Call):
                continue
            if ctx.qualname(node.func) not in _PSPEC_QUALNAMES:
                continue
            for s in ast.walk(node):
                if isinstance(s, ast.Constant) and \
                        isinstance(s.value, str) and s.value not in axes:
                    yield ctx.finding(
                        s, self.code,
                        f"{kw_name} names axis `{s.value}` but mesh "
                        f"`{mesh_label}` defines axes "
                        f"{sorted(axes)}",
                        hint=self.hint)
