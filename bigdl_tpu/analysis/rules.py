"""The analyzer's rule families.

Every rule here encodes a bug class this repo has actually shipped (see
docs/analysis.md for the war stories):

==========  ==============================================================
SPMD101     compat drift — version-moved jax APIs spelled directly
SPMD102     PartitionSpec spelling drift (the PR-4 double-compile)
SPMD103     recompile hazards in/around jitted programs
SPMD104     donated buffer reused after the donating call
SPMD105     Python control flow on traced values
SPMD106     shard_map specs naming axes the mesh does not have
SRV201-208  serving contracts (whole-program fact table)
ASY301-305  async readiness: host-sync hygiene on the HOT PATH, scoped
            by call-graph reachability from the serving super-step
            roots (core.hotpath_chains)
==========  ==============================================================

All rules are import-resolution based, not textual: ``lax.pvary`` is
flagged under ``from jax import lax`` and not when ``lax`` is someone's
local variable, and docstrings/comments never trigger (the historical
reason the repo could not just grep for these).
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

from bigdl_tpu.analysis.core import (
    UNRESOLVED, FileContext, Finding, Rule, _own_scope_nodes,
    _unit_functions, enclosing_unit, hotpath_chains, literal_value,
    register, register_fact_collector as _register_facts,
)

# --------------------------------------------------------------------------
# shared machinery
# --------------------------------------------------------------------------

#: wrappers whose function argument becomes a traced body
_JIT_QUALNAMES = {"jax.jit", "jax.pmap"}
_SHARD_MAP_QUALNAMES = {
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "bigdl_tpu.utils.compat.shard_map",
    "bigdl_tpu.utils.compat.resolve_shard_map",
}
#: control-flow combinators: (qualname -> positions of traced callees)
_COMBINATOR_FN_ARGS = {
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": None,       # every arg from 1 on is a branch
    "jax.lax.associative_scan": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
}

#: attributes of a traced array that are static at trace time — branching
#: or formatting on these is fine
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "weak_type", "itemsize", "nbytes"}
#: calls whose result on a tracer is static / python-level
_STATIC_CALLS = {"len", "isinstance", "callable", "hasattr", "getattr",
                 "type", "id", "repr"}


def _const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """(1, 2) / 1 / [0] as a tuple of ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int) \
                    and not isinstance(e.value, bool):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    return None


def _const_str_set(node: ast.AST) -> Optional[Set[str]]:
    """Set of string constants in a str / tuple/list-of-str literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
            else:
                return None
        return out
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in
             list(getattr(a, "posonlyargs", [])) + list(a.args)
             + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class _TracedFn:
    """A function object the analyzer believes gets traced, plus which of
    its parameters are dynamic (non-static) there."""

    def __init__(self, fn: ast.AST, via: str,
                 static_argnums: Tuple[int, ...] = (),
                 static_argnames: Sequence[str] = ()) -> None:
        self.fn = fn                      # FunctionDef / Lambda
        self.via = via                    # "jax.jit", "compat.shard_map", ...
        names = _param_names(fn)
        drop = set(static_argnames)
        for i in static_argnums:
            if 0 <= i < len(names):
                drop.add(names[i])
        self.dynamic_params = {n for n in names if n not in drop
                               and n != "self"}


def _local_defs(ctx: FileContext) -> Dict[str, List[ast.AST]]:
    """name -> FunctionDefs in the file (all scopes), in source order."""
    out = ctx.cache.get("local_defs")
    if out is None:
        out = ctx.cache["local_defs"] = {}
        for node in sorted(ctx.by_type(ast.FunctionDef,
                                       ast.AsyncFunctionDef),
                           key=lambda n: n.lineno):
            out.setdefault(node.name, []).append(node)
    return out


def _resolve_fn_arg(ctx: FileContext, node: ast.AST,
                    defs: Dict[str, List[ast.AST]],
                    before_line: int) -> Optional[ast.AST]:
    """The function object an argument refers to: a Lambda/def literal,
    or the nearest preceding local def with that name."""
    if isinstance(node, ast.Lambda):
        return node
    if isinstance(node, ast.Name) and node.id in defs:
        cands = [d for d in defs[node.id] if d.lineno <= before_line]
        return cands[-1] if cands else defs[node.id][0]
    return None


def _is_partial(ctx: FileContext, call: ast.Call) -> bool:
    q = ctx.qualname(call.func)
    return q in {"functools.partial", "partial"} or \
        (isinstance(call.func, ast.Name) and call.func.id == "partial")


def _jit_info(ctx: FileContext, value: ast.AST,
              ) -> Optional[Tuple[ast.Call, Tuple[int, ...], List[str]]]:
    """If ``value`` is a (possibly partial-wrapped) ``jax.jit(...)`` call,
    -> (the jit Call, static_argnums, static_argnames)."""
    if not isinstance(value, ast.Call):
        return None
    call = value
    q = ctx.qualname(call.func)
    if q in {"functools.partial", "partial"} and call.args:
        inner_q = ctx.qualname(call.args[0])
        if inner_q in _JIT_QUALNAMES:
            q = inner_q
        else:
            return None
    if q not in _JIT_QUALNAMES:
        return None
    nums = _kwarg(call, "static_argnums")
    names = _kwarg(call, "static_argnames")
    return (call,
            _const_int_tuple(nums) or () if nums is not None else (),
            sorted(_const_str_set(names) or set()) if names is not None
            else [])


def _traced_functions(ctx: FileContext) -> List[_TracedFn]:
    """Every local def/lambda the file hands to jit / shard_map / a lax
    control-flow combinator, plus defs decorated with them.  Cached per
    file — SPMD103 and SPMD105 share one derivation."""
    cached = ctx.cache.get("traced_functions")
    if cached is not None:
        return cached
    defs = _local_defs(ctx)
    traced: List[_TracedFn] = []
    seen: Set[int] = set()

    def add(fn: Optional[ast.AST], via: str,
            static_argnums: Tuple[int, ...] = (),
            static_argnames: Sequence[str] = ()) -> None:
        if fn is None or id(fn) in seen:
            return
        seen.add(id(fn))
        traced.append(_TracedFn(fn, via, static_argnums, static_argnames))

    for node in ctx.by_type(ast.Call, ast.FunctionDef,
                            ast.AsyncFunctionDef):
        if isinstance(node, ast.Call):
            q = ctx.qualname(node.func)
            if q in _JIT_QUALNAMES or q in _SHARD_MAP_QUALNAMES:
                info = _jit_info(ctx, node)
                nums, names = (info[1], info[2]) if info else ((), [])
                if node.args:
                    add(_resolve_fn_arg(ctx, node.args[0], defs,
                                        node.lineno), q or "jit",
                        nums, names)
            elif q in _COMBINATOR_FN_ARGS:
                poss = _COMBINATOR_FN_ARGS[q]
                if poss is None:                       # lax.switch
                    poss = tuple(range(1, len(node.args)))
                for i in poss:
                    if i < len(node.args):
                        add(_resolve_fn_arg(ctx, node.args[i], defs,
                                            node.lineno), q)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    q = ctx.qualname(dec.func)
                    if q in _JIT_QUALNAMES:
                        nums = _kwarg(dec, "static_argnums")
                        names = _kwarg(dec, "static_argnames")
                        add(node, q, _const_int_tuple(nums) or ()
                            if nums is not None else (),
                            sorted(_const_str_set(names) or set())
                            if names is not None else [])
                    elif _is_partial(ctx, dec) and dec.args and \
                            ctx.qualname(dec.args[0]) in _JIT_QUALNAMES:
                        nums = _kwarg(dec, "static_argnums")
                        names = _kwarg(dec, "static_argnames")
                        add(node, "jax.jit", _const_int_tuple(nums) or ()
                            if nums is not None else (),
                            sorted(_const_str_set(names) or set())
                            if names is not None else [])
                else:
                    q = ctx.qualname(dec)
                    if q in _JIT_QUALNAMES:
                        add(node, q)
    ctx.cache["traced_functions"] = traced
    return traced


def _dynamic_uses(expr: ast.AST, tainted: Set[str]) -> List[ast.Name]:
    """Name nodes in ``expr`` bound to tainted (traced) values that are
    used *dynamically* — i.e. NOT behind a trace-time-static accessor
    (``x.shape``/``x.ndim``/``x.dtype``..., ``len(x)``, ``isinstance``,
    ``x is None``).  These are the uses that concretize a tracer."""
    offending: List[ast.Name] = []

    def visit(node: ast.AST, static: bool) -> None:
        if isinstance(node, ast.Name):
            if node.id in tainted and not static:
                offending.append(node)
            return
        if isinstance(node, ast.Attribute):
            visit(node.value, static or node.attr in _STATIC_ATTRS)
            return
        if isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) else None
            inner_static = static or fname in _STATIC_CALLS
            for child in list(node.args) + [kw.value for kw in node.keywords]:
                visit(child, inner_static)
            if not isinstance(node.func, ast.Name):
                visit(node.func, static)
            return
        if isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            for child in [node.left] + list(node.comparators):
                visit(child, True)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, static)

    visit(expr, False)
    return offending


# --------------------------------------------------------------------------
# SPMD101 — compat drift
# --------------------------------------------------------------------------

#: qualified names that moved between jax releases and therefore must be
#: spelled only inside utils/compat.py; value = the shim to use instead
_COMPAT_ONLY = {
    "jax.shard_map": "utils.compat.shard_map",
    "jax.experimental.shard_map": "utils.compat.shard_map",
    "jax.typeof": "utils.compat.varying_axes",
    "jax.lax.pvary": "utils.compat.device_varying_marker",
    "jax.lax.pcast": "utils.compat.device_varying_marker",
}
#: getattr-probe spellings of the same drift ({module qualname: attrs})
_COMPAT_ONLY_PROBES = {
    "jax": {"shard_map": "utils.compat.shard_map",
            "typeof": "utils.compat.varying_axes"},
    "jax.lax": {"pvary": "utils.compat.device_varying_marker",
                "pcast": "utils.compat.device_varying_marker"},
}


def _compat_match(qual: str) -> Optional[Tuple[str, str]]:
    """-> (matched banned prefix, replacement shim) or None."""
    for banned, shim in _COMPAT_ONLY.items():
        if qual == banned or qual.startswith(banned + "."):
            return banned, shim
    return None


@register
class CompatDriftRule(Rule):
    code = "SPMD101"
    name = "compat-drift"
    summary = ("version-moved jax API (shard_map / typeof / pvary / pcast) "
               "spelled directly instead of through utils.compat")
    hint = ("route through bigdl_tpu.utils.compat — shard_map for "
            "jax.shard_map/jax.experimental.shard_map, varying_axes for "
            "jax.typeof(...).vma, device_varying_marker for lax.pvary/"
            "lax.pcast; the shim resolves the right spelling per jax "
            "generation")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.is_compat:
            return
        flagged: Set[Tuple[int, int]] = set()

        def emit(node: ast.AST, qual: str, shim: str) -> Optional[Finding]:
            key = (node.lineno, node.col_offset)
            if key in flagged:
                return None
            flagged.add(key)
            return ctx.finding(
                node, self.code,
                f"direct use of `{qual}` outside utils/compat.py "
                f"— this API moved between jax releases",
                hint=f"use `{shim}` — {self.hint}")

        for node in ctx.by_type(ast.Import, ast.ImportFrom,
                                ast.Attribute, ast.Call):
            if isinstance(node, ast.Import):
                for a in node.names:
                    m = _compat_match(a.name)
                    if m:
                        f = emit(node, a.name, m[1])
                        if f:
                            yield f
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    m = _compat_match(f"{node.module}.{a.name}")
                    if m:
                        f = emit(node, f"{node.module}.{a.name}", m[1])
                        if f:
                            yield f
            elif isinstance(node, ast.Attribute):
                qual = ctx.qualname(node)
                if qual:
                    m = _compat_match(qual)
                    if m and not isinstance(ctx.parents.get(node),
                                            ast.Attribute):
                        f = emit(node, qual, m[1])
                        if f:
                            yield f
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id == "getattr" and len(node.args) >= 2:
                mod = ctx.qualname(node.args[0])
                attr = node.args[1]
                if mod in _COMPAT_ONLY_PROBES and \
                        isinstance(attr, ast.Constant) and \
                        attr.value in _COMPAT_ONLY_PROBES[mod]:
                    shim = _COMPAT_ONLY_PROBES[mod][attr.value]
                    f = emit(node, f'getattr({mod}, "{attr.value}")', shim)
                    if f:
                        yield f


# --------------------------------------------------------------------------
# SPMD102 — PartitionSpec spelling drift
# --------------------------------------------------------------------------

_PSPEC_QUALNAMES = {"jax.sharding.PartitionSpec",
                    "jax.experimental.pjit.PartitionSpec"}


@register
class SpecSpellingRule(Rule):
    code = "SPMD102"
    name = "spec-spelling"
    summary = ("PartitionSpec single-axis tuple spelling `P((\"a\",))` — "
               "hashes differently from `P(\"a\")` and double-compiles")
    hint = ("spell single-axis entries as the bare string: "
            "`P(\"data\")`, never `P((\"data\",))` — jit cache keys and "
            "NamedSharding equality treat them as DIFFERENT specs even "
            "though they place identically, so one drifted spelling "
            "silently compiles every program twice (the PR-4 bug); for "
            "placement specs, build through "
            "bigdl_tpu.serving.sharded.named_sharding which also drops "
            "size-1 axes")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.by_type(ast.Call):
            if ctx.qualname(node.func) not in _PSPEC_QUALNAMES:
                continue
            for arg in node.args:
                if isinstance(arg, (ast.Tuple, ast.List)) and \
                        len(arg.elts) == 1:
                    spelled = ast.unparse(arg)
                    yield ctx.finding(
                        arg, self.code,
                        f"single-axis tuple spelling `{spelled}` in "
                        f"PartitionSpec — equivalent placement to the bare "
                        f"string but a DIFFERENT hash/compile key",
                        hint=self.hint)


# --------------------------------------------------------------------------
# SPMD103 — recompile hazards
# --------------------------------------------------------------------------

_BLOCKSPEC_QUALNAMES = {"jax.experimental.pallas.BlockSpec"}


@register
class RecompileHazardRule(Rule):
    code = "SPMD103"
    name = "recompile-hazard"
    summary = ("f-string/.format on traced values inside jitted bodies; "
               "structure-varying containers passed to jitted callables; "
               "Pallas BlockSpec index-map closures over per-call values")
    hint = ("traced values cannot be formatted (concretization error, or "
            "a retrace per shape via `.shape` interpolation) — format "
            "outside the traced function, e.g. in the caller or via "
            "jax.debug.print; containers built by comprehension change "
            "their pytree STRUCTURE with the data, and structure is part "
            "of the jit cache key — pad to a fixed layout or bucket it "
            "(see serving/admission.py); a BlockSpec index map that "
            "closes over an enclosing function's local bakes that value "
            "into the kernel trace — every distinct value is a NEW "
            "compiled kernel; pass per-call offsets as operands "
            "(scalar prefetch) or fold them into the grid "
            "(see ops/decode_attention.py for the closure-free pattern)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # (a) formatting on traced values inside traced bodies
        for tf in _traced_functions(ctx):
            tainted = set(tf.dynamic_params)
            for node in ast.walk(tf.fn):
                if isinstance(node, ast.JoinedStr):
                    offs: List[ast.Name] = []
                    for part in node.values:
                        if isinstance(part, ast.FormattedValue):
                            offs.extend(_dynamic_uses(part.value, tainted))
                    if offs:
                        yield ctx.finding(
                            node, self.code,
                            f"f-string interpolates traced value "
                            f"`{offs[0].id}` inside a body traced via "
                            f"{tf.via}",
                            hint=self.hint)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "format":
                    offs = []
                    for a in list(node.args) + \
                            [kw.value for kw in node.keywords]:
                        offs.extend(_dynamic_uses(a, tainted))
                    if offs:
                        yield ctx.finding(
                            node, self.code,
                            f".format() on traced value `{offs[0].id}` "
                            f"inside a body traced via {tf.via}",
                            hint=self.hint)

        # (c) Pallas BlockSpec index maps that close over per-call
        # values: the index map is traced into the kernel's program, so
        # a captured enclosing-scope local (a per-request offset, a
        # data-derived start) keys a NEW pallas compile per distinct
        # value. Index maps should be pure functions of the grid
        # indices; per-call data belongs in operands. (Module-level
        # constants and the lambda's own params are fine — only names
        # bound in an enclosing function scope fire.)
        for node in ctx.by_type(ast.Call):
            if ctx.qualname(node.func) not in _BLOCKSPEC_QUALNAMES:
                continue
            im = node.args[1] if len(node.args) >= 2 else None
            for kw in node.keywords:
                if kw.arg == "index_map":
                    im = kw.value
            if not isinstance(im, ast.Lambda):
                continue
            a = im.args
            own = {p.arg for p in
                   list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}
            if a.vararg:
                own.add(a.vararg.arg)
            if a.kwarg:
                own.add(a.kwarg.arg)
            outer = ctx.scope_local_names(im)
            for n in ast.walk(im.body):
                if isinstance(n, ast.Name) and n.id not in own and \
                        n.id in outer:
                    yield ctx.finding(
                        im, self.code,
                        f"BlockSpec index map closes over enclosing-"
                        f"scope value `{n.id}` — the closure is baked "
                        f"into the kernel trace, so every distinct "
                        f"value compiles a new pallas program",
                        hint=self.hint)
                    break

        # (b) structure-varying container literally built at the call
        # site of a known-jitted callable
        jitted_names: Set[str] = set()
        for node in ctx.by_type(ast.Assign, ast.Return):
            if isinstance(node, ast.Assign) and _jit_info(ctx, node.value):
                for t in node.targets:
                    d = ctx.dotted(t)
                    if d:
                        jitted_names.add(d)
            elif isinstance(node, ast.Return) and node.value is not None \
                    and _jit_info(ctx, node.value):
                fn = ctx.enclosing_function(node)
                if isinstance(fn, ast.FunctionDef):
                    # e.g. a cached_property returning jax.jit(...) —
                    # call sites spell it self.<name>
                    jitted_names.add(f"self.{fn.name}")
        if not jitted_names:
            return
        for node in ctx.by_type(ast.Call):
            if ctx.dotted(node.func) not in jitted_names:
                continue
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, (ast.DictComp, ast.ListComp, ast.SetComp,
                                  ast.GeneratorExp)):
                    yield ctx.finding(
                        a, self.code,
                        "container built by comprehension flows into "
                        f"jitted callable `{ctx.dotted(node.func)}` — its "
                        "pytree structure varies with the data, so every "
                        "new structure is a new compile",
                        hint=self.hint)


# --------------------------------------------------------------------------
# SPMD104 — donation misuse
# --------------------------------------------------------------------------

@register
class DonationReuseRule(Rule):
    code = "SPMD104"
    name = "donation-reuse"
    summary = ("argument donated via donate_argnums read again after the "
               "donating call")
    hint = ("a donated buffer is INVALID after the call (XLA reuses its "
            "memory for the outputs) — rebind the name to the call's "
            "result (`carry = step(carry, x)`) or drop donation for "
            "buffers you must keep")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # donated callable name -> donated positional indices
        donated = _donating_callables(ctx)
        if not donated:
            return

        for node in ctx.by_type(ast.Call):
            callee = ctx.dotted(node.func)
            if callee not in donated:
                continue
            scope = ctx.enclosing_function(node) or ctx.tree
            for i in donated[callee]:
                if i >= len(node.args):
                    continue
                buf = ctx.dotted(node.args[i])
                if buf is None or buf == "self":
                    continue
                reuse = _first_reuse(ctx, scope, buf, node)
                if reuse is not None:
                    yield ctx.finding(
                        reuse, self.code,
                        f"`{buf}` was donated to `{callee}` on line "
                        f"{node.lineno} (donate_argnums includes position "
                        f"{i}) and is read again here",
                        hint=self.hint)


def _donating_callables(ctx: FileContext) -> Dict[str, Tuple[int, ...]]:
    """Dotted callable name -> donated positional indices, for every
    jitted-with-donation binding visible in the file (the SPMD104
    ground truth, shared with SRV204's call-graph lifting; cached per
    file)."""
    cached = ctx.cache.get("donating_callables")
    if cached is not None:
        return cached
    donated: Dict[str, Tuple[int, ...]] = {}
    try:
        for node in ctx.by_type(ast.Assign, ast.Return, ast.FunctionDef,
                                ast.AsyncFunctionDef):
            info = None
            if isinstance(node, ast.Assign):
                info = _jit_info(ctx, node.value)
                targets = [ctx.dotted(t) for t in node.targets]
            elif isinstance(node, ast.Return) and node.value is not None:
                info = _jit_info(ctx, node.value)
                fn = ctx.enclosing_function(node)
                targets = [f"self.{fn.name}"] \
                    if isinstance(fn, ast.FunctionDef) else []
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    j = _jit_info(ctx, dec) if isinstance(dec, ast.Call) \
                        else None
                    if j:
                        info, targets = j, [node.name]
                        break
                else:
                    continue
            else:
                continue
            if not info:
                continue
            nums = _kwarg(info[0], "donate_argnums")
            pos = _const_int_tuple(nums) if nums is not None else None
            if pos:
                for t in targets:
                    if t:
                        donated[t] = pos
    finally:
        ctx.cache["donating_callables"] = donated
    return donated


def _first_reuse(ctx: FileContext, scope: ast.AST, buf: str,
                 call: ast.Call) -> Optional[ast.AST]:
    """First Load of ``buf`` after the donating ``call`` in ``scope``
    (same function only — closures and other functions are out of
    this linear approximation) with no intervening rebinding.  Shared
    by SPMD104 and its call-graph-lifted twin SRV204."""
    call_line = getattr(call, "end_lineno", call.lineno)
    scope_fn = scope if isinstance(
        scope, (ast.FunctionDef, ast.AsyncFunctionDef,
                ast.Lambda)) else None
    loads: List[ast.AST] = []
    stores: List[int] = []
    for n in ast.walk(scope):
        if isinstance(n, ast.AugAssign):
            # `cache += 1` reads the old buffer before rebinding —
            # the target carries Store ctx only, so surface the
            # implicit read here
            if ctx.dotted(n.target) == buf and \
                    ctx.enclosing_function(n) is scope_fn and \
                    n.lineno > call_line:
                loads.append(n.target)
            continue
        d = ctx.dotted(n) if isinstance(n, (ast.Name, ast.Attribute)) \
            else None
        if d != buf:
            continue
        if ctx.enclosing_function(n) is not scope_fn:
            continue
        ic = getattr(n, "ctx", None)
        if isinstance(ic, ast.Load):
            # strictly after the donating call's last line — the
            # call's own argument loads never count
            if n.lineno > call_line:
                loads.append(n)
        elif isinstance(ic, (ast.Store, ast.Del)):
            stores.append(n.lineno)
    for n in sorted(loads, key=lambda x: (x.lineno, x.col_offset)):
        # a store masks only loads on LATER lines: in
        # `cache = cache + 1` the RHS reads the (dead) buffer before
        # the same-statement rebind takes effect
        if not any(call.lineno <= s < n.lineno for s in stores):
            return n
    return None


# --------------------------------------------------------------------------
# SPMD105 — tracer leaks
# --------------------------------------------------------------------------

@register
class TracerLeakRule(Rule):
    code = "SPMD105"
    name = "tracer-leak"
    summary = ("Python `if`/`while` on a traced value inside a "
               "jitted/shard_mapped/scanned body")
    hint = ("Python control flow runs at TRACE time and needs a concrete "
            "bool — on a tracer this raises (or silently bakes in one "
            "branch). Use lax.cond / lax.select / jnp.where for value-"
            "dependent branches; branching on static facts "
            "(`x is None`, `x.ndim`, `x.shape[0]`, `len(xs)`) is fine "
            "and not flagged")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        reported: Set[Tuple[int, int]] = set()
        for tf in _traced_functions(ctx):
            params = set(tf.dynamic_params)
            if not params:
                continue
            for node in ast.walk(tf.fn):
                if not isinstance(node, (ast.If, ast.While, ast.IfExp,
                                         ast.Assert)):
                    continue
                test = node.test
                offs = _dynamic_uses(test, params)
                if not offs:
                    continue
                key = (node.lineno, node.col_offset)
                if key in reported:
                    continue
                reported.add(key)
                kind = {ast.If: "if", ast.While: "while",
                        ast.IfExp: "conditional expression",
                        ast.Assert: "assert"}[type(node)]
                yield ctx.finding(
                    node, self.code,
                    f"`{kind}` on traced value `{offs[0].id}` inside a "
                    f"body traced via {tf.via}",
                    hint=self.hint)


# --------------------------------------------------------------------------
# SPMD106 — mesh-axis consistency
# --------------------------------------------------------------------------

_MESH_QUALNAMES = {"jax.sharding.Mesh", "jax.experimental.maps.Mesh"}
#: mesh factories with FIXED axis names (bigdl_tpu.serving.sharded.make_mesh
#: always builds ("data", "model"))
_MESH_FACTORIES = {
    "bigdl_tpu.serving.sharded.make_mesh": {"data", "model"},
    "bigdl_tpu.serving.make_mesh": {"data", "model"},
}


def _mesh_axes_from_call(ctx: FileContext,
                         call: ast.Call) -> Optional[Set[str]]:
    q = ctx.qualname(call.func)
    if q in _MESH_FACTORIES:
        return set(_MESH_FACTORIES[q])
    if q in _MESH_QUALNAMES:
        ax = _kwarg(call, "axis_names")
        if ax is None and len(call.args) >= 2:
            ax = call.args[1]
        if ax is None:
            return None
        return _const_str_set(ax)
    return None


@register
class MeshAxisRule(Rule):
    code = "SPMD106"
    name = "mesh-axis"
    summary = ("in_specs/out_specs naming an axis the shard_map's mesh "
               "does not define")
    hint = ("every axis name in in_specs/out_specs must be one of the "
            "Mesh's axis_names — a misspelled axis fails at trace time "
            "at best, silently replicates at worst; fix the spec or the "
            "Mesh construction")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ctx.by_type(ast.Call):
            q = ctx.qualname(node.func)
            if q not in _SHARD_MAP_QUALNAMES:
                continue
            mesh_arg = _kwarg(node, "mesh")
            if mesh_arg is None:
                continue
            axes: Optional[Set[str]] = None
            mesh_label = ast.unparse(mesh_arg)
            if isinstance(mesh_arg, ast.Call):
                axes = _mesh_axes_from_call(ctx, mesh_arg)
            else:
                d = ctx.dotted(mesh_arg)
                if d:
                    # scope-chain provenance (core.resolve_binding):
                    # the nearest preceding assignment wins, and a
                    # binding the analyzer cannot see into (a helper
                    # call, a parameter) SHADOWS literal constructions
                    # rather than being skipped over
                    val = ctx.resolve_binding(d, node)
                    if isinstance(val, ast.Call):
                        axes = _mesh_axes_from_call(ctx, val)
            if axes is None:
                continue           # provenance unknown — stay silent
            for kw_name in ("in_specs", "out_specs"):
                specs = _kwarg(node, kw_name)
                if specs is None:
                    continue
                for f in self._check_specs(ctx, specs, axes, kw_name,
                                           mesh_label):
                    yield f

    def _check_specs(self, ctx: FileContext, specs: ast.AST,
                     axes: Set[str], kw_name: str,
                     mesh_label: str) -> Iterator[Finding]:
        for node in ast.walk(specs):
            if not isinstance(node, ast.Call):
                continue
            if ctx.qualname(node.func) not in _PSPEC_QUALNAMES:
                continue
            for s in ast.walk(node):
                if isinstance(s, ast.Constant) and \
                        isinstance(s.value, str) and s.value not in axes:
                    yield ctx.finding(
                        s, self.code,
                        f"{kw_name} names axis `{s.value}` but mesh "
                        f"`{mesh_label}` defines axes "
                        f"{sorted(axes)}",
                        hint=self.hint)


# ==========================================================================
# The SRV2xx serving-contract family — WHOLE-PROGRAM rules.
#
# Everything below consumes the ProjectContext fact table
# (core.collect_file_facts / merge_facts): per-file fact collectors
# extract the cross-module ground truth (which attributes hold compiled
# steps, the pooled-carry key schema, the KVPool class hierarchy, the
# finish-reason vocabulary, donation signatures of helper functions),
# the engine merges them across every scanned file, and the rules below
# check each file against the MERGED table.  Single-file scans (the
# fixtures) degrade to per-file facts plus the documented fallbacks.
# ==========================================================================

#: the compiled-step caches in bigdl_tpu.models.transformer; value =
#: index of the step fn in the returned tuple (None = the call's whole
#: result IS the step fn)
_STEP_GETTERS = {
    "bigdl_tpu.models.transformer.get_decode_step": 0,
    "bigdl_tpu.models.transformer.get_batch_decode_step": 0,
    "bigdl_tpu.models.transformer.get_batch_verify_step": 0,
    "bigdl_tpu.models.transformer.get_prefill_step": None,
    "bigdl_tpu.models.transformer.get_batch_prefill_step": None,
}

#: fallback pooled-carry key schema, used only when the scan does not
#: include models/transformer.py (single-file fixture runs): must match
#: what _serving_init_carry declares
_DEFAULT_CARRY_PATTERNS = (
    "pos", "rng", "tok_counts", "prompt_mask",
    r"k\d+", r"v\d+", r"k\d+_scale", r"v\d+_scale",
)

#: fallback finish-reason vocabulary (single-file fixture runs): must
#: match ServingMetrics.FINISH_REASONS
_DEFAULT_FINISH_REASONS = frozenset(
    {"eos", "stop", "length", "shed", "deadline", "infeasible", "error",
     "cancelled"})

#: fallback serialized row-payload schema (single-file fixture runs):
#: must match serving/disagg.py's ROW_PAYLOAD_KEYS declaration
_DEFAULT_PAYLOAD_KEYS = ("request", "carry", "draft", "chunk_done",
                         "chunk_target", "adapter")

#: KVPool-lineage roots: any class whose base chain reaches a class
#: with one of these qualified-name tails owns pooled device state with
#: host mirrors
_KVPOOL_TAILS = (".KVPool",)


def _last_seg(dotted: Optional[str]) -> Optional[str]:
    return None if dotted is None else dotted.rsplit(".", 1)[-1]


def _in_serving_tree(ctx: FileContext) -> bool:
    return "bigdl_tpu/serving/" in ctx.relpath.replace("\\", "/")


def _serving_scope(ctx: FileContext) -> bool:
    """True for files the serving-contract rules police: the serving
    plane itself, plus any file that imports from it (tests, fixtures,
    a future second engine) — cached per file."""
    hit = ctx.cache.get("serving_scope")
    if hit is None:
        hit = _in_serving_tree(ctx) or any(
            m.startswith("bigdl_tpu.serving")
            or m.startswith("bigdl_tpu.models.transformer")
            for m in _imported_modules(ctx))
        ctx.cache["serving_scope"] = hit
    return hit


def _imported_modules(ctx: FileContext) -> List[str]:
    mods = ctx.cache.get("imported_modules")
    if mods is None:
        mods = []
        for node in ctx.by_type(ast.Import, ast.ImportFrom):
            if isinstance(node, ast.Import):
                mods.extend(a.name for a in node.names)
            elif node.module and node.level == 0:
                mods.append(node.module)
        ctx.cache["imported_modules"] = mods
    return mods


def _facts(ctx: FileContext) -> Dict:
    if ctx.project is not None:
        return ctx.project.facts
    # hand-built context (no engine): per-file facts only
    from bigdl_tpu.analysis.core import collect_file_facts

    return collect_file_facts(ctx)


# -- fact collectors --------------------------------------------------------

def _defines_dispatch(ctx: FileContext) -> bool:
    """True when the file defines a ``_dispatch`` routing of its own —
    the minimal-engine shape SRV201 polices outside bigdl_tpu/serving/."""
    hit = ctx.cache.get("defines_dispatch")
    if hit is None:
        hit = any(fn.name == "_dispatch"
                  for fn in ctx.by_type(ast.FunctionDef,
                                        ast.AsyncFunctionDef))
        ctx.cache["defines_dispatch"] = hit
    return hit


@_register_facts
def _step_binding_facts(ctx: FileContext) -> Dict:
    """Which attribute/variable names hold compiled steps from the
    ``get_*_step`` caches — the SRV201 ground truth.  Collected from
    files that live in dispatch scope (the serving tree, or a file
    with a ``_dispatch`` of its own) and merged, so
    ``eng._batch_prefill_fn`` used in admission.py resolves through the
    binding in engine.py.  Bindings elsewhere (``generate()``/
    ``beam_generate`` in models/, tests, benchmarks) are deliberately
    NOT tracked — their generic names (``step``) would indict every
    method called ``step`` in the engine."""
    if not (_in_serving_tree(ctx) or _defines_dispatch(ctx)):
        return {}
    attrs: Dict[str, List[str]] = {}
    for node in ctx.by_type(ast.Assign):
        if not isinstance(node.value, ast.Call):
            continue
        q = ctx.qualname(node.value.func)
        if q not in _STEP_GETTERS:
            continue
        idx = _STEP_GETTERS[q]
        for t in node.targets:
            target = t
            if idx is not None:
                if not (isinstance(t, (ast.Tuple, ast.List))
                        and len(t.elts) > idx):
                    continue
                target = t.elts[idx]
            seg = _last_seg(ctx.dotted(target))
            if seg:
                attrs.setdefault(seg, []).append(q)
    return {"step_attrs": {k: sorted(set(v))
                           for k, v in attrs.items()}} if attrs else {}


@_register_facts
def _carry_schema_facts(ctx: FileContext) -> Dict:
    """The pooled-carry key schema, extracted from the ONE layout
    declaration (``_serving_init_carry`` in models/transformer.py):
    constant keys verbatim, f-string keys with interpolations widened
    to ``\\d+`` (the layer index).  SRV202 checks every carry subscript
    against these patterns."""
    for fn in ctx.by_type(ast.FunctionDef):
        if fn.name != "_serving_init_carry":
            continue
        pats: Set[str] = set()
        for node in ast.walk(fn):
            key = None
            if isinstance(node, ast.Assign) and \
                    isinstance(node.targets[0], ast.Subscript):
                key = node.targets[0].slice
            elif isinstance(node, ast.Dict):
                for k in node.keys:
                    p = _key_pattern(k)
                    if p:
                        pats.add(p)
                continue
            p = _key_pattern(key)
            if p:
                pats.add(p)
        if pats:
            return {"carry_patterns": sorted(pats)}
    return {}


@_register_facts
def _row_payload_facts(ctx: FileContext) -> Dict:
    """The serialized row-payload key schema, extracted from the ONE
    wire-format declaration (``ROW_PAYLOAD_KEYS`` in
    serving/disagg.py).  SRV202's payload half checks every subscript
    on a ``payload``-named dict against it — the cross-module twin of
    the carry schema, so a typo'd transfer key is machine-caught
    before it ships a row that restores wrong."""
    from bigdl_tpu.analysis.core import UNRESOLVED as _UNRES
    from bigdl_tpu.analysis.core import literal_value

    for node in ctx.by_type(ast.Assign):
        if any(isinstance(t, ast.Name) and t.id == "ROW_PAYLOAD_KEYS"
               for t in node.targets):
            val = literal_value(node.value)
            if val is not _UNRES:
                return {"payload_keys": sorted(val)}
    return {}


def _key_pattern(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return re.escape(node.value)
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(re.escape(v.value))
            elif isinstance(v, ast.FormattedValue):
                parts.append(r"\d+")
            else:
                return None
        return "".join(parts)
    return None


@_register_facts
def _class_edge_facts(ctx: FileContext) -> Dict:
    """Class-inheritance edges (qualified through each file's imports)
    — SRV203 computes the KVPool lineage from the merged edge set, so
    a subclass two modules away is still covered."""
    edges: Dict[str, List[str]] = {}
    for node in ctx.by_type(ast.ClassDef):
        qual = f"{ctx.module}.{node.name}" if ctx.module else node.name
        bases = []
        for b in node.bases:
            bq = ctx.qualname(b)
            if bq is None:
                d = ctx.dotted(b)
                if d and "." not in d:
                    bq = f"{ctx.module}.{d}" if ctx.module else d
            if bq:
                bases.append(bq)
        edges[qual] = sorted(set(bases))
    return {"class_edges": edges} if edges else {}


@_register_facts
def _finish_reason_facts(ctx: FileContext) -> Dict:
    """The declared finish-reason vocabulary
    (``ServingMetrics.FINISH_REASONS``) — SRV205's schema."""
    from bigdl_tpu.analysis.core import UNRESOLVED as _UNRES
    from bigdl_tpu.analysis.core import literal_value

    for node in ctx.by_type(ast.ClassDef):
        if node.name != "ServingMetrics":
            continue
        for sub in node.body:
            if isinstance(sub, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "FINISH_REASONS"
                    for t in sub.targets):
                val = literal_value(sub.value)
                if val is not _UNRES:
                    return {"finish_reasons": sorted(val)}
    return {}


@_register_facts
def _donated_wrapper_facts(ctx: FileContext) -> Dict:
    """Module-level functions that DONATE one of their parameters (pass
    it at a donated position of a jitted-with-donation callable) —
    SRV204's cross-module half.  Keys are qualified function names;
    values are the donated caller-argument positions."""
    out: Dict[str, List[int]] = {}
    for qual, positions in _donating_wrappers(ctx).items():
        if "." not in qual:           # module-level plain function
            full = f"{ctx.module}.{qual}" if ctx.module else qual
            out[full] = sorted(positions)
    return {"donated_wrappers": out} if out else {}


def _donating_wrappers(ctx: FileContext) -> Dict[str, List[int]]:
    """name -> donated caller-arg positions, for every function in the
    file whose PARAMETER flows into a donated position of a local
    donating callable.  Methods are keyed ``self.<name>`` (positions
    already exclude ``self``); plain functions by bare name.  One level
    of lifting — a wrapper of a wrapper is out of scope (documented)."""
    cached = ctx.cache.get("donating_wrappers")
    if cached is not None:
        return cached
    donated = _donating_callables(ctx)
    wrappers: Dict[str, List[int]] = {}
    if donated:
        for fn in ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef):
            params = _param_names(fn)
            is_method = bool(params) and params[0] == "self"
            hits: Set[int] = set()
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = ctx.dotted(node.func)
                if callee not in donated:
                    continue
                for i in donated[callee]:
                    if i < len(node.args) and \
                            isinstance(node.args[i], ast.Name) and \
                            node.args[i].id in params:
                        p = params.index(node.args[i].id)
                        if is_method:
                            if p > 0:
                                hits.add(p - 1)
                        else:
                            hits.add(p)
            if hits:
                key = f"self.{fn.name}" if is_method else fn.name
                wrappers[key] = sorted(hits)
    ctx.cache["donating_wrappers"] = wrappers
    return wrappers


# -- SRV201 — dispatch bypass ----------------------------------------------

@register
class DispatchBypassRule(Rule):
    code = "SRV201"
    name = "dispatch-bypass"
    summary = ("compiled serving step invoked directly inside the "
               "serving plane instead of through engine._dispatch")
    hint = ("every serving-path device dispatch must route through "
            "`engine._dispatch(site, fn, *args)` — a direct call "
            "silently bypasses fault injection, the step watchdog, and "
            "retry accounting (serving/faults.py). Spell it "
            "`self._dispatch(\"decode\", self._step_fn, ...)`; tests "
            "and benchmarks outside serving/ may call steps directly")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # scope: the serving plane itself, or a file that defines a
        # `_dispatch` routing of its own (the fixture/minimal-engine
        # shape) — test/bench code without a dispatcher is exempt
        if not (_in_serving_tree(ctx) or _defines_dispatch(ctx)):
            return
        step_attrs = _facts(ctx).get("step_attrs", {})
        if not step_attrs:
            return
        # local aliases: `fn = self.engine._batch_prefill_fn` makes a
        # bare-name call in the SAME function a bypass too
        aliases: Dict[str, list] = {}
        for node in ctx.by_type(ast.Assign):
            seg = _last_seg(ctx.dotted(node.value)) \
                if isinstance(node.value, (ast.Name, ast.Attribute)) \
                else None
            if seg in step_attrs:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases.setdefault(t.id, []).append(
                            (ctx.enclosing_function(node), seg))
        for node in ctx.by_type(ast.Call):
            seg = None
            if isinstance(node.func, ast.Attribute):
                seg = _last_seg(ctx.dotted(node.func))
                if seg not in step_attrs:
                    continue
            elif isinstance(node.func, ast.Name):
                nm = node.func.id
                if nm in step_attrs:
                    seg = nm
                else:
                    scope = ctx.enclosing_function(node)
                    for ascope, aseg in aliases.get(nm, ()):
                        if ascope is scope:
                            seg = aseg
                            break
                if seg is None:
                    continue
            else:
                continue
            getters = step_attrs.get(seg, ["get_*_step"])
            yield ctx.finding(
                node, self.code,
                f"compiled step `{_last_seg(ctx.dotted(node.func)) or seg}`"
                f" (bound from {getters[0].rsplit('.', 1)[-1]}) invoked "
                f"directly — this dispatch bypasses engine._dispatch",
                hint=self.hint)


# -- SRV202 — carry-key schema ---------------------------------------------

@register
class CarryKeyRule(Rule):
    code = "SRV202"
    name = "carry-key-schema"
    summary = ("string key on a pooled serving carry (or serialized "
               "row payload) that its declared schema does not define")
    hint = ("pooled-carry keys are a CLOSED schema declared once in "
            "models/transformer.py:_serving_init_carry (pos, rng, "
            "tok_counts, prompt_mask, k<i>/v<i> and their _scale rows), "
            "and row-payload keys one declared in serving/disagg.py:"
            "ROW_PAYLOAD_KEYS (request, carry, draft, chunk_done, "
            "chunk_target, adapter) — a typo'd key fails only at "
            "runtime, or "
            "worse, silently creates a NEW key the step (or the "
            "handoff restore) never reads; fix the spelling or extend "
            "the schema declaration first")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _serving_scope(ctx):
            return
        facts = _facts(ctx)
        carry_pats = facts.get("carry_patterns") or \
            list(_DEFAULT_CARRY_PATTERNS)
        payload_keys = facts.get("payload_keys") or \
            list(_DEFAULT_PAYLOAD_KEYS)
        rx = {
            "carry": re.compile(
                "|".join(f"(?:{p})" for p in carry_pats)),
            "payload": re.compile(
                "|".join(re.escape(k) for k in payload_keys)),
        }
        what = {
            "carry": "the pooled-carry layout declared by "
                     "_serving_init_carry",
            "payload": "the serialized row-payload schema declared by "
                       "ROW_PAYLOAD_KEYS (serving/disagg.py)",
        }
        for node in ctx.by_type(ast.Subscript, ast.Call, ast.Compare):
            recv, key, kind = self._carry_key(ctx, node)
            if recv is None or key is None:
                continue
            if rx[kind].fullmatch(key):
                continue
            noun = "carry" if kind == "carry" else "row payload"
            yield ctx.finding(
                node, self.code,
                f"key {key!r} on {noun} `{recv}` is not in "
                f"{what[kind]}",
                hint=self.hint)

    @staticmethod
    def _carry_key(ctx: FileContext, node: ast.AST):
        """(receiver, key, schema kind) when ``node`` reads/writes a
        string key on a carry-named object (the pooled-carry schema)
        or a ``payload``-named one (the serialized row-payload schema
        — ``KVPool.row_state`` dicts and the disagg wire payloads):
        subscripts, ``.get("k")`` calls, and ``"k" in carry``
        membership tests."""
        if isinstance(node, ast.Subscript):
            recv, key = node.value, node.slice
        elif isinstance(node, ast.Call):
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get" and node.args):
                return None, None, None
            recv, key = node.func.value, node.args[0]
        else:                                   # Compare: "k" in carry
            if not (len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.In, ast.NotIn))):
                return None, None, None
            recv, key = node.comparators[0], node.left
        d = ctx.dotted(recv)
        seg = _last_seg(d)
        if seg is None:
            return None, None, None
        if "payload" in seg:
            kind = "payload"
        elif "carry" in seg:
            kind = "carry"
        else:
            return None, None, None
        if not (isinstance(key, ast.Constant)
                and isinstance(key.value, str)):
            return None, None, None
        return d, key.value, kind


# -- SRV203 — host-mirror lockstep -----------------------------------------

@register
class MirrorLockstepRule(Rule):
    code = "SRV203"
    name = "mirror-lockstep"
    summary = ("KVPool-lineage method moves the device `pos` without "
               "updating the chunk_done/chunk_target host mirrors")
    hint = ("KVPool.chunk_done/chunk_target are HOST MIRRORS of the "
            "device `pos` (the chunked-admission pump plans from them "
            "without a device readback — serving/chunked.py); any "
            "method that moves a slot's target-carry pos must keep "
            "them in lockstep (write the mirror, or delegate to "
            "write_prefill/set_pos/begin_chunks/super()). The DRAFT "
            "carry has no mirrors and is exempt")

    #: calls that move pos as a side effect (the donated reset/scatter)
    _POS_MOVERS = {"_free_reset", "_scatter"}
    #: delegating calls that already maintain the mirrors
    _MIRROR_KEEPERS = {"write_prefill", "set_pos", "begin_chunks", "free"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        lineage = self._lineage(ctx)
        if not lineage:
            return
        for cls in ctx.by_type(ast.ClassDef):
            qual = f"{ctx.module}.{cls.name}" if ctx.module else cls.name
            if qual not in lineage:
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                move = self._first_pos_move(ctx, fn)
                if move is None:
                    continue
                if self._touches_mirror(ctx, fn):
                    continue
                yield ctx.finding(
                    move, self.code,
                    f"{cls.name}.{fn.name} moves the device `pos` but "
                    f"never updates chunk_done/chunk_target — the host "
                    f"mirrors drift from the device state",
                    hint=self.hint)

    @staticmethod
    def _lineage(ctx: FileContext) -> Set[str]:
        """Classes in this PROJECT whose base chain reaches KVPool,
        computed from the merged class-edge facts (cross-module)."""
        edges = _facts(ctx).get("class_edges", {})
        out: Set[str] = set()
        for qual in edges:
            chain, todo = set(), [qual]
            while todo:
                q = todo.pop()
                if q in chain:
                    continue
                chain.add(q)
                todo.extend(edges.get(q, ()))
            if any(q.endswith(t) or q == t.lstrip(".")
                   for q in chain for t in _KVPOOL_TAILS):
                out.add(qual)
        return out

    def _first_pos_move(self, ctx: FileContext,
                        fn: ast.AST) -> Optional[ast.AST]:
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            ctx.dotted(t.value) == "self.carry" and \
                            isinstance(t.slice, ast.Constant) and \
                            t.slice.value == "pos":
                        return t
            elif isinstance(node, ast.Call):
                seg = _last_seg(ctx.dotted(node.func))
                if seg in self._POS_MOVERS and \
                        ctx.dotted(node.func) == f"self.{seg}":
                    return node
        return None

    def _touches_mirror(self, ctx: FileContext, fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and \
                    node.attr in ("chunk_done", "chunk_target") and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                return True
            if isinstance(node, ast.Call):
                d = ctx.dotted(node.func)
                seg = _last_seg(d)
                if seg in self._MIRROR_KEEPERS and d != f"self.{fn.name}" \
                        and (d or "").startswith("self."):
                    return True
                # super().free(...) etc. delegates the whole contract
                if isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Call) and \
                        isinstance(node.func.value.func, ast.Name) and \
                        node.func.value.func.id == "super":
                    return True
        return False


# -- SRV204 — interprocedural donation reuse -------------------------------

@register
class CrossDonationRule(Rule):
    code = "SRV204"
    name = "cross-donation-reuse"
    summary = ("buffer donated through a helper function (the helper "
               "passes its parameter to a donating jit) and read again "
               "by the caller")
    hint = ("SPMD104 lifted through the call graph: the helper's "
            "parameter flows into a `donate_argnums` position, so the "
            "CALLER's buffer is invalid after the helper returns — "
            "rebind the name to the helper's result (`carry = "
            "ingest(carry, u)`), exactly like the direct-donation "
            "idiom. One level of lifting; wrappers of wrappers are out "
            "of scope (docs/analysis.md)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        local = _donating_wrappers(ctx)
        xmod = _facts(ctx).get("donated_wrappers", {})
        if not local and not xmod:
            return
        for node in ctx.by_type(ast.Call):
            callee = ctx.dotted(node.func)
            if callee is None:
                continue
            positions = local.get(callee)
            label = callee
            if positions is None:
                q = ctx.qualname(node.func)
                if q:
                    hit = xmod.get(q)
                    if hit is None:
                        # module keys are path-derived; the import may
                        # spell a shorter (or sys.path-rooted) prefix —
                        # match on the dotted suffix
                        for k, v in xmod.items():
                            if k.endswith("." + q):
                                hit, q = v, k
                                break
                    if hit is not None:
                        positions, label = hit, q
            if not positions:
                continue
            # the wrapper's own body is exempt (that call is the
            # definition site, already modeled)
            scope = ctx.enclosing_function(node) or ctx.tree
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                params = _param_names(scope)
                key = f"self.{scope.name}" if params[:1] == ["self"] \
                    else scope.name
                if key == callee:
                    continue
            for i in positions:
                if i >= len(node.args):
                    continue
                buf = ctx.dotted(node.args[i])
                if buf is None or buf == "self":
                    continue
                reuse = _first_reuse(ctx, scope, buf, node)
                if reuse is not None:
                    yield ctx.finding(
                        reuse, self.code,
                        f"`{buf}` was donated THROUGH `{label}` on line "
                        f"{node.lineno} (its parameter {i} flows into a "
                        f"donate_argnums position) and is read again "
                        f"here",
                        hint=self.hint)


# -- SRV205 — finish-reason accounting -------------------------------------

@register
class FinishReasonRule(Rule):
    code = "SRV205"
    name = "finish-reason-accounting"
    summary = ("finish_reason string outside the declared "
               "ServingMetrics.FINISH_REASONS vocabulary")
    hint = ("finish reasons are a CLOSED vocabulary declared by "
            "ServingMetrics.FINISH_REASONS, and every reason has a "
            "per-reason counter path (serving/finish_<reason> via "
            "on_finish_reason) — a novel string silently escapes "
            "goodput/shed accounting and dashboards. Fix the typo, or "
            "add the reason to FINISH_REASONS + its counter first")

    #: call sites that consume a reason string: final segment -> the
    #: positional index of the reason argument
    _REASON_CALLS = {"_shed": 1, "_finish_row": 1, "on_finish_reason": 0}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _serving_scope(ctx):
            return
        vocab = _facts(ctx).get("finish_reasons")
        vocab = set(vocab) if vocab else set(_DEFAULT_FINISH_REASONS)
        for node in ctx.by_type(ast.Assign, ast.Call):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "finish_reason" and \
                            isinstance(node.value, ast.Constant) and \
                            isinstance(node.value.value, str) and \
                            node.value.value not in vocab:
                        yield ctx.finding(
                            node, self.code,
                            f"finish_reason {node.value.value!r} is not "
                            f"in ServingMetrics.FINISH_REASONS "
                            f"{sorted(vocab)}",
                            hint=self.hint)
                        break
                continue
            seg = _last_seg(ctx.dotted(node.func))
            idx = self._REASON_CALLS.get(seg or "")
            if idx is None:
                continue
            arg = node.args[idx] if idx < len(node.args) else \
                _kwarg(node, "reason")
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str) and arg.value not in vocab:
                yield ctx.finding(
                    node, self.code,
                    f"reason {arg.value!r} passed to {seg}() is not in "
                    f"ServingMetrics.FINISH_REASONS {sorted(vocab)}",
                    hint=self.hint)


# -- SRV206 — stranded rows -------------------------------------------------

@register
class StrandedRowRule(Rule):
    code = "SRV206"
    name = "stranded-row"
    summary = ("row removed from a scheduler table with no requeue, "
               "handoff, or finish disposition in scope")
    hint = ("every code path that takes a request out of a pool's "
            "running/partial tables must leave it SOMEWHERE: "
            "requeue/submit it back into a scheduler, serialize it "
            "for handoff (row_state / pack_payload), or land a "
            "FINISH_REASONS disposition (_finish_row/_ledger_finish/"
            "_shed/on_finish_reason/finish/cancel) — the static twin "
            "of the pool-failover invariant (docs/serving.md \"Pool "
            "failover and autoscaling\"). A row that silently leaves "
            "the tables strands its request: drain() never finishes "
            "it and no finish_<reason> counter accounts for it. The "
            "scheduler's own primitives (the class that OWNS the "
            "tables) are the sanctioned removal spellings and are "
            "exempt")

    #: the slot-holding scheduler tables the invariant covers (the
    #: waiting heap has its own closed drop surface — pop_waiting —
    #: inside the owning class)
    _TABLES = ("running", "partial")
    #: removal spellings on a table receiver
    _REMOVERS = ("pop", "clear", "popitem")
    #: calls that give the removed row a destination: scheduler
    #: re-entry, handoff serialization, or a finish disposition
    _KEEPERS = {"requeue", "submit", "row_state", "pack_payload",
                "finish", "_finish_row", "_ledger_finish", "_shed",
                "on_finish_reason", "cancel_running", "cancel"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _serving_scope(ctx):
            return
        for node in ctx.by_type(ast.Delete, ast.Call):
            hit = self._removal(ctx, node)
            if hit is None:
                continue
            table, recv = hit
            fn = ctx.enclosing_function(node)
            if fn is None:
                continue                 # module-level = fixture setup
            if recv == f"self.{table}" and self._class_owns_tables(ctx,
                                                                   node):
                continue                 # the owner's primitives
            if self._has_keeper(ctx, fn, node):
                continue
            verb = "del" if isinstance(node, ast.Delete) else \
                f".{node.func.attr}()"
            yield ctx.finding(
                node, self.code,
                f"row removed from `{recv}` ({verb}) with no "
                f"requeue/submit, row_state/pack_payload handoff, or "
                f"finish disposition in "
                f"`{getattr(fn, 'name', '<lambda>')}` — the request "
                f"is stranded",
                hint=self.hint)

    def _removal(self, ctx: FileContext,
                 node: ast.AST) -> Optional[Tuple[str, str]]:
        """(table, receiver-spelling) when ``node`` removes from a
        running/partial table: ``del <x>.running[...]`` or
        ``<x>.running.pop/clear/popitem(...)``."""
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    recv = ctx.dotted(t.value)
                    table = self._table_of(recv)
                    if table is not None:
                        return table, recv
            return None
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in self._REMOVERS:
            recv = ctx.dotted(node.func.value)
            table = self._table_of(recv)
            if table is not None:
                return table, recv
        return None

    def _table_of(self, recv: Optional[str]) -> Optional[str]:
        if recv is None:
            return None
        for table in self._TABLES:
            if recv == table or recv.endswith("." + table):
                return table
        return None

    def _class_owns_tables(self, ctx: FileContext,
                           node: ast.AST) -> bool:
        """Is ``node`` inside a class whose own body assigns
        ``self.running`` (the Scheduler shape)? Its methods ARE the
        sanctioned removal primitives."""
        cur = ctx.parents.get(node)
        while cur is not None and not isinstance(cur, ast.ClassDef):
            cur = ctx.parents.get(cur)
        if cur is None:
            return False
        for sub in ast.walk(cur):
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, ast.AnnAssign):   # self.running: Dict
                targets = [sub.target]
            else:
                continue
            for t in targets:
                if isinstance(t, ast.Attribute) and \
                        t.attr in self._TABLES and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    return True
        return False

    def _has_keeper(self, ctx: FileContext, fn: ast.AST,
                    removal: ast.AST) -> bool:
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) and sub is not removal:
                seg = _last_seg(ctx.dotted(sub.func))
                if seg in self._KEEPERS:
                    return True
        return False


# -- SRV207 — tier-codec bypass ---------------------------------------------

@register
class TierCodecBypassRule(Rule):
    code = "SRV207"
    name = "tier-codec-bypass"
    summary = ("row state written to a block store outside the "
               "row_state()/pack_payload codec, or device state read "
               "from a slot already freed (spilled)")
    hint = ("the host KV tier has exactly ONE wire format: a row "
            "leaves HBM as `pack_payload(request_meta(req), "
            "pool.row_state(slot))` bytes, and comes back through "
            "`unpack_payload` + `restore_row` (docs/serving.md "
            "\"Tiered KV\"). A raw row_state dict (or anything tainted "
            "by one) written into a block store skips the length-"
            "prefixed codec — the bytes are unreadable by every fetch "
            "path and the byte-identity contract silently dies. And a "
            "`pool.free(slot)` BEFORE `row_state(slot)` serializes a "
            "recycled row: spill captures whatever request owns the "
            "slot next. Pack first, free after — the order every "
            "shipping site (preemption, handoff, drain) already "
            "follows. Wrapper detection is one level deep: a helper "
            "whose parameter flows into a store `.put()` counts as a "
            "store write at its call sites")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _serving_scope(ctx):
            return
        wrappers = self._store_put_wrappers(ctx)
        for fn in ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef):
            tainted, sanitized = self._taints(ctx, fn)
            yield from self._raw_store_writes(ctx, fn, tainted,
                                              sanitized, wrappers)
            yield from self._freed_slot_reads(ctx, fn)

    # -- taint bookkeeping (per function, flow-insensitive) ---------------

    @staticmethod
    def _params(fn: ast.AST) -> List[str]:
        a = fn.args
        return [p.arg for p in (getattr(a, "posonlyargs", []) + a.args
                                + a.kwonlyargs)]

    def _taints(self, ctx: FileContext,
                fn: ast.AST) -> Tuple[Set[str], Set[str]]:
        """(tainted, sanitized) local names: tainted = carries a raw
        row-state payload (a ``payload``-named parameter, a
        ``row_state()`` result, or a copy of either); sanitized =
        assigned from ``pack_payload()`` (the codec's output is the
        ONLY sanctioned store content)."""
        tainted = {p for p in self._params(fn)
                   if p == "payload" or p.endswith("_payload")}
        sanitized: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for sub in ast.walk(fn):
                if not (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)):
                    continue
                tgt, v = sub.targets[0].id, sub.value
                if isinstance(v, ast.Call):
                    seg = _last_seg(ctx.dotted(v.func))
                    if seg == "row_state" and tgt not in tainted:
                        tainted.add(tgt)
                        changed = True
                    elif seg == "pack_payload" and tgt not in sanitized:
                        sanitized.add(tgt)
                        changed = True
                elif isinstance(v, ast.Name) and v.id in tainted \
                        and tgt not in tainted:
                    tainted.add(tgt)
                    changed = True
        return tainted, sanitized

    # -- sink 1: un-coded writes into a block store -----------------------

    def _store_put_wrappers(self, ctx: FileContext) -> Dict[str, Set[int]]:
        """Function name -> positional indices (self excluded) whose
        argument flows into a ``<...store>.put(...)`` call inside the
        function body — one level of lifting, like SRV204."""
        out: Dict[str, Set[int]] = {}
        for fn in ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef):
            params = self._params(fn)
            offset = 1 if params[:1] == ["self"] else 0
            for sub in ast.walk(fn):
                if not (isinstance(sub, ast.Call)
                        and self._is_store_put(ctx, sub)):
                    continue
                for arg in sub.args:
                    if isinstance(arg, ast.Name) and arg.id in params:
                        i = params.index(arg.id) - offset
                        if i >= 0:
                            out.setdefault(fn.name, set()).add(i)
        return out

    @staticmethod
    def _is_store_put(ctx: FileContext, call: ast.Call) -> bool:
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr == "put"):
            return False
        recv = ctx.dotted(call.func.value)
        seg = _last_seg(recv)
        return bool(seg) and "store" in seg.lower()

    def _raw_store_writes(self, ctx: FileContext, fn: ast.AST,
                          tainted: Set[str], sanitized: Set[str],
                          wrappers: Dict[str, Set[int]]
                          ) -> Iterator[Finding]:
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call) or \
                    ctx.enclosing_function(sub) is not fn:
                continue
            if self._is_store_put(ctx, sub):
                bad_args = [a for a in sub.args[1:]]      # skip the key
            else:
                seg = _last_seg(ctx.dotted(sub.func))
                positions = wrappers.get(seg or "")
                # the wrapper's own body is the modeled definition site
                if positions is None or seg == fn.name:
                    continue
                bad_args = [sub.args[i] for i in positions
                            if i < len(sub.args)]
            for arg in bad_args:
                if isinstance(arg, ast.Name) and arg.id in tainted \
                        and arg.id not in sanitized:
                    yield ctx.finding(
                        sub, self.code,
                        f"`{arg.id}` carries a raw row_state payload "
                        f"and is written into a block store without "
                        f"passing through pack_payload — the tier's "
                        f"fetch paths cannot decode it",
                        hint=self.hint)

    # -- sink 2: row_state after free (spilled-slot device read) ----------

    def _freed_slot_reads(self, ctx: FileContext,
                          fn: ast.AST) -> Iterator[Finding]:
        freed: Dict[str, int] = {}
        for sub in ast.walk(fn):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and len(sub.args) == 1
                    and isinstance(sub.args[0], ast.Name)
                    and ctx.enclosing_function(sub) is fn):
                continue
            name = sub.args[0].id
            if sub.func.attr == "free":
                freed.setdefault(name, sub.lineno)
            elif sub.func.attr == "row_state" and name in freed \
                    and freed[name] < sub.lineno:
                yield ctx.finding(
                    sub, self.code,
                    f"row_state(`{name}`) on line {sub.lineno} reads a "
                    f"slot freed on line {freed[name]} — the slot may "
                    f"already be recycled; serialize BEFORE freeing",
                    hint=self.hint)


# -- SRV208 — undeclared actuation ------------------------------------------

#: the serving plane's runtime CONTROL KNOBS — per-row / per-admitter
#: host fields the autopilot's actuator bus owns. An attribute WRITE to
#: one of these outside the declared ACTUATION_SITES (or a constructor)
#: is an undeclared actuation: it moves a knob the audit log never sees
_KNOB_ATTRS = frozenset({"chunk_budget", "max_new_tokens",
                         "draft_tokens", "draft_cap", "degrade_at",
                         "degraded"})
#: pool lifecycle transitions — actuations spelled as CALLS, not writes
_KNOB_CALLS = frozenset({"_activate_pool", "drain_pool"})
#: fallback ACTUATION_SITES vocabulary (single-file fixture runs): must
#: match serving/autopilot.py ACTUATION_SITES
_DEFAULT_ACTUATION_SITES = frozenset({
    "autopilot.ActuatorBus.set_chunk_budget",
    "autopilot.ActuatorBus.set_draft_cap",
    "autopilot.ActuatorBus.degrade_waiting",
    "autopilot.ActuatorBus.restore_waiting",
    "engine.ServingEngine._apply_degrade",
    "engine.ServingEngine._restore_degrade",
    "disagg.DisaggregatedEngine._autoscale",
    "disagg.DisaggregatedEngine._failover_pool",
})


@_register_facts
def _actuation_site_facts(ctx: FileContext) -> Dict:
    """The declared actuator vocabulary (``ACTUATION_SITES``) —
    SRV208's ground truth, extracted the way MH403 reads CLOCK_SITES."""
    for node in ctx.by_type(ast.Assign):
        if not any(isinstance(t, ast.Name) and t.id == "ACTUATION_SITES"
                   for t in node.targets):
            continue
        val = literal_value(node.value)
        if val is not UNRESOLVED:
            return {"actuation_sites": sorted(val)}
    return {}


def _actuation_sites(ctx: FileContext) -> Set[str]:
    sites = _facts(ctx).get("actuation_sites")
    return set(sites) if sites else set(_DEFAULT_ACTUATION_SITES)


@register
class UndeclaredActuationRule(Rule):
    code = "SRV208"
    name = "undeclared-actuation"
    summary = ("serving control knob mutated (chunk_budget / degrade "
               "fields / draft cap / pool activate-drain) outside the "
               "declared ACTUATION_SITES vocabulary")
    hint = ("every runtime knob the control plane moves — the chunked "
            "admitter's budget, a request's degrade fields, the "
            "speculative draft cap, pool activation/drain — goes "
            "through the declared actuator API "
            "(serving/autopilot.py ACTUATION_SITES, the FENCE_SITES / "
            "CLOCK_SITES pattern), so every actuation lands in the "
            "bus's audit log and hysteresis owns the cadence. A knob "
            "assigned anywhere else is an invisible actuation: it "
            "fights the controllers, skips the log, and breaks the "
            "replay story. Route it through ActuatorBus (or the "
            "engine's _apply_degrade/_restore_degrade), or — for a "
            "genuinely new actuator — add its unit to ACTUATION_SITES "
            "first (a reviewable one-line diff). Constructors are "
            "exempt: setting a knob's INITIAL value is configuration, "
            "not actuation")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not (_in_serving_tree(ctx) or _defines_dispatch(ctx)):
            return
        sites = _actuation_sites(ctx)

        def undeclared(node) -> Optional[str]:
            """The enclosing unit's qualname when the node sits outside
            every declared site (None = sanctioned). Module/class-body
            statements (dataclass field defaults) are declarations, not
            actuations, and constructors set initial values."""
            unit = enclosing_unit(ctx, node)
            if unit is None:
                return None
            uq = unit[0]
            if uq.rsplit(".", 1)[-1] in ("__init__", "__post_init__"):
                return None
            if any(uq == s or uq.endswith("." + s) for s in sites):
                return None
            return uq

        for node in ctx.by_type(ast.Assign, ast.AnnAssign, ast.AugAssign):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if not (isinstance(tgt, ast.Attribute)
                        and tgt.attr in _KNOB_ATTRS):
                    continue
                uq = undeclared(node)
                if uq is not None:
                    yield ctx.finding(
                        node, self.code,
                        f"control knob `.{tgt.attr}` assigned in "
                        f"`{uq}` — outside the declared "
                        f"ACTUATION_SITES vocabulary",
                        hint=self.hint)
        for node in ctx.by_type(ast.Call):
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _KNOB_CALLS):
                continue
            uq = undeclared(node)
            if uq is not None:
                yield ctx.finding(
                    node, self.code,
                    f"pool lifecycle actuation `.{node.func.attr}()` "
                    f"in `{uq}` — outside the declared "
                    f"ACTUATION_SITES vocabulary",
                    hint=self.hint)


# ==========================================================================
# The ASY3xx async-readiness family — HOT-PATH host-sync rules.
#
# The async dispatch-ahead refactor (ROADMAP "raw speed") needs the
# super-step loop to stop forcing device→host syncs it never declared.
# These rules machine-inventory every such sync: ASY301 implicit
# readbacks, ASY302 raw block_until_ready / fence-vocabulary drift,
# ASY303 host branches on un-fenced device values, ASY304 per-iteration
# readback accumulation, ASY305 wall-clock pairs timing un-fenced
# device work. All of them apply ONLY to functions reachable from the
# serving plane's hot-path roots through the merged call-graph facts
# (core.hotpath_chains) — benches, tests, and setup/teardown code are
# exempt by REACHABILITY, not by path glob. The one idiom a deliberate
# sync may wear is serving/fences.py (fence = one batched device_get,
# fence_wait = block_until_ready for timers); the rules extract its
# module + site vocabulary as facts, so the fence sites the async
# refactor will move are born machine-checked.
# ==========================================================================

#: fallback fence-site vocabulary (single-file fixture runs): must
#: match serving/fences.py FENCE_SITES
_DEFAULT_FENCE_SITES = frozenset({"decode", "verify", "draft", "prefill"})
#: host-crossing cast builtins (one positional arg = the readback shape)
_READBACK_CASTS = frozenset({"float", "int", "bool"})
#: numpy conversions that force a device value across (jnp.asarray is
#: the host→device UPLOAD and deliberately absent)
_NP_READBACK_QUALS = frozenset({"numpy.asarray", "numpy.array"})
_DEVICE_GET_QUALS = frozenset({"jax.device_get"})
_BLOCK_READY_NAME = "block_until_ready"
#: wall-clock sources (plus any `*._clock()` callable attribute — the
#: engine's injectable clock)
_CLOCK_QUALS = frozenset({"time.time", "time.perf_counter",
                          "time.monotonic", "time.process_time"})
#: calls whose RESULT lives on device: the engine's fault-routing
#: dispatcher and the pool's row slice; compiled-step attrs come from
#: the SRV201 step_attrs fact, jax factories from their qualnames
_DEVICE_CALL_SEGS = frozenset({"_dispatch", "read_row"})
_DEVICE_FACTORY_PREFIXES = ("jax.numpy.", "jax.random.", "jax.lax.")


@_register_facts
def _fence_facts(ctx: FileContext) -> Dict:
    """The declared fence-site vocabulary (``FENCE_SITES``) and the
    module that declares it — ASY301/302's ground truth, extracted the
    way SRV205 reads FINISH_REASONS."""
    for node in ctx.by_type(ast.Assign):
        if not any(isinstance(t, ast.Name) and t.id == "FENCE_SITES"
                   for t in node.targets):
            continue
        val = literal_value(node.value)
        if val is not UNRESOLVED:
            return {"fence_sites": sorted(val),
                    "fence_modules": [ctx.module]}
    return {}


def _is_fence_module(ctx: FileContext) -> bool:
    """True for the file that DECLARES the fence idiom — the one module
    allowed to spell device_get/block_until_ready raw (the compat-shim
    pattern)."""
    hit = ctx.cache.get("is_fence_module")
    if hit is None:
        hit = any(
            isinstance(t, ast.Name) and t.id == "FENCE_SITES"
            for node in ctx.by_type(ast.Assign) for t in node.targets)
        ctx.cache["is_fence_module"] = hit
    return hit


def _fence_call_kind(ctx: FileContext,
                     call: ast.Call) -> Optional[str]:
    """``"fence"``/``"fence_wait"`` when ``call`` resolves to the
    declared fence module's idiom (fallback when the fact is absent —
    single-file runs: any module spelled ``...fences``)."""
    q = ctx.qualname(call.func)
    if not q:
        return None
    mod, _, name = q.rpartition(".")
    if name not in ("fence", "fence_wait"):
        return None
    mods = _facts(ctx).get("fence_modules")
    if mods:
        if mod in mods or any(m.endswith("." + mod) or
                              mod.endswith("." + m) for m in mods):
            return name
        return None
    return name if mod.rsplit(".", 1)[-1] == "fences" else None


def _fence_sites(ctx: FileContext) -> Set[str]:
    sites = _facts(ctx).get("fence_sites")
    return set(sites) if sites else set(_DEFAULT_FENCE_SITES)


#: fallbacks for single-file fixture runs — must match serving/fences.py
_DEFAULT_WINDOW_KNOBS = frozenset({"dispatch_ahead"})
_DEFAULT_DELAYED_SITES = frozenset({"decode"})


@_register_facts
def _window_facts(ctx: FileContext) -> Dict:
    """The declared dispatch-ahead vocabulary — ``WINDOW_KNOBS`` (the
    engine knobs a window-depth guard may reference, ASY308's ground
    truth) and ``DELAYED_CONSUMER_SITES`` (the fence sites allowed to
    sit behind the window, ASY306/309's ground truth) — extracted from
    the fence module the way :func:`_fence_facts` reads FENCE_SITES."""
    out: Dict = {}
    for node in ctx.by_type(ast.Assign):
        for t in node.targets:
            if not isinstance(t, ast.Name):
                continue
            if t.id == "WINDOW_KNOBS":
                val = literal_value(node.value)
                if val is not UNRESOLVED:
                    out["window_knobs"] = sorted(val)
            elif t.id == "DELAYED_CONSUMER_SITES":
                val = literal_value(node.value)
                if val is not UNRESOLVED:
                    out["delayed_sites"] = sorted(val)
    return out


def _window_knobs(ctx: FileContext) -> Set[str]:
    v = _facts(ctx).get("window_knobs")
    return set(v) if v else set(_DEFAULT_WINDOW_KNOBS)


def _delayed_sites(ctx: FileContext) -> Set[str]:
    v = _facts(ctx).get("delayed_sites")
    return set(v) if v else set(_DEFAULT_DELAYED_SITES)


def _is_window_pop(call: ast.Call) -> bool:
    """``<recv>.popleft()`` / ``<recv>.pop(0)`` — the delayed
    consumer's oldest-first take from a window collection."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr == "popleft" and not call.args:
        return True
    return (f.attr == "pop" and len(call.args) == 1
            and isinstance(call.args[0], ast.Constant)
            and call.args[0].value == 0)


def _window_collections(ctx: FileContext) -> Set[str]:
    """Dotted receivers that ARE dispatch-ahead window collections in
    this file: something a hot unit ``.append``s DEVICE-tainted values
    into AND something is ``popleft()``/``pop(0)``ed from (the
    producer/consumer pair). Requiring the pop side keeps plain
    device-handle accumulators — the speculative plane's draft chain
    list, metric buffers — out: a window is a queue, not a list."""
    hit = ctx.cache.get("asy_window_colls")
    if hit is not None:
        return hit
    appended: Set[str] = set()
    for _qual, fn, _chain in _hot_units(ctx):
        scan = _asy_scan(ctx, fn)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append" and node.args):
                continue
            recv = ctx.dotted(node.func.value)
            if not recv:
                continue
            if any(_taint_use(ctx, a, scan.tainted_at(node.lineno))
                   for a in node.args):
                appended.add(recv)
    popped: Set[str] = set()
    if appended:
        for node in ctx.by_type(ast.Call):
            if _is_window_pop(node):
                recv = ctx.dotted(node.func.value)
                if recv:
                    popped.add(recv)
    hit = appended & popped
    ctx.cache["asy_window_colls"] = hit
    return hit


def _unit_window_role(ctx: FileContext, fn: ast.AST,
                      colls: Set[str]) -> Tuple[bool, bool]:
    """``(owns, consumes)`` for one unit: owns = appends to a window
    collection (the dispatch side), consumes = pops one (the delayed-
    consumer side). The ASY306-310 rules scope by these roles."""
    owns = consumes = False
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        recv = ctx.dotted(node.func.value)
        if recv not in colls:
            continue
        if node.func.attr == "append":
            owns = True
        elif _is_window_pop(node):
            consumes = True
    return owns, consumes


def _carry_seg(name: str) -> bool:
    """Names/attributes that ARE pooled device state by the serving
    plane's naming convention: ``carry``, ``dcarry``, ``draft_carry``,
    ``resume_carry``, ``prefill_carry``, ``_zero_carry``..."""
    return name.endswith("carry")


def _step_attr_segs(ctx: FileContext) -> Set[str]:
    segs = ctx.cache.get("asy_step_segs")
    if segs is None:
        segs = set(_facts(ctx).get("step_attrs", {}).keys())
        ctx.cache["asy_step_segs"] = segs
    return segs


def _device_call(ctx: FileContext, call: ast.Call) -> bool:
    """Calls whose result is a device value."""
    f = call.func
    if isinstance(f, (ast.Name, ast.Attribute)):
        seg = _last_seg(ctx.dotted(f))
        if seg in _DEVICE_CALL_SEGS or seg in _step_attr_segs(ctx):
            return True
    q = ctx.qualname(f)
    return bool(q) and (q.startswith(_DEVICE_FACTORY_PREFIXES)
                        or q == "jax.device_put")


def _readback_kind(ctx: FileContext, call: ast.Call) -> Optional[str]:
    """``"cast"``/``"item"``/``"np"``/``"device_get"`` when ``call`` is
    a host-crossing readback OPERATION (taint of its argument decides
    whether it is a finding)."""
    f = call.func
    if isinstance(f, ast.Name) and f.id in _READBACK_CASTS \
            and len(call.args) == 1 and not call.keywords:
        return "cast"
    if isinstance(f, ast.Attribute) and f.attr == "item" \
            and not call.args:
        return "item"
    q = ctx.qualname(f)
    if q in _NP_READBACK_QUALS:
        return "np"
    if q in _DEVICE_GET_QUALS:
        return "device_get"
    return None


def _taint_use(ctx: FileContext, expr: ast.AST,
               tainted: Set[str]) -> Optional[ast.AST]:
    """First DYNAMIC use of a device value in ``expr``: a tainted name,
    a carry-suffixed name/attribute, or a device-producing call. Static
    accessors (``x.shape``, ``len``, ``is None``) never count, and
    fence/readback calls are boundaries — their results are host
    values, judged at their own call sites."""
    out: List[ast.AST] = []

    def visit(node: ast.AST, static: bool) -> None:
        if out:
            return
        if isinstance(node, ast.Name):
            if not static and (node.id in tainted or _carry_seg(node.id)):
                out.append(node)
            return
        if isinstance(node, ast.Attribute):
            if not static and _carry_seg(node.attr):
                out.append(node)
                return
            visit(node.value, static or node.attr in _STATIC_ATTRS)
            return
        if isinstance(node, ast.Call):
            if _fence_call_kind(ctx, node) or _readback_kind(ctx, node):
                return
            if _device_call(ctx, node):
                if not static:
                    out.append(node)
                return
            fname = node.func.id if isinstance(node.func, ast.Name) \
                else None
            inner_static = static or fname in _STATIC_CALLS
            for child in list(node.args) + \
                    [kw.value for kw in node.keywords]:
                visit(child, inner_static)
            if not isinstance(node.func, ast.Name):
                visit(node.func, static)
            return
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                for child in [node.left] + list(node.comparators):
                    visit(child, True)
                return
            if all(isinstance(op, (ast.In, ast.NotIn))
                   for op in node.ops):
                # key membership ("rng" in carry) inspects the carry
                # DICT's structure on host — never a device sync; only
                # the probed value itself can be one
                visit(node.left, static)
                for child in node.comparators:
                    visit(child, True)
                return
        for child in ast.iter_child_nodes(node):
            visit(child, static)

    visit(expr, False)
    return out[0] if out else None


def _hot_chains(ctx: FileContext) -> Dict[str, Tuple[str, ...]]:
    """unit qual -> root chain, for every unit reachable from a
    hot-path root (project-memoized — one BFS per analyzer run)."""
    proj = ctx.project
    if proj is not None:
        hit = proj.cache.get("hotpath_chains")
        if hit is None:
            hit = proj.cache["hotpath_chains"] = hotpath_chains(
                proj.facts)
        return hit
    return hotpath_chains(_facts(ctx))


def _target_names_of(target: ast.AST) -> List[str]:
    """Plain names bound by an assignment/loop target (tuple/list
    destructuring included) — shared by the ASY device-taint and MH
    divergence-taint timelines."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in target.elts:
            out.extend(_target_names_of(e))
        return out
    return []


def _taint_state_at(events: Dict[str, List[Tuple[int, bool]]],
                    line: int) -> Set[str]:
    """Names whose last taint event at or before ``line`` is True —
    the one timeline-replay rule both taint scans share."""
    out: Set[str] = set()
    for name, evs in events.items():
        state = False
        for ln, val in evs:
            if ln > line:
                break
            state = val
        if state:
            out.add(name)
    return out


class _AsyScan:
    """One shared pass over a hot unit: the device-taint timeline, the
    readback/fence/dispatch/clock inventories, and the loop-accumulation
    claims — every ASY rule reads this instead of re-walking."""

    def __init__(self, ctx: FileContext, fn: ast.AST) -> None:
        self.ctx = ctx
        self.fn = fn
        #: name -> [(line, tainted_bool)] in line order
        self.events: Dict[str, List[Tuple[int, bool]]] = {}
        #: lines of super-step device dispatches (_dispatch / step attrs)
        self.dispatch_lines: List[int] = []
        #: lines where the pending device work is SYNCED (fences,
        #: block_until_ready, readbacks of tainted values)
        self.sync_lines: List[int] = []
        #: (call node, kind, site literal or None) for fence idiom calls
        self.fences: List[Tuple[ast.Call, str, Optional[str]]] = []
        #: (call node, kind, offending use) readback candidates
        self.readbacks: List[Tuple[ast.Call, str, Optional[ast.AST]]] = []
        #: block_until_ready call nodes
        self.blocks: List[ast.AST] = []
        #: clock-call assignment targets: name -> [assign lines]
        self.clock_assigns: Dict[str, List[int]] = {}
        #: loads of clock targets: (node, name, line)
        self.clock_loads: List[Tuple[ast.AST, str, int]] = []
        #: node ids of readbacks claimed by loop accumulation (ASY304)
        self.accum_claimed: Set[int] = set()
        #: (accumulation node, inner readback call) ASY304 findings
        self.accumulations: List[Tuple[ast.AST, ast.Call]] = []
        self._build()

    # -- taint timeline (shared replay rule: _taint_state_at) ---------------

    def tainted_at(self, line: int) -> Set[str]:
        return _taint_state_at(self.events, line)

    def _target_names(self, target: ast.AST) -> List[str]:
        return _target_names_of(target)

    def _build(self) -> None:
        ctx = self.ctx
        cur: Set[str] = set()

        def mark(names: List[str], line: int, val: bool) -> None:
            for n in names:
                if val:
                    cur.add(n)
                elif n in cur:
                    cur.discard(n)
                else:
                    continue
                self.events.setdefault(n, []).append((line, val))

        stmts = sorted(
            (n for n in ast.walk(self.fn)
             if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                               ast.For, ast.Call, ast.Name, ast.If,
                               ast.While))),
            key=lambda n: (getattr(n, "lineno", 0),
                           getattr(n, "col_offset", 0)))
        clock_targets: Set[str] = set()
        for node in stmts:
            line = getattr(node, "lineno", 0)
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if value is None:
                    continue
                # elementwise tuple unpacking: `best, n = node, i + m`
                # must not smear one element's taint onto the others
                if len(targets) == 1 and \
                        isinstance(targets[0], (ast.Tuple, ast.List)) and \
                        isinstance(value, (ast.Tuple, ast.List)) and \
                        len(targets[0].elts) == len(value.elts):
                    for t, v in zip(targets[0].elts, value.elts):
                        mark(self._target_names(t), line,
                             bool(_taint_use(ctx, v, cur)))
                    continue
                names = []
                for t in targets:
                    names.extend(self._target_names(t))
                if isinstance(value, ast.Call):
                    kind = _fence_call_kind(ctx, value)
                    if kind == "fence":
                        mark(names, line, False)     # host copies
                        continue
                    if kind == "fence_wait":
                        # same (device) tree back — taint passes through
                        mark(names, line, bool(
                            any(_taint_use(ctx, a, cur)
                                for a in value.args)))
                        continue
                    if _readback_kind(ctx, value):
                        mark(names, line, False)     # host value now
                        continue
                    if self._is_clock_call(value) and len(names) == 1:
                        self.clock_assigns.setdefault(
                            names[0], []).append(line)
                        clock_targets.add(names[0])
                        continue
                mark(names, line, bool(_taint_use(ctx, value, cur)))
            elif isinstance(node, ast.AugAssign):
                names = self._target_names(node.target)
                if _taint_use(ctx, node.value, cur):
                    mark(names, line, True)
            elif isinstance(node, ast.For):
                names = self._target_names(node.target)
                mark(names, line, bool(_taint_use(ctx, node.iter, cur)))
            elif isinstance(node, ast.Name):
                if isinstance(getattr(node, "ctx", None), ast.Load) and \
                        node.id in clock_targets:
                    self.clock_loads.append((node, node.id, node.lineno))

        # second pass: calls (dispatches, fences, readbacks, blocks)
        for node in ast.walk(self.fn):
            if not isinstance(node, ast.Call):
                continue
            line = node.lineno
            kind = _fence_call_kind(ctx, node)
            if kind:
                site = None
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    site = node.args[0].value
                self.fences.append((node, kind, site))
                self.sync_lines.append(line)
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr == _BLOCK_READY_NAME:
                self.blocks.append(node)
                self.sync_lines.append(line)
                continue
            q = ctx.qualname(f)
            if q == f"jax.{_BLOCK_READY_NAME}":
                self.blocks.append(node)
                self.sync_lines.append(line)
                continue
            rb = _readback_kind(ctx, node)
            if rb:
                tainted = self.tainted_at(line)
                if rb == "device_get":
                    self.readbacks.append((node, rb, node))
                    self.sync_lines.append(line)
                    continue
                src = node.func.value if rb == "item" else node.args[0]
                off = _taint_use(ctx, src, tainted)
                if off is not None:
                    self.readbacks.append((node, rb, off))
                    self.sync_lines.append(line)
                continue
            if isinstance(f, (ast.Name, ast.Attribute)):
                seg = _last_seg(ctx.dotted(f))
                if seg in _DEVICE_CALL_SEGS - {"read_row"} or \
                        seg in _step_attr_segs(ctx):
                    self.dispatch_lines.append(line)

        # third pass: loop accumulation of readbacks (ASY304 claims)
        rb_by_id = {id(n): (n, k, o) for n, k, o in self.readbacks}
        for loop in (n for n in ast.walk(self.fn)
                     if isinstance(n, (ast.For, ast.While))):
            for node in ast.walk(loop):
                value = None
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in ("append", "extend") and \
                        len(node.args) == 1:
                    value = node.args[0]
                elif isinstance(node, ast.AugAssign):
                    value = node.value
                if value is None:
                    continue
                for sub in ast.walk(value):
                    hit = rb_by_id.get(id(sub))
                    if hit is not None and id(sub) not in \
                            self.accum_claimed:
                        self.accum_claimed.add(id(sub))
                        self.accumulations.append((node, hit[0]))
                        break

    def _is_clock_call(self, call: ast.Call) -> bool:
        q = self.ctx.qualname(call.func)
        if q in _CLOCK_QUALS:
            return True
        seg = _last_seg(self.ctx.dotted(call.func))
        return seg == "_clock" and not call.args


def _asy_scan(ctx: FileContext, fn: ast.AST) -> _AsyScan:
    key = ("asy_scan", id(fn))
    hit = ctx.cache.get(key)
    if hit is None:
        hit = ctx.cache[key] = _AsyScan(ctx, fn)
    return hit


def _hot_units(ctx: FileContext):
    """(qual, fn, chain) for this file's hot-path-reachable units."""
    if _is_fence_module(ctx):
        return
    chains = _hot_chains(ctx)
    if not chains:
        return
    for qual, fn, _cls in _unit_functions(ctx):
        chain = chains.get(qual)
        if chain is not None:
            yield qual, fn, chain


# -- ASY301 — implicit device→host readback on the hot path ----------------

@register
class HotReadbackRule(Rule):
    code = "ASY301"
    name = "hot-readback"
    summary = ("implicit device→host readback (.item/float/int/bool/"
               "np.asarray/device_get) on a hot-path-reachable function")
    hint = ("every device→host crossing on the super-step hot path "
            "must wear the fence idiom — "
            "`fence(\"<site>\", *values)` (serving/fences.py) does ONE "
            "batched jax.device_get and returns host arrays, so "
            "downstream bookkeeping never syncs again. Batch several "
            "small readbacks into one fence; cold code (benches, "
            "tests, setup) is exempt by call-graph reachability")

    _KINDS = {"cast": "host cast", "item": ".item()",
              "np": "np.asarray/np.array",
              "device_get": "raw jax.device_get"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for qual, fn, chain in _hot_units(ctx):
            scan = _asy_scan(ctx, fn)
            for node, kind, off in scan.readbacks:
                if id(node) in scan.accum_claimed:
                    continue                    # ASY304 owns it
                what = ast.unparse(off)[:40] if off is not None else ""
                yield ctx.finding(
                    node, self.code,
                    f"{self._KINDS[kind]} readback of device value "
                    f"`{what}` in `{qual}` — hot-path-reachable "
                    f"(via {' -> '.join(chain)})",
                    hint=self.hint)


# -- ASY302 — block_until_ready / fence vocabulary drift -------------------

@register
class UnfencedBlockRule(Rule):
    code = "ASY302"
    name = "unfenced-block"
    summary = ("block_until_ready outside the declared fence module, "
               "or a fence site string outside FENCE_SITES, on the "
               "hot path")
    hint = ("deliberate completion waits wear the fence idiom: "
            "`fence_wait(\"<site>\", tree)` (serving/fences.py) is the "
            "ONE designated home of block_until_ready, and its site "
            "vocabulary is CLOSED (FENCE_SITES) so the async refactor "
            "can enumerate every sync point it must move. Add new "
            "sites to FENCE_SITES first")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        sites = _fence_sites(ctx)
        for qual, fn, chain in _hot_units(ctx):
            scan = _asy_scan(ctx, fn)
            for node in scan.blocks:
                yield ctx.finding(
                    node, self.code,
                    f"raw block_until_ready in `{qual}` — hot-path-"
                    f"reachable (via {' -> '.join(chain)}) and outside "
                    f"the declared fence module",
                    hint=self.hint)
            for node, kind, site in scan.fences:
                if site is not None and site not in sites:
                    yield ctx.finding(
                        node, self.code,
                        f"{kind} site {site!r} is not in the declared "
                        f"FENCE_SITES vocabulary {sorted(sites)}",
                        hint=self.hint)


# -- ASY303 — host control flow on un-fenced device values ------------------

@register
class LoopBranchSyncRule(Rule):
    code = "ASY303"
    name = "hot-branch-sync"
    summary = ("Python branch (if/while/ternary/assert) on an un-fenced "
               "device value in a hot-path-reachable function")
    hint = ("a Python branch needs a concrete bool, so it SYNCS the "
            "host on the whole pending device pipeline — exactly the "
            "stall the async dispatch-ahead loop must not pay. Branch "
            "on values from a declared `fence(...)` readback (host "
            "arrays), keep pure host mirrors (KVPool.chunk_done), or "
            "move the decision on-device (lax.cond/jnp.where)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for qual, fn, chain in _hot_units(ctx):
            scan = _asy_scan(ctx, fn)
            seen: Set[Tuple[int, int]] = set()
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While, ast.IfExp,
                                         ast.Assert)):
                    continue
                off = _taint_use(ctx, node.test,
                                 scan.tainted_at(node.lineno))
                if off is None:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                kind = {ast.If: "if", ast.While: "while",
                        ast.IfExp: "conditional expression",
                        ast.Assert: "assert"}[type(node)]
                yield ctx.finding(
                    node, self.code,
                    f"`{kind}` on un-fenced device value "
                    f"`{ast.unparse(off)[:40]}` in `{qual}` — forces a "
                    f"host sync before the next dispatch "
                    f"(hot via {' -> '.join(chain)})",
                    hint=self.hint)


# -- ASY304 — per-iteration readback accumulation ---------------------------

@register
class ReadbackAccumulationRule(Rule):
    code = "ASY304"
    name = "readback-accumulation"
    summary = ("append/+= of a per-iteration device readback inside a "
               "hot-path loop — one host sync per iteration")
    hint = ("accumulating readbacks item by item syncs the device "
            "EVERY iteration; batch them — keep the loop on device "
            "values (accumulating device handles is free) and cross to "
            "host ONCE per step through a single `fence(...)` of the "
            "small results, then do the host bookkeeping between "
            "fences")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for qual, fn, chain in _hot_units(ctx):
            scan = _asy_scan(ctx, fn)
            for node, rb in scan.accumulations:
                yield ctx.finding(
                    node, self.code,
                    f"per-iteration readback "
                    f"`{ast.unparse(rb)[:48]}` accumulated inside a "
                    f"loop in `{qual}` (hot via "
                    f"{' -> '.join(chain)}) — one device sync per "
                    f"iteration",
                    hint=self.hint)


# -- ASY305 — wall-clock reads straddling un-fenced device work -------------

@register
class ClockStraddleRule(Rule):
    code = "ASY305"
    name = "clock-straddle"
    summary = ("clock-read pair timing a device dispatch with no fence "
               "between dispatch and the second read — the measured "
               "time is launch latency, not work")
    hint = ("under async dispatch the host clock keeps running while "
            "the device works, so `t1 - t0` around an un-synced "
            "dispatch measures only the LAUNCH — decode_gap_s, phase "
            "timers, and the watchdog all lie. Pin the timer to a "
            "fence: `fence_wait(\"<site>\", out)` (or consume the "
            "step's `fence(...)` readback) before reading the clock "
            "again")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        colls = _window_collections(ctx)
        for qual, fn, chain in _hot_units(ctx):
            scan = _asy_scan(ctx, fn)
            if not scan.dispatch_lines:
                continue
            # the entry-timestamp idiom is NOT a straddle: a pre-
            # dispatch clock read riding a window-collection append
            # (`win.append(Entry(..., t0, ...))`) is consumed by the
            # DELAYED consumer, which measures elapsed against it
            # strictly after its own fence — the pin ASY305 wants is
            # the entry's consumption, and ASY310 checks that side
            stamped: Set[int] = set()
            if colls:
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr == "append"
                            and ctx.dotted(node.func.value) in colls):
                        for a in node.args:
                            for sub in ast.walk(a):
                                stamped.add(id(sub))
            for name, assigns in scan.clock_assigns.items():
                for i, a_line in enumerate(assigns):
                    next_assign = assigns[i + 1] if i + 1 < len(assigns) \
                        else float("inf")
                    loads = sorted(
                        ((node, ln) for node, n, ln in scan.clock_loads
                         if n == name and a_line < ln < next_assign),
                        key=lambda t: t[1])
                    for node, ln in loads:
                        if id(node) in stamped:
                            continue
                        bad = any(
                            a_line < d < ln and not any(
                                d < s <= ln for s in scan.sync_lines)
                            for d in scan.dispatch_lines)
                        if bad:
                            yield ctx.finding(
                                node, self.code,
                                f"clock pair `{name}` (set line "
                                f"{a_line}) read here straddles an "
                                f"un-fenced device dispatch in "
                                f"`{qual}` (hot via "
                                f"{' -> '.join(chain)}) — the elapsed "
                                f"time measures the launch, not the "
                                f"work",
                                hint=self.hint)
                            break


# ==========================================================================
# ASY306-310 — the dispatch-ahead discipline (analyzer tier 5).
#
# The delayed-consumer refactor (ServingEngine dispatch_ahead=W —
# docs/serving.md "Dispatch-ahead decode") keeps up to W decode
# dispatches in flight BEHIND the fence that consumes them. Four
# orderings make that window wrong and one makes it lie, and each is a
# static shape: consuming a deferred readback into the SAME step's
# dispatch (ASY306), re-donating a carry the in-flight window still
# owns (ASY307), bounding the window by anything but a declared knob
# (ASY308), an extra fence inside the dispatch side re-serializing the
# window (ASY309), and a delayed consumer that stopped reading the
# clock, starving the watchdog and fault replay (ASY310). A "window"
# is detected structurally — a collection hot units append
# device-tainted values into AND pop oldest-first from
# (_window_collections) — so the rules were born BEFORE the refactor
# landed and gate every future one.
# ==========================================================================


# -- ASY306 — deferred readback consumed into the same step's dispatch ------

@register
class StaleConsumerRule(Rule):
    code = "ASY306"
    name = "stale-consumer"
    summary = ("a delayed-site fence readback feeds a value back into "
               "a dispatch LATER in the same unit — consume-before-"
               "dispatch ordering the window must not have")
    hint = ("a deferred fence's readback (tokens, finish verdicts, "
            "ban flips) is W steps STALE — feeding it into the same "
            "unit's next dispatch silently re-serializes the window "
            "(the dispatch must wait for the fence) or, worse, chains "
            "the wrong tokens. Chain steady-state dispatches on the "
            "previous dispatch's DEVICE handle and keep the fenced "
            "host values in the delayed consumer's bookkeeping "
            "(ServingEngine._consume_window); flush the window before "
            "any dispatch that needs host-consumed state")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        dsites = _delayed_sites(ctx)
        step_segs = _step_attr_segs(ctx)
        for qual, fn, chain in _hot_units(ctx):
            scan = _asy_scan(ctx, fn)
            fence_ids = {id(node) for node, kind, site in scan.fences
                         if kind == "fence" and site in dsites}
            if not fence_ids:
                continue
            # names bound FROM a delayed-site fence, with simple
            # forward propagation through assignments (`toks =
            # jnp.asarray(nxt)` keeps the taint); name -> bind line
            bound: Dict[str, int] = {}
            assigns = sorted(
                (n for n in ast.walk(fn) if isinstance(n, ast.Assign)),
                key=lambda n: n.lineno)
            for node in assigns:
                names: List[str] = []
                for t in node.targets:
                    names.extend(_target_names_of(t))
                if id(node.value) in fence_ids:
                    for n in names:
                        bound.setdefault(n, node.lineno)
                    continue
                if isinstance(node.value, ast.Call) and \
                        isinstance(node.value.func,
                                   (ast.Name, ast.Attribute)):
                    seg = _last_seg(ctx.dotted(node.value.func))
                    if seg in _DEVICE_CALL_SEGS or seg in step_segs:
                        # a dispatch RESULT is a fresh device handle —
                        # chaining the next dispatch on it is exactly
                        # the sanctioned steady-state pattern, so the
                        # stale-host taint stops here (the stale value
                        # already fired on the dispatch's own args)
                        continue
                if any(isinstance(sub, ast.Name) and sub.id in bound
                       and sub.lineno > bound[sub.id]
                       for sub in ast.walk(node.value)):
                    for n in names:
                        bound.setdefault(n, node.lineno)
            if not bound:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if not isinstance(f, (ast.Name, ast.Attribute)):
                    continue
                seg = _last_seg(ctx.dotted(f))
                if seg not in _DEVICE_CALL_SEGS - {"read_row"} and \
                        seg not in step_segs:
                    continue
                hit = next(
                    (sub for a in list(node.args) +
                     [kw.value for kw in node.keywords]
                     for sub in ast.walk(a)
                     if isinstance(sub, ast.Name) and sub.id in bound
                     and node.lineno > bound[sub.id]), None)
                if hit is not None:
                    yield ctx.finding(
                        node, self.code,
                        f"delayed-site fence readback `{hit.id}` "
                        f"(consumed line {bound[hit.id]}) feeds this "
                        f"dispatch in `{qual}` (hot via "
                        f"{' -> '.join(chain)}) — the window must "
                        f"dispatch from device handles, not "
                        f"just-fenced host state",
                        hint=self.hint)


# -- ASY307 — carry donated again while the window still owns it ------------

@register
class WindowDonationRule(Rule):
    code = "ASY307"
    name = "window-donation"
    summary = ("a carry buffer donated to an in-flight (not-yet-"
               "fenced) dispatch is read or donated again before it "
               "is rebound — use-after-donate lifted to the multi-"
               "step window")
    hint = ("every dispatch DONATES its carry argument (the buffer is "
            "dead the moment the call is issued — SPMD104/SRV204); "
            "with W dispatches in flight the live buffer is the LAST "
            "dispatch's return, so touching the donated spelling "
            "before rebinding it reads freed memory W steps early. "
            "Rebind on the same line (`_, carry = dispatch(..., "
            "carry)`) or immediately commit the returned carry "
            "(`pool.carry = carry`) before anything else reads it")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        colls = _window_collections(ctx)
        if not colls:
            return
        step_segs = _step_attr_segs(ctx)
        for qual, fn, chain in _hot_units(ctx):
            owns, consumes = _unit_window_role(ctx, fn, colls)
            if not (owns or consumes):
                continue
            # (line, kind, dotted, node) timeline of carry donations,
            # loads, and stores, replayed in line order per spelling
            events: List[Tuple[int, int, str, str, ast.AST]] = []
            donated_ids: Set[int] = set()
            # `_, carry = dispatch(..., carry)` rebinds the donated
            # spelling in the SAME statement — the sanctioned idiom;
            # that donation is cleared the instant the call returns
            rebinds: Dict[int, Set[str]] = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    tgts: Set[str] = set()
                    for t in node.targets:
                        for sub in ast.walk(t):
                            d = ctx.dotted(sub)
                            if d:
                                tgts.add(d)
                    rebinds[id(node.value)] = tgts
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, (ast.Name, ast.Attribute)):
                    seg = _last_seg(ctx.dotted(node.func))
                    if seg in _DEVICE_CALL_SEGS - {"read_row"} or \
                            seg in step_segs:
                        for a in node.args:
                            d = ctx.dotted(a)
                            if d and _carry_seg(_last_seg(d)):
                                if d in rebinds.get(id(node), ()):
                                    for sub in ast.walk(a):
                                        donated_ids.add(id(sub))
                                    continue
                                # the donation anchors at the ARG's own
                                # position (multi-line calls), and the
                                # arg is the donation, not a read of it
                                for sub in ast.walk(a):
                                    donated_ids.add(id(sub))
                                events.append(
                                    (a.lineno, a.col_offset,
                                     "donate", d, node))
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        for sub in ast.walk(t):
                            d = ctx.dotted(sub)
                            if d and _carry_seg(_last_seg(d)):
                                events.append(
                                    (node.lineno, -1, "store", d, sub))
                elif isinstance(node, (ast.Name, ast.Attribute)) and \
                        isinstance(getattr(node, "ctx", None), ast.Load):
                    if id(node) in donated_ids:
                        continue
                    d = ctx.dotted(node)
                    if d and _carry_seg(_last_seg(d)):
                        events.append((node.lineno, node.col_offset,
                                       "load", d, node))
            by_name: Dict[str, List] = {}
            for ev in sorted(events, key=lambda e: (e[0], e[1])):
                by_name.setdefault(ev[3], []).append(ev)
            for name, evs in by_name.items():
                donated_at: Optional[int] = None
                for line, _col, kind, _d, node in evs:
                    if kind == "store":
                        donated_at = None    # rebound: live again
                        # (a same-line store — `_, c = disp(..., c)` —
                        # clears the donation it rode in on too)
                    elif kind == "donate":
                        if donated_at is not None and line > donated_at:
                            yield ctx.finding(
                                node, self.code,
                                f"carry `{name}` donated again here "
                                f"while an in-flight dispatch (line "
                                f"{donated_at}) still owns it, in "
                                f"`{qual}` (hot via "
                                f"{' -> '.join(chain)})",
                                hint=self.hint)
                            break
                        donated_at = line
                    elif kind == "load" and donated_at is not None \
                            and line > donated_at:
                        yield ctx.finding(
                            node, self.code,
                            f"carry `{name}` read here after being "
                            f"donated to the in-flight dispatch at "
                            f"line {donated_at} in `{qual}` (hot via "
                            f"{' -> '.join(chain)}) — rebind it from "
                            f"the dispatch's return first",
                            hint=self.hint)
                        break


# -- ASY308 — window depth not bound by a declared knob ---------------------

@register
class UnboundedWindowRule(Rule):
    code = "ASY308"
    name = "unbounded-window"
    summary = ("a dispatch-ahead window depth guard that does not "
               "reference a declared WINDOW_KNOBS engine knob — a "
               "literal or bare counter bounds the window")
    hint = ("the window depth is an ENGINE CONTRACT (W=0 must be the "
            "fence-immediately engine, byte for byte), so every depth "
            "guard must read a knob from the declared WINDOW_KNOBS "
            "vocabulary (serving/fences.py — the FENCE_SITES pattern): "
            "`while len(self._window) > self.dispatch_ahead`. A "
            "literal depth or a bare loop counter is vocabulary drift "
            "the W-sweep contracts cannot reach")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        colls = _window_collections(ctx)
        if not colls:
            return
        knobs = _window_knobs(ctx)

        def knob_ref(expr: ast.AST) -> bool:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Attribute) and \
                        sub.attr.lstrip("_") in knobs:
                    return True
                if isinstance(sub, ast.Name) and \
                        sub.id.lstrip("_") in knobs:
                    return True
            return False

        def len_of_window(expr: ast.AST) -> bool:
            return any(
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "len" and len(sub.args) == 1
                and ctx.dotted(sub.args[0]) in colls
                for sub in ast.walk(expr))

        def has_window_append(body_node: ast.AST) -> bool:
            return any(
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "append"
                and ctx.dotted(sub.func.value) in colls
                for sub in ast.walk(body_node))

        for qual, fn, chain in _hot_units(ctx):
            owns, _consumes = _unit_window_role(ctx, fn, colls)
            if not owns:
                continue       # the consumer's `while window:` drain
                               # is truthiness, not a depth bound
            for node in ast.walk(fn):
                if isinstance(node, (ast.While, ast.If)):
                    if len_of_window(node.test) and \
                            not knob_ref(node.test):
                        yield ctx.finding(
                            node, self.code,
                            f"window depth guard "
                            f"`{ast.unparse(node.test)[:48]}` in "
                            f"`{qual}` (hot via {' -> '.join(chain)}) "
                            f"references no declared WINDOW_KNOBS "
                            f"knob {sorted(knobs)}",
                            hint=self.hint)
                elif isinstance(node, ast.For):
                    if has_window_append(node) and \
                            not knob_ref(node.iter):
                        yield ctx.finding(
                            node, self.code,
                            f"dispatch-ahead loop "
                            f"`for {ast.unparse(node.target)} in "
                            f"{ast.unparse(node.iter)[:40]}` fills a "
                            f"window in `{qual}` (hot via "
                            f"{' -> '.join(chain)}) without a "
                            f"declared WINDOW_KNOBS bound "
                            f"{sorted(knobs)}",
                            hint=self.hint)


# -- ASY309 — a fence inside the dispatch side of the window ----------------

@register
class InWindowFenceRule(Rule):
    code = "ASY309"
    name = "in-window-fence"
    summary = ("a fence/fence_wait site other than the declared "
               "delayed-consumer readback inside a window-DISPATCHING "
               "unit — re-serializes the window by accident")
    hint = ("the dispatch side of a dispatch-ahead window must not "
            "wait on the device AT ALL — any fence there drains the "
            "whole pipeline before the next dispatch, silently "
            "turning W back into 0. Exactly the DELAYED_CONSUMER_SITES"
            " readbacks (serving/fences.py) may be consumed against "
            "the window, and they belong in the delayed consumer "
            "(ServingEngine._consume_window), not the dispatch loop; "
            "move any other sync out of the window-owning unit")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        colls = _window_collections(ctx)
        if not colls:
            return
        dsites = _delayed_sites(ctx)
        for qual, fn, chain in _hot_units(ctx):
            owns, _consumes = _unit_window_role(ctx, fn, colls)
            if not owns:
                continue
            scan = _asy_scan(ctx, fn)
            for node, kind, site in scan.fences:
                if kind == "fence" and site in dsites:
                    continue   # the declared delayed readback (W=0
                               # consumes it inline; ASY306 guards the
                               # ordering either way)
                yield ctx.finding(
                    node, self.code,
                    f"{kind}:{site or '?'} inside window-dispatching "
                    f"unit `{qual}` (hot via {' -> '.join(chain)}) — "
                    f"re-serializes the dispatch-ahead window",
                    hint=self.hint)


# -- ASY310 — delayed consumer without a clock sample -----------------------

@register
class UnpairedDeferredClockRule(Rule):
    code = "ASY310"
    name = "unpaired-deferred-clock"
    summary = ("a window-consuming unit fences a delayed site without "
               "reading the engine clock — the deferred sample is "
               "unpaired, so watchdog + fault replay go blind")
    hint = ("every deferred fence consumption must advance/read the "
            "engine's virtual clock: the watchdog's elapsed "
            "(dispatch t0 -> fence landed) is what catches a stalled "
            "deferred readback, and byte-identical fault replay keys "
            "off those clock samples. Bracket the fence with "
            "`self._clock()` reads (the fence_wait phase + the "
            "entry-elapsed watchdog sample, as "
            "ServingEngine._consume_window does)")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        colls = _window_collections(ctx)
        if not colls:
            return
        dsites = _delayed_sites(ctx)
        for qual, fn, chain in _hot_units(ctx):
            _owns, consumes = _unit_window_role(ctx, fn, colls)
            if not consumes:
                continue
            scan = _asy_scan(ctx, fn)
            deferred = [node for node, kind, site in scan.fences
                        if kind == "fence" and site in dsites]
            if not deferred:
                continue
            has_clock = any(
                isinstance(node, ast.Call) and scan._is_clock_call(node)
                for node in ast.walk(fn))
            if not has_clock:
                yield ctx.finding(
                    deferred[0], self.code,
                    f"delayed consumer `{qual}` (hot via "
                    f"{' -> '.join(chain)}) fences a deferred site "
                    f"with NO engine-clock read — the watchdog's "
                    f"elapsed and fault replay lose their sample",
                    hint=self.hint)


# ==========================================================================
# The MH4xx multi-host lockstep & determinism family.
#
# The next serving tier runs the disaggregated pools process-per-host
# over CoordServiceBlockStore on a real jax.distributed pod, and the
# bug class that kills SPMD pods is SILENT LOCKSTEP DIVERGENCE: one
# process traces a different program, calls a collective the others
# skip, or makes a routing/replay decision from wall-clock or unseeded
# randomness the other processes don't share. Every worker must execute
# the identical step sequence (the synchronous-AllReduce design of the
# BigDL reference and the MLPerf pod-scaling work both hinge on it).
#
# The machinery is a DIVERGENCE-TAINT layer on the existing
# interprocedural call graph:
#
# * values derived from ``jax.process_index()`` or per-peer block-store
#   reads (``try_get``/``get_blocking`` on a store) are
#   *process-divergent* — each process sees a different value.
#   ``jax.process_count()`` is recorded as a divergence ROOT for the
#   worksheet (``--report lockstep``) but is pod-uniform in a healthy
#   pod, so branches on it are lockstep-safe and exempt from MH401;
# * facts record which units invoke cross-process AGREEMENT POINTS:
#   collectives (psum / all_gather / ppermute ...), compiled-step
#   dispatches (``_dispatch`` / step-attr calls — every process must
#   trace and launch the same program), and block-store barriers /
#   straggler waits (``get_blocking`` / ``get_weights``);
# * a reverse reachability closure over the merged call edges answers
#   "does this call reach an agreement point?" project-wide.
#
# On top of that: MH401 divergent branch reaching an agreement point
# (the classic trace-divergence pod hang), MH402 collectives/handoffs
# issued from unordered-set iteration (PYTHONHASHSEED makes set order
# per-process), MH403 raw wall-clock reads in the serving plane outside
# the closed CLOCK_SITES vocabulary (the FENCE_SITES pattern — lockstep
# decisions must run on the injected engine clock), MH404 ambient
# randomness on replay paths (byte-identical failover/preemption replay
# must be a pure function of request seeds), MH405 block-store keys
# built from divergent values without the process-id namespace
# (cross-process key collisions).
# ==========================================================================

#: cross-process collective primitives: every process in the mesh must
#: call these the same number of times in the same order or the pod
#: hangs
_COLLECTIVE_QUALS = frozenset({
    "jax.lax.psum", "jax.lax.pmean", "jax.lax.pmax", "jax.lax.pmin",
    "jax.lax.psum_scatter", "jax.lax.all_gather", "jax.lax.all_to_all",
    "jax.lax.ppermute", "jax.lax.pshuffle",
})
#: block-store barrier / straggler-wait spellings (the host-side
#: agreement points of the blockstore parameter plane)
_BARRIER_SEGS = frozenset({"get_blocking", "get_weights", "wait_all",
                           "barrier"})
#: the per-process identity — THE divergence root
_PROCESS_ID_QUALS = frozenset({"jax.process_index"})
#: recorded divergence roots for the worksheet (process_count is
#: pod-uniform, so it feeds the inventory but not the MH401 taint)
_PROCESS_TOPOLOGY_QUALS = frozenset({"jax.process_index",
                                     "jax.process_count"})
#: per-peer block-store reads: another process wrote the value, so
#: what THIS process sees depends on arrival order — divergent
_PEER_READ_SEGS = frozenset({"try_get", "get_blocking"})
#: cross-process handoff spellings (payload send order feeds the
#: receiver's agreement) — MH402's second trigger class
_HANDOFF_SEGS = frozenset({"send", "pack_payload", "put"})
#: raw wall-clock sources the serving plane must not read outside the
#: declared CLOCK_SITES (time.sleep included: serving simulates stalls
#: on the VirtualClock, never by sleeping)
_WALL_CLOCK_QUALS = frozenset({"time.time", "time.perf_counter",
                               "time.monotonic", "time.process_time",
                               "time.sleep"})
#: fallback CLOCK_SITES vocabulary (single-file fixture runs): must
#: match serving/faults.py CLOCK_SITES
_DEFAULT_CLOCK_SITES = frozenset({"faults.default_clock",
                                  "metrics.ServingMetrics.on_step"})
#: seeded RNG constructors — sanctioned WITH an explicit seed argument
_SEEDED_RNG_QUALS = frozenset({
    "numpy.random.default_rng", "numpy.random.RandomState",
    "numpy.random.SeedSequence", "numpy.random.Generator",
    "random.Random",
})
#: fresh jax key constructors — sanctioned only inside the sampling
#: module's seed derivation (sampling.lane_key)
_FRESH_KEY_QUALS = frozenset({"jax.random.PRNGKey", "jax.random.key"})


@_register_facts
def _clock_site_facts(ctx: FileContext) -> Dict:
    """The declared clock-site vocabulary (``CLOCK_SITES``) and the
    module that declares it — MH403's ground truth, extracted the way
    ASY302 reads FENCE_SITES."""
    for node in ctx.by_type(ast.Assign):
        if not any(isinstance(t, ast.Name) and t.id == "CLOCK_SITES"
                   for t in node.targets):
            continue
        val = literal_value(node.value)
        if val is not UNRESOLVED:
            return {"clock_sites": sorted(val),
                    "clock_modules": [ctx.module]}
    return {}


def _clock_sites(ctx: FileContext) -> Set[str]:
    sites = _facts(ctx).get("clock_sites")
    return set(sites) if sites else set(_DEFAULT_CLOCK_SITES)


def _is_blockstore_module(ctx: FileContext) -> bool:
    """True for the module that DEFINES the block-store layer (the
    ``BlockStore`` base class): its polling loops ARE the cross-process
    synchronization implementation — branching on per-peer reads is its
    job, so MH401 exempts it (the compat.py / fences.py pattern)."""
    hit = ctx.cache.get("is_blockstore_module")
    if hit is None:
        hit = any(cls.name == "BlockStore"
                  for cls in ctx.by_type(ast.ClassDef))
        ctx.cache["is_blockstore_module"] = hit
    return hit


def _class_method_names(ctx: FileContext) -> Dict[str, Set[str]]:
    out = ctx.cache.get("class_method_names")
    if out is None:
        out = ctx.cache["class_method_names"] = {}
        for cls in ctx.by_type(ast.ClassDef):
            out[cls.name] = {
                f.name for f in cls.body
                if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))}
    return out


@_register_facts
def _lockstep_facts(ctx: FileContext) -> Dict:
    """Per-unit multi-host facts: ``collective_units`` (units that
    directly invoke a cross-process agreement point — a collective, a
    compiled-step dispatch, or a block-store barrier) and
    ``divergent_units`` (units that read a divergence root —
    ``jax.process_index``/``process_count`` or a per-peer store read).
    The reachability closure and the ``--report lockstep`` worksheet
    are built from the merged tables."""
    units = _unit_functions(ctx)
    if not units:
        return {}
    step_segs = set(_step_binding_facts(ctx).get("step_attrs", {}))
    coll: Dict[str, List[str]] = {}
    div: Dict[str, List[str]] = {}
    for qual, fn, _cls in units:
        kinds: Set[str] = set()
        roots: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            q = ctx.qualname(node.func)
            seg = _last_seg(ctx.dotted(node.func))
            if q in _COLLECTIVE_QUALS:
                kinds.add(f"collective:{q.rsplit('.', 1)[-1]}")
            elif seg == "_dispatch" or seg in step_segs:
                kinds.add("dispatch")
            elif seg in _BARRIER_SEGS:
                kinds.add(f"barrier:{seg}")
            if q in _PROCESS_TOPOLOGY_QUALS:
                roots.add(q.rsplit(".", 1)[-1])
            elif seg in _PEER_READ_SEGS and _storeish_receiver(ctx,
                                                              node):
                roots.add("peer-read")
        if kinds:
            coll[qual] = sorted(kinds)
        if roots:
            div[qual] = sorted(roots)
    out: Dict[str, Any] = {}
    if coll:
        out["collective_units"] = coll
    if div:
        out["divergent_units"] = div
    return out


def _storeish_receiver(ctx: FileContext, call: ast.Call) -> bool:
    """True when the call's receiver looks like a block store
    (``...store.try_get`` / ``bs.get_blocking``)."""
    if not isinstance(call.func, ast.Attribute):
        return False
    d = ctx.dotted(call.func.value)
    return bool(d) and "store" in d.rsplit(".", 1)[-1].lower()


def _collective_reach(ctx: FileContext) -> Set[str]:
    """Unit quals from which a cross-process agreement point is
    reachable through the merged call-graph edges (the agreement units
    themselves included) — reverse BFS over the same edge-resolution
    rules ``core.hotpath_chains`` uses, project-memoized."""
    def compute(facts: Dict) -> Set[str]:
        edges: Dict[str, List[str]] = facts.get("call_edges") or {}
        methods: Dict[str, List[str]] = facts.get("method_units") or {}
        coll = set(facts.get("collective_units") or {})
        if not coll:
            return set()
        by_tail: Dict[str, List[str]] = {}
        for q in edges:
            by_tail.setdefault(q.rsplit(".", 1)[-1], []).append(q)
        rev: Dict[str, List[str]] = {}
        for qual, callees in edges.items():
            for callee in callees:
                if callee.startswith("."):
                    targets = methods.get(callee[1:], [])
                elif callee in edges:
                    targets = [callee]
                else:
                    tail = callee.rsplit(".", 1)[-1]
                    targets = [q for q in by_tail.get(tail, ())
                               if q.endswith("." + callee)
                               or callee.endswith("." + q)]
                for t in targets:
                    rev.setdefault(t, []).append(qual)
        seen = set(coll)
        queue = list(coll)
        while queue:
            q = queue.pop()
            for p in rev.get(q, ()):
                if p not in seen:
                    seen.add(p)
                    queue.append(p)
        return seen

    proj = ctx.project
    if proj is not None:
        hit = proj.cache.get("collective_reach")
        if hit is None:
            hit = proj.cache["collective_reach"] = compute(proj.facts)
        return hit
    return compute(_facts(ctx))


def _callee_token(ctx: FileContext, call: ast.Call,
                  cls: Optional[str]) -> Optional[str]:
    """The call-graph edge token a Call would contribute (mirrors
    ``core._call_graph_facts`` at one call site): a qualified name,
    a ``.attr`` suffix, or None."""
    f = call.func
    mod = ctx.module
    if isinstance(f, ast.Name):
        local = ctx.cache.get("toplevel_defs")
        if local is None:
            local = ctx.cache["toplevel_defs"] = {
                fn.name for fn in ctx.tree.body
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))}
        if f.id in local:
            return f"{mod}.{f.id}" if mod else f.id
        return ctx.qualname(f)
    if isinstance(f, ast.Attribute):
        q = ctx.qualname(f)
        if q:
            return q
        d = ctx.dotted(f)
        if d and cls and d == f"self.{f.attr}" and \
                f.attr in _class_method_names(ctx).get(cls, ()):
            return f"{mod}.{cls}.{f.attr}" if mod else f"{cls}.{f.attr}"
        return "." + f.attr
    return None


def _agreement_call(ctx: FileContext, call: ast.Call,
                    cls: Optional[str]) -> Optional[str]:
    """What cross-process agreement ``call`` commits this process to:
    ``"collective:psum"``-style for a direct collective, ``"dispatch"``
    for a compiled-step launch, ``"barrier:..."`` for a block-store
    wait, ``"reaches <unit>"`` when the callee reaches one through the
    merged call graph — else None."""
    q = ctx.qualname(call.func)
    if q in _COLLECTIVE_QUALS:
        return f"collective:{q.rsplit('.', 1)[-1]}"
    seg = _last_seg(ctx.dotted(call.func))
    if seg == "_dispatch" or seg in _step_attr_segs(ctx):
        return "dispatch"
    if seg in _BARRIER_SEGS:
        return f"barrier:{seg}"
    token = _callee_token(ctx, call, cls)
    if token is None:
        return None
    reach = _collective_reach(ctx)
    if not reach:
        return None
    facts = _facts(ctx)
    methods: Dict[str, List[str]] = facts.get("method_units") or {}
    if token.startswith("."):
        targets = methods.get(token[1:], [])
    elif token in reach:
        return f"reaches {token}"
    else:
        targets = [t for t in reach
                   if t.endswith("." + token) or token.endswith("." + t)]
    for t in targets:
        if t in reach:
            return f"reaches {t}"
    return None


def _divergent_self_attrs(ctx: FileContext) -> Dict[Tuple[str, str], str]:
    """``(class name, attr) -> "pid" | "div"`` for attributes assigned
    a divergence root anywhere in the class body (``self.pid =
    jax.process_index()`` in ``__init__``, branched on in a method —
    the cross-method half the per-unit timeline cannot see)."""
    out = ctx.cache.get("divergent_self_attrs")
    if out is None:
        out = ctx.cache["divergent_self_attrs"] = {}
        for cls in ctx.by_type(ast.ClassDef):
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                kind = None
                if _pid_direct_expr(ctx, node.value, set()):
                    kind = "pid"
                elif _div_root_call(ctx, node.value):
                    kind = "div"
                if kind is None:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        out[(cls.name, t.attr)] = kind
    return out


def _div_root_call(ctx: FileContext, expr: ast.AST) -> bool:
    """Any divergence-root call inside ``expr`` (process_index or a
    per-peer store read)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            if ctx.qualname(node.func) in _PROCESS_ID_QUALS:
                return True
            if _last_seg(ctx.dotted(node.func)) in _PEER_READ_SEGS and \
                    _storeish_receiver(ctx, node):
                return True
    return False


def _pid_direct_expr(ctx: FileContext, expr: ast.AST,
                     pid_names: Set[str],
                     cls: Optional[str] = None) -> bool:
    """True when ``expr`` IS the process id (usable as a key
    namespace): a bare ``jax.process_index()`` call, an ``int()`` or
    ``str()`` wrap of one, a name currently bound to one, or a
    pid-assigned ``self.`` attribute."""
    if isinstance(expr, ast.Call):
        if ctx.qualname(expr.func) in _PROCESS_ID_QUALS:
            return True
        if isinstance(expr.func, ast.Name) and \
                expr.func.id in ("int", "str") and len(expr.args) == 1:
            return _pid_direct_expr(ctx, expr.args[0], pid_names, cls)
        return False
    if isinstance(expr, ast.Name):
        return expr.id in pid_names
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        return _divergent_self_attrs(ctx).get((cls or "", expr.attr)) \
            == "pid"
    return False


class _DivScan:
    """Per-unit divergence-taint timeline: which local names hold
    process-divergent values (derived from ``jax.process_index()`` or
    per-peer store reads) at each line, plus the ``pid``-direct subset
    (names that ARE the process id — the legal key namespace)."""

    def __init__(self, ctx: FileContext, fn: ast.AST,
                 cls: Optional[str]) -> None:
        self.ctx = ctx
        self.fn = fn
        self.cls = cls
        self.events: Dict[str, List[Tuple[int, bool]]] = {}
        self.pid_names_final: Set[str] = set()
        self._pid_cur: Set[str] = set()
        self._build()

    def tainted_at(self, line: int) -> Set[str]:
        return _taint_state_at(self.events, line)

    def _build(self) -> None:
        ctx = self.ctx
        cur: Set[str] = set()

        def mark(names: List[str], line: int, val: bool) -> None:
            for n in names:
                if val:
                    cur.add(n)
                elif n in cur:
                    cur.discard(n)
                else:
                    continue
                self.events.setdefault(n, []).append((line, val))

        stmts = sorted(
            (n for n in ast.walk(self.fn)
             if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                               ast.For))),
            key=lambda n: (getattr(n, "lineno", 0),
                           getattr(n, "col_offset", 0)))
        for node in stmts:
            line = getattr(node, "lineno", 0)
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = node.value
                if value is None:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                names: List[str] = []
                for t in targets:
                    names.extend(_target_names_of(t))
                if _pid_direct_expr(ctx, value, self._pid_cur, self.cls):
                    self._pid_cur.update(names)
                else:
                    self._pid_cur.difference_update(names)
                mark(names, line,
                     self.div_use(value, line, _cur=cur) is not None)
            elif isinstance(node, ast.AugAssign):
                if self.div_use(node.value, line, _cur=cur) is not None:
                    mark(_target_names_of(node.target), line, True)
            elif isinstance(node, ast.For):
                mark(_target_names_of(node.target), line,
                     self.div_use(node.iter, line, _cur=cur) is not None)
        self.pid_names_final = set(self._pid_cur)

    def div_use(self, expr: ast.AST, line: int,
                _cur: Optional[Set[str]] = None) -> Optional[ast.AST]:
        """First process-divergent use inside ``expr``: a tainted name,
        a divergence-root call, or a divergent ``self.`` attribute."""
        ctx = self.ctx
        tainted = _cur if _cur is not None else self.tainted_at(line)
        out: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            if out:
                return
            if isinstance(node, ast.Name):
                if node.id in tainted:
                    out.append(node)
                return
            if isinstance(node, ast.Attribute):
                if isinstance(node.value, ast.Name) and \
                        node.value.id == "self" and \
                        (self.cls or "", node.attr) in \
                        _divergent_self_attrs(ctx):
                    out.append(node)
                    return
                visit(node.value)
                return
            if isinstance(node, ast.Call):
                if ctx.qualname(node.func) in _PROCESS_ID_QUALS:
                    out.append(node)
                    return
                if _last_seg(ctx.dotted(node.func)) in _PEER_READ_SEGS \
                        and _storeish_receiver(ctx, node):
                    out.append(node)
                    return
                for child in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    visit(child)
                if not isinstance(node.func, ast.Name):
                    visit(node.func)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(expr)
        return out[0] if out else None

    def pid_in_parts(self, parts: Sequence[ast.AST]) -> bool:
        return any(_pid_direct_expr(self.ctx, p, self.pid_names_final,
                                    self.cls) for p in parts)


def _div_scan(ctx: FileContext, fn: ast.AST,
              cls: Optional[str]) -> _DivScan:
    key = ("div_scan", id(fn))
    hit = ctx.cache.get(key)
    if hit is None:
        hit = ctx.cache[key] = _DivScan(ctx, fn, cls)
    return hit


def _file_has_div_roots(ctx: FileContext) -> bool:
    """Cheap gate: any divergence-root call anywhere in the file
    (process_index or a store-receiver peer read)."""
    hit = ctx.cache.get("has_div_roots")
    if hit is None:
        hit = False
        for node in ctx.by_type(ast.Call):
            if ctx.qualname(node.func) in _PROCESS_ID_QUALS or (
                    _last_seg(ctx.dotted(node.func)) in _PEER_READ_SEGS
                    and _storeish_receiver(ctx, node)):
                hit = True
                break
        ctx.cache["has_div_roots"] = hit
    return hit


# -- MH401 — divergent branch reaching a collective -------------------------

@register
class DivergentBranchRule(Rule):
    code = "MH401"
    name = "divergent-branch-collective"
    summary = ("Python branch on a process-divergent value whose body "
               "reaches a collective / compiled-step dispatch / "
               "block-store barrier — the classic trace-divergence "
               "pod hang")
    hint = ("every process in an SPMD pod must execute the identical "
            "dispatch + collective sequence; a branch on "
            "jax.process_index() (or a per-peer store read) that "
            "guards a collective means one process calls it and the "
            "others don't — the pod hangs at the next barrier. Hoist "
            "the agreement point out of the branch (all processes "
            "dispatch; rank-gate only the pure-host side effects like "
            "logging/checkpoint WRITES), or make the decision from "
            "pod-uniform state")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if _is_blockstore_module(ctx) or not _file_has_div_roots(ctx):
            return
        for qual, fn, cls in _unit_functions(ctx):
            scan = _div_scan(ctx, fn, cls)
            seen: Set[Tuple[int, int]] = set()
            for node in ast.walk(fn):
                # If/While/IfExp only: an `assert` on a divergent value
                # is the standard single-process TEST idiom (asserting
                # on a store read), and the pod-hang shape is a guarded
                # agreement point, which asserts cannot express
                if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
                    continue
                off = scan.div_use(node.test, node.lineno)
                if off is None:
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                bodies: List[ast.AST] = []
                if isinstance(node, ast.IfExp):
                    bodies = [node.body, node.orelse]
                else:
                    bodies = list(node.body) + list(node.orelse)
                hit = None
                for b in bodies:
                    for sub in ast.walk(b):
                        if isinstance(sub, ast.Call):
                            kind = _agreement_call(ctx, sub, cls)
                            if kind:
                                hit = (sub, kind)
                                break
                    if hit:
                        break
                if hit is None:
                    continue
                seen.add(key)
                yield ctx.finding(
                    node, self.code,
                    f"branch on process-divergent value "
                    f"`{ast.unparse(off)[:40]}` guards a cross-process "
                    f"agreement point ({hit[1]}) in `{qual}` — "
                    f"processes diverge on whether they "
                    f"dispatch/collect",
                    hint=self.hint)


# -- MH402 — collectives/handoffs from unordered iteration ------------------

@register
class OrderDivergentIterationRule(Rule):
    code = "MH402"
    name = "unordered-agreement-iteration"
    summary = ("collective or cross-process handoff issued from "
               "iteration over a set — per-process iteration order "
               "feeds cross-process agreement")
    hint = ("set iteration order depends on hash seeding and insertion "
            "history, which differ per process — two processes looping "
            "`for x in pending:` issue their sends/collectives in "
            "DIFFERENT orders and the receivers (or the collective "
            "schedule) disagree. Iterate a canonical order instead: "
            "`for x in sorted(pending):` (one reviewable line), or "
            "keep the work queue a list")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for qual, fn, cls in _unit_functions(ctx):
            for loop in (n for n in ast.walk(fn)
                         if isinstance(n, ast.For)):
                if not _set_provenance(ctx, loop.iter, loop):
                    continue
                hit = None
                for stmt in loop.body:
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, ast.Call):
                            continue
                        kind = _agreement_call(ctx, sub, cls)
                        if kind is None and \
                                _last_seg(ctx.dotted(sub.func)) in \
                                _HANDOFF_SEGS:
                            kind = f"handoff:" \
                                f"{_last_seg(ctx.dotted(sub.func))}"
                        if kind:
                            hit = kind
                            break
                    if hit:
                        break
                if hit is None:
                    continue
                yield ctx.finding(
                    loop, self.code,
                    f"iteration over a set issues a cross-process "
                    f"agreement point ({hit}) in `{qual}` — set order "
                    f"is per-process, so the agreement order diverges",
                    hint=self.hint)


_SET_METHOD_SEGS = frozenset({"union", "intersection", "difference",
                              "symmetric_difference"})


def _set_provenance(ctx: FileContext, node: ast.AST, at: ast.AST,
                    depth: int = 0) -> bool:
    """True when ``node`` is statically a ``set``: a literal /
    comprehension / ``set()``/``frozenset()`` call / set-algebra method
    or operator over one, or a name whose visible binding is one.
    Unknown provenance stays silent (``sorted(s)`` is a list — the
    compliant spelling)."""
    if depth > 4:
        return False
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and \
                node.func.id in ("set", "frozenset"):
            return True
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SET_METHOD_SEGS:
            return _set_provenance(ctx, node.func.value, at, depth + 1)
        return False
    if isinstance(node, ast.BinOp) and \
            isinstance(node.op, (ast.BitOr, ast.BitAnd, ast.Sub,
                                 ast.BitXor)):
        return _set_provenance(ctx, node.left, at, depth + 1) or \
            _set_provenance(ctx, node.right, at, depth + 1)
    if isinstance(node, (ast.Name, ast.Attribute)):
        d = ctx.dotted(node)
        if d:
            val = ctx.resolve_binding(d, at)
            if val is not None:
                return _set_provenance(ctx, val, at, depth + 1)
    return False


# -- MH403 — clock discipline -----------------------------------------------

@register
class ClockDisciplineRule(Rule):
    code = "MH403"
    name = "clock-discipline"
    summary = ("raw wall-clock read (time.time/perf_counter/monotonic/"
               "sleep) in the serving plane outside the declared "
               "CLOCK_SITES vocabulary")
    hint = ("serving-plane lifecycle decisions (deadlines, health, "
            "backoff, autoscaling, stall simulation) run on the ONE "
            "injected engine clock (`self._clock()` — a VirtualClock "
            "in tests, `faults.default_clock` in production), so "
            "every process and every replay sees the same time. A raw "
            "time.* read forks the time source: route it through the "
            "engine clock, or — for a genuinely new raw site — add "
            "its unit to serving/faults.py CLOCK_SITES first (the "
            "FENCE_SITES pattern). time.sleep never belongs in "
            "serving: stalls advance the VirtualClock")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not (_in_serving_tree(ctx) or _defines_dispatch(ctx)):
            return
        sites = _clock_sites(ctx)
        for node in ctx.by_type(ast.Call):
            q = ctx.qualname(node.func)
            if q not in _WALL_CLOCK_QUALS:
                continue
            unit = enclosing_unit(ctx, node)
            if unit is not None:
                uq = unit[0]
                if any(uq == s or uq.endswith("." + s) for s in sites):
                    continue
            where = unit[0] if unit else "<module>"
            yield ctx.finding(
                node, self.code,
                f"raw wall-clock read `{q}` in `{where}` — outside "
                f"the declared CLOCK_SITES {sorted(sites)}",
                hint=self.hint)


# -- MH404 — ambient randomness on replay paths -----------------------------

@register
class AmbientRandomnessRule(Rule):
    code = "MH404"
    name = "ambient-randomness"
    summary = ("ambient randomness in the serving plane: stdlib "
               "random.*, the global numpy generator, an unseeded "
               "default_rng, or a fresh PRNGKey outside sampling's "
               "seed derivation")
    hint = ("byte-identical failover/preemption replay is a pure "
            "function of request seeds: every draw must come from "
            "sampling.lane_key(seed) derivation (fold_in/split/"
            "advance_lane) or an explicitly seeded generator "
            "(np.random.default_rng(seed) — the fault injector's "
            "sanctioned source). Ambient entropy (random.*, module-"
            "level np.random draws, default_rng(), a fresh PRNGKey "
            "outside serving/sampling.py) differs per process and per "
            "run, so replays and pod peers silently diverge")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not (_in_serving_tree(ctx) or _defines_dispatch(ctx)):
            return
        in_sampling = ctx.module.rsplit(".", 1)[-1] == "sampling"
        for node in ctx.by_type(ast.Call):
            q = ctx.qualname(node.func)
            if not q:
                continue
            if q in _SEEDED_RNG_QUALS:
                if not node.args and not node.keywords:
                    yield ctx.finding(
                        node, self.code,
                        f"`{q}()` with no seed draws ambient OS "
                        f"entropy — replays and pod peers diverge",
                        hint=self.hint)
                continue
            if q in _FRESH_KEY_QUALS:
                if not in_sampling:
                    yield ctx.finding(
                        node, self.code,
                        f"fresh `{q}` outside sampling's seed "
                        f"derivation — request streams must derive "
                        f"every key from sampling.lane_key",
                        hint=self.hint)
                continue
            if q.startswith("random.") or q.startswith("numpy.random."):
                yield ctx.finding(
                    node, self.code,
                    f"`{q}` draws from ambient/global RNG state — "
                    f"not a pure function of request seeds",
                    hint=self.hint)


# -- MH405 — block-store key namespace --------------------------------------

@register
class StoreKeyNamespaceRule(Rule):
    code = "MH405"
    name = "store-key-namespace"
    summary = ("block-store key built from a process-divergent value "
               "without the process-id namespace — cross-process key "
               "collisions")
    hint = ("a store key derived from per-process state (a local slot "
            "number, a peer-read value) can collide across processes: "
            "two workers write the same key for DIFFERENT rows and "
            "one silently wins. Namespace such keys by the process id "
            "(the BlockStoreParameter pattern: "
            "f\"{ns}/g/{t}/{part}/{src}\" carries the source pid) or "
            "derive them from pod-uniform coordinates only")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _file_has_div_roots(ctx):
            return
        for qual, fn, cls in _unit_functions(ctx):
            scan = _div_scan(ctx, fn, cls)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "put"
                        and _storeish_receiver(ctx, node)
                        and node.args):
                    continue
                key = node.args[0]
                if isinstance(key, ast.Name):
                    bound = ctx.resolve_binding(key.id, node)
                    if bound is not None:
                        key = bound
                parts = _key_parts(key)
                if parts is None:
                    continue
                div = [p for p in parts
                       if scan.div_use(p, node.lineno) is not None]
                if not div or scan.pid_in_parts(parts):
                    continue
                yield ctx.finding(
                    node, self.code,
                    f"store key interpolates process-divergent value "
                    f"`{ast.unparse(div[0])[:40]}` without a process-"
                    f"id component in `{qual}` — keys can collide "
                    f"across processes",
                    hint=self.hint)


def _key_parts(key: ast.AST) -> Optional[List[ast.AST]]:
    """Non-constant components of a constructed key: f-string
    interpolations or ``+``-concat operands. None when the key is not
    a visible construction (a helper call, a plain constant)."""
    if isinstance(key, ast.JoinedStr):
        return [v.value for v in key.values
                if isinstance(v, ast.FormattedValue)]
    if isinstance(key, ast.BinOp) and isinstance(key.op, ast.Add):
        parts: List[ast.AST] = []
        stack = [key]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Add):
                stack.extend([n.left, n.right])
            elif not isinstance(n, ast.Constant):
                parts.append(n)
        return parts
    return None


# -- the sync-point inventory (--report sync-points) ------------------------

_ASY_CODES = ("ASY301", "ASY302", "ASY303", "ASY304", "ASY305",
              "ASY306", "ASY307", "ASY308", "ASY309", "ASY310")


def sync_point_inventory(contexts: Sequence[FileContext]) -> List[dict]:
    """The async-refactor worksheet: every DECLARED sync (fence /
    fence_wait call) and every ASY finding on a hot-path-reachable
    unit, each with its root chain — what ``python -m bigdl_tpu.
    analysis --report sync-points`` prints. Suppressed findings
    (``# analysis: ok``) are listed with ``suppressed: true`` rather
    than hidden: the inventory is for reading, not gating."""
    from bigdl_tpu.analysis.core import _SUPPRESS_RE

    asy_rules = [r for r in all_rules_registry() if r.code in _ASY_CODES]
    out: List[dict] = []
    for ctx in contexts:
        if _is_fence_module(ctx):
            continue
        sites = _fence_sites(ctx)
        dsites = _delayed_sites(ctx)
        knobs = ", ".join(sorted(_window_knobs(ctx)))
        for qual, fn, chain in _hot_units(ctx):
            scan = _asy_scan(ctx, fn)
            for node, kind, site in scan.fences:
                if site is not None and site not in sites:
                    continue        # vocabulary drift: listed as ASY302
                # the window column: which sites sit BEHIND the
                # dispatch-ahead window (delayed consumer, depth from
                # the declared knob) vs consumed inline at depth 0
                window = (f"delayed (depth: {knobs})"
                          if kind == "fence" and site in dsites
                          else "inline")
                out.append({
                    "path": ctx.relpath,
                    "line": node.lineno + ctx.line_base,
                    "function": qual,
                    "chain": list(chain),
                    "kind": f"{kind}:{site or '?'}",
                    "classification": "declared sync point",
                    "window": window,
                    "detail": ctx.source_line(node.lineno),
                    "suggestion": (
                        "one batched device_get readback"
                        if kind == "fence" else
                        "completion wait (timer pin)"),
                    "suppressed": False,
                })
        for rule in asy_rules:
            for f in rule.check(ctx):
                out.append({
                    "path": f.path, "line": f.line,
                    "function": "", "chain": [],
                    "kind": f.code,
                    "classification": f.message,
                    "window": "",
                    "detail": f.source,
                    "suggestion": rule.hint,
                    "suppressed": bool(_SUPPRESS_RE.search(f.source)),
                })
    out.sort(key=lambda e: (e["path"], e["line"], e["kind"]))
    return out


def all_rules_registry():
    from bigdl_tpu.analysis.core import all_rules

    return all_rules()


# -- the lockstep inventory (--report lockstep) ------------------------------

_MH_CODES = ("MH401", "MH402", "MH403", "MH404", "MH405")


def lockstep_inventory(contexts: Sequence[FileContext]) -> List[dict]:
    """The multi-host pod worksheet (``--report lockstep``, the
    ``--report sync-points`` twin): everything the process-per-host
    refactor must keep in LOCKSTEP across the pod —

    * **agreement points**: every unit that directly issues a
      collective, a compiled-step dispatch, or a block-store barrier
      (with its hot-path root chain when it has one) — the lines every
      process must execute the same number of times in the same order;
    * **divergence roots**: every unit that reads
      ``jax.process_index()``/``process_count()`` or a per-peer store —
      the values a lockstep decision must never branch on;
    * **declared clock sites**: the CLOCK_SITES units (the only legal
      raw wall-clock reads in the serving plane);
    * any un-fixed MH401–405 finding, listed like the ASY findings in
      the sync-point report (suppressed ones shown, not hidden).
    """
    from bigdl_tpu.analysis.core import _SUPPRESS_RE

    mh_rules = [r for r in all_rules_registry() if r.code in _MH_CODES]
    out: List[dict] = []
    for ctx in contexts:
        chains = _hot_chains(ctx)
        sites = _clock_sites(ctx)
        for qual, fn, cls in _unit_functions(ctx):
            chain = chains.get(qual)
            kinds: List[Tuple[ast.AST, str, str]] = []
            step_segs = _step_attr_segs(ctx)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                q = ctx.qualname(node.func)
                seg = _last_seg(ctx.dotted(node.func))
                if q in _COLLECTIVE_QUALS:
                    kinds.append((node, "agreement",
                                  f"collective:{q.rsplit('.', 1)[-1]}"))
                elif seg == "_dispatch" or seg in step_segs:
                    kinds.append((node, "agreement", "dispatch"))
                elif seg in _BARRIER_SEGS:
                    kinds.append((node, "agreement", f"barrier:{seg}"))
                if q in _PROCESS_TOPOLOGY_QUALS:
                    kinds.append((node, "divergence",
                                  q.rsplit(".", 1)[-1]))
                elif seg in _PEER_READ_SEGS and \
                        _storeish_receiver(ctx, node):
                    kinds.append((node, "divergence", "peer-read"))
                if q in _WALL_CLOCK_QUALS and any(
                        qual == s or qual.endswith("." + s)
                        for s in sites):
                    kinds.append((node, "clock", q))
            seen: Set[Tuple[int, str, str]] = set()
            for node, cat, what in kinds:
                key = (node.lineno, cat, what)
                if key in seen:
                    continue
                seen.add(key)
                out.append({
                    "path": ctx.relpath,
                    "line": node.lineno + ctx.line_base,
                    "function": qual,
                    "chain": list(chain) if chain else [],
                    "kind": f"{cat}:{what}",
                    "classification": {
                        "agreement": "cross-process agreement point",
                        "divergence": "process-divergence root",
                        "clock": "declared clock site",
                    }[cat],
                    "detail": ctx.source_line(node.lineno),
                    "suggestion": "",
                    "suppressed": False,
                })
        for rule in mh_rules:
            for f in rule.check(ctx):
                out.append({
                    "path": f.path, "line": f.line,
                    "function": "", "chain": [],
                    "kind": f.code,
                    "classification": f.message,
                    "detail": f.source,
                    "suggestion": rule.hint,
                    "suppressed": bool(_SUPPRESS_RE.search(f.source)),
                })
    out.sort(key=lambda e: (e["path"], e["line"], e["kind"]))
    return out
