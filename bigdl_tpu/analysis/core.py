"""Rule engine for the whole-program SPMD-hygiene + serving-contract
analyzer.

Pure stdlib ``ast`` — importing this module (or running the CLI) never
imports jax, so the pass costs milliseconds per file and runs anywhere,
including boxes where the SPMD plane itself cannot even trace.

The moving parts:

* :class:`Finding` — one violation: ``path:line:col``, a stable rule
  ``code``, a message, a fix ``hint``, and the stripped offending source
  line (the line content, not the line *number*, feeds the baseline
  fingerprint so baselines survive unrelated edits above the finding).
* :class:`Rule` + :func:`register` — the rule registry.  Each rule walks
  one parsed file (:class:`FileContext`) and yields findings.
* :class:`ProjectContext` — the WHOLE-PROGRAM half: every scanned file
  parsed up front and a merged cross-module FACT table (per-file
  collectors qualify names through each file's imports — class
  inheritance edges, step-cache bindings, donation signatures, the
  declared schemas — and the engine unions them project-wide).  Every
  :class:`FileContext` carries a ``.project`` backref, so per-file
  rules consult cross-module state without re-deriving it (the SRV2xx
  family is built on this).
* **Embedded program units** — string constants that hold Python
  programs (e.g. the ``pod_projection._CHILD`` child source) are
  parsed as nested :class:`FileContext` units and scanned by every
  rule, with finding lines remapped into the host file.  This closes
  the documented PR-5 blind spot; ``str.format`` templates are
  unescaped first (``{{``/``}}`` → braces, ``{placeholder}`` → a
  parseable stub).
* :func:`analyze_paths` — walk files/dirs, parse once, build the
  project, run every selected rule.
* :func:`load_baseline` / :func:`format_baseline_entry` /
  :func:`stale_entries` / :func:`prune_baseline_text` — grandfathered
  findings.  An entry matches ``path : code : fingerprint`` so moving a
  violating line does not un-baseline it, while *editing* it does;
  entries whose fingerprint no longer matches ANY finding are STALE
  (warned about on every scan, removed by ``--prune-baseline``).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
from pathlib import Path
from typing import (
    Any, Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple,
)

#: directory basenames never walked into — fixture trees hold deliberate
#: violations and must only be scanned when named explicitly as files
DEFAULT_EXCLUDE_DIRS = frozenset(
    {"__pycache__", ".git", "_build", ".cache", "analysis_fixtures"})


def _module_name(relpath: str) -> str:
    """Dotted module name for a repo-relative path
    (``bigdl_tpu/serving/engine.py`` → ``bigdl_tpu.serving.engine``;
    ``__init__.py`` collapses onto its package)."""
    p = relpath.replace(os.sep, "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [x for x in p.split("/") if x not in ("", ".", "..")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _own_scope_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function body WITHOUT descending into nested
    def/lambda subtrees — their assignment targets are locals of a
    DIFFERENT scope and must not count as this function's bindings."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        sub = stack.pop()
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        yield sub
        stack.extend(ast.iter_child_nodes(sub))


def literal_value(node: Optional[ast.AST]) -> Any:
    """Best-effort Python value of a literal-ish AST node: constants,
    tuples/lists/sets/dicts of literals, plus ``frozenset(...)`` /
    ``set(...)`` / ``tuple(...)`` / ``list(...)`` calls over a literal
    argument (``ast.literal_eval`` rejects those spellings).  Returns
    :data:`UNRESOLVED` when the node is not statically evaluable —
    callers must treat that as "provenance unknown", never as a
    value."""
    if node is None:
        return UNRESOLVED
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("frozenset", "set", "tuple", "list") \
            and not node.keywords and len(node.args) <= 1:
        inner = literal_value(node.args[0]) if node.args else ()
        if inner is UNRESOLVED:
            return UNRESOLVED
        try:
            return {"frozenset": frozenset, "set": set,
                    "tuple": tuple, "list": list}[node.func.id](inner)
        except TypeError:
            return UNRESOLVED
    try:
        return ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError, MemoryError,
            RecursionError):
        return UNRESOLVED


#: sentinel for "this expression is not statically resolvable"
UNRESOLVED = object()


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str            # posix-style path as reported (relative when possible)
    line: int            # 1-based
    col: int             # 1-based (ast cols are 0-based; shifted for humans)
    code: str            # e.g. "SPMD101"
    message: str
    hint: str = ""
    source: str = ""     # stripped source line, for fingerprints + context
    occurrence: int = 0  # nth finding with this (code, source) in the file

    def fingerprint(self) -> str:
        """Content hash of (code, offending line, occurrence index) —
        line-number free so baselines survive edits elsewhere in the
        file, occurrence-indexed so a baselined line PASTED a second
        time is a NEW finding, not a silently grandfathered one."""
        h = hashlib.sha1(
            f"{self.code}:{self.source}:{self.occurrence}".encode(
                "utf-8", "replace"))
        return h.hexdigest()[:12]

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.path, self.code, self.fingerprint())

    def format(self, show_hint: bool = True) -> str:
        s = f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"
        if show_hint and self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def to_dict(self) -> dict:
        return {
            "path": self.path, "line": self.line, "col": self.col,
            "code": self.code, "message": self.message, "hint": self.hint,
            "source": self.source, "occurrence": self.occurrence,
            "fingerprint": self.fingerprint(),
        }


class FileContext:
    """One parsed file handed to every rule: the tree, the raw lines,
    and helpers for building findings and resolving imported names.

    ``module`` is the dotted module name derived from the path
    (``bigdl_tpu/serving/engine.py`` → ``bigdl_tpu.serving.engine``);
    ``project`` is the owning :class:`ProjectContext` (set by the
    engine — None only for hand-built contexts). An EMBEDDED unit (a
    program parsed out of a string constant) shares its host's
    ``relpath`` and carries ``line_base`` so findings report host-file
    line numbers."""

    def __init__(self, path: str, relpath: str, text: str,
                 tree: ast.Module, line_base: int = 0,
                 embedded: bool = False) -> None:
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        self.line_base = line_base      # host-line offset (embedded units)
        self.embedded = embedded
        self.module = _module_name(relpath)
        self.project: Optional["ProjectContext"] = None
        #: per-file memo for rule-computed facts (e.g. the traced-
        #: function list two rules share) — one AST pass each, not one
        #: per rule
        self.cache: Dict[str, Any] = {}
        self._parents: Optional[dict] = None
        self._imports: Optional[dict] = None
        self._nodes: Optional[List[ast.AST]] = None
        self._type_index: Dict[tuple, List[ast.AST]] = {}

    # -- identity ----------------------------------------------------------

    @property
    def is_compat(self) -> bool:
        """True for ``bigdl_tpu/utils/compat.py`` itself — the one module
        allowed to spell version-moved jax APIs directly."""
        p = self.relpath.replace(os.sep, "/")
        return p.endswith("bigdl_tpu/utils/compat.py") or \
            p.endswith("utils/compat.py")

    # -- finding construction ---------------------------------------------

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, node: ast.AST, code: str, message: str,
                hint: str = "") -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        # embedded units report HOST-file lines (the string's first
        # value line sits on the Constant node's own line)
        return Finding(path=self.relpath, line=line + self.line_base,
                       col=col, code=code, message=message, hint=hint,
                       source=self.source_line(line))

    # -- structure helpers -------------------------------------------------

    @property
    def nodes(self) -> List[ast.AST]:
        """Every AST node of the file, from ONE traversal that also
        builds the parent map — the whole-file walk each rule reuses
        instead of re-walking the tree (the analyzer's hot loop: six+
        rules x every file)."""
        if self._nodes is None:
            self._nodes = []
            self._parents = {}
            buckets: Dict[type, List[ast.AST]] = {}
            stack: List[ast.AST] = [self.tree]
            while stack:
                n = stack.pop()
                self._nodes.append(n)
                buckets.setdefault(type(n), []).append(n)
                for child in ast.iter_child_nodes(n):
                    self._parents[child] = n
                    stack.append(child)
            self._buckets = buckets
        return self._nodes

    def by_type(self, *types) -> List[ast.AST]:
        """All nodes of the given exact AST type(s), from the shared
        traversal — grouped once at build time, so each lookup is a
        dict hit, not a re-scan."""
        idx = self._type_index.get(types)
        if idx is None:
            _ = self.nodes
            out: List[ast.AST] = []
            for t in types:
                out.extend(self._buckets.get(t, ()))
            idx = self._type_index[types] = out
        return idx

    @property
    def parents(self) -> dict:
        """child-node -> parent-node map (built lazily, once per file)."""
        if self._parents is None:
            _ = self.nodes                 # builds both in one pass
        return self._parents

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or None
        at module level."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    # -- import resolution -------------------------------------------------

    @property
    def imports(self) -> dict:
        """local alias -> fully qualified dotted name, from every
        Import/ImportFrom in the file (any nesting level — the repo
        imports jax inside functions deliberately)."""
        if self._imports is not None:
            return self._imports
        amap: dict = {}
        for node in self.by_type(ast.Import, ast.ImportFrom):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        amap[a.asname] = a.name
                    else:
                        # `import jax.lax` binds `jax`; the chain resolves
                        # attribute-by-attribute from the root
                        amap[a.name.split(".")[0]] = a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    amap[a.asname or a.name] = f"{node.module}.{a.name}"
        self._imports = amap
        return amap

    def qualname(self, node: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute chain to a fully qualified dotted
        name using the file's imports (``lax.pvary`` -> ``jax.lax.pvary``
        under ``from jax import lax``).  None when the root is not an
        imported name."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.imports.get(cur.id)
        if root is None:
            return None
        return ".".join([root] + list(reversed(parts)))

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Unresolved dotted spelling of a Name/Attribute chain
        (``self._scatter``), for matching local callables and reuse of
        donated buffers.  None for anything else."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        return ".".join([cur.id] + list(reversed(parts)))

    # -- scope-chain provenance (shared by SPMD103/106 + the SRV rules) ----

    def scope_local_names(self, node: ast.AST) -> Set[str]:
        """Names bound in the enclosing function/lambda scope chain of
        ``node`` (params + assignment/loop/with targets) — the values a
        closure at ``node`` could capture per call, as opposed to
        module-level constants."""
        names: Set[str] = set()
        cur = self.enclosing_function(node)
        while cur is not None:
            a = cur.args
            for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
                names.add(p.arg)
            if a.vararg:
                names.add(a.vararg.arg)
            if a.kwarg:
                names.add(a.kwarg.arg)
            if not isinstance(cur, ast.Lambda):
                for sub in _own_scope_nodes(cur):
                    targets: List[ast.AST] = []
                    if isinstance(sub, ast.Assign):
                        targets = list(sub.targets)
                    elif isinstance(sub, (ast.AugAssign, ast.AnnAssign,
                                          ast.For)):
                        targets = [sub.target]
                    elif isinstance(sub, ast.withitem) and \
                            sub.optional_vars is not None:
                        targets = [sub.optional_vars]
                    for t in targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                names.add(n.id)
            cur = self.enclosing_function(cur)
        return names

    def binding_candidates(self, dotted: str) -> List[
            Tuple[Optional[ast.AST], int, ast.AST]]:
        """Every plain assignment to ``dotted`` in the file:
        ``(enclosing scope, lineno, value node)`` tuples in walk order.
        The raw material for :meth:`resolve_binding`; cached per file."""
        cache = getattr(self, "_binding_cache", None)
        if cache is None:
            cache = self._binding_cache = {}
            for node in self.by_type(ast.Assign):
                scope = self.enclosing_function(node)
                for t in node.targets:
                    d = self.dotted(t)
                    if d:
                        cache.setdefault(d, []).append(
                            (scope, node.lineno, node.value))
        return cache.get(dotted, [])

    def resolve_binding(self, dotted: str,
                        at: ast.AST) -> Optional[ast.AST]:
        """The VALUE node of the assignment to ``dotted`` that is in
        effect at ``at``: the nearest preceding assignment in ``at``'s
        lexical scope chain, searched innermost-out.  Returns None when
        no assignment is visible; an assignment whose provenance a rule
        cannot interpret still SHADOWS outer ones (the caller sees its
        value node and decides) — that is the scope-chain resolution
        SPMD106 pioneered, now shared project-wide."""
        cands = self.binding_candidates(dotted)
        if not cands:
            return None
        scope: Optional[ast.AST] = self.enclosing_function(at)
        while True:
            in_scope = [(ln, val) for (s, ln, val) in cands
                        if s is scope and ln <= at.lineno]
            if in_scope:
                return max(in_scope, key=lambda t: t[0])[1]
            if scope is None:
                return None
            scope = self.enclosing_function(scope)


# -- embedded program units -------------------------------------------------

#: cheap screen before attempting a parse: a real child program has
#: several lines and imports something
_EMBED_MIN_LINES = 4
_EMBED_HINT = "import "
#: opt-out comment for assigned strings that are deliberate-violation
#: test material rather than shipped child programs
_NO_EMBED_MARK = "analysis: no-embed"


def _format_unescape(s: str) -> str:
    """Turn a ``str.format`` TEMPLATE into parseable Python: ``{{``/
    ``}}`` become literal braces and ``{placeholder}`` fields become
    ``None`` stubs.  Newlines are preserved, so line numbers survive
    the transform (columns inside substituted spans do not — lines are
    what the baseline and the fixtures key on)."""
    s = s.replace("{{", "\x00").replace("}}", "\x01")
    s = re.sub(r"\{[^{}\n]*\}", "None", s)
    return s.replace("\x00", "{").replace("\x01", "}")


def _parse_embedded(value: str) -> Optional[Tuple[ast.Module, str]]:
    """Parse a candidate embedded program, trying the raw text first
    and the format-unescaped form second.  Returns ``(tree, text)``
    for whichever form parsed, or None when neither parses or the
    result contains no import (prose/docstring-shaped strings never
    qualify)."""
    for text in (value, _format_unescape(value)):
        try:
            tree = ast.parse(text)
        except (SyntaxError, ValueError):
            continue
        if any(isinstance(n, (ast.Import, ast.ImportFrom))
               for n in ast.walk(tree)):
            return tree, text
    return None


def extract_embedded_units(ctx: FileContext) -> List[FileContext]:
    """Nested :class:`FileContext` units for every string constant in
    ``ctx`` that holds a Python program — ASSIGNED strings only (bare
    expression strings are docstrings), multi-line, import-bearing,
    and parseable (after ``str.format`` unescaping for templates like
    ``pod_projection._CHILD``).  Findings inside a unit report the
    HOST file's path and line numbers.  One level deep: units never
    recurse into their own strings."""
    if ctx.embedded:
        return []
    units: List[FileContext] = []
    for node in ctx.by_type(ast.Assign, ast.AnnAssign):
        value = node.value
        if not (isinstance(value, ast.Constant)
                and isinstance(value.value, str)):
            continue
        text = value.value
        if text.count("\n") + 1 < _EMBED_MIN_LINES or \
                _EMBED_HINT not in text:
            continue
        # suppression idiom: a string that is a DELIBERATE violation
        # (a test building bad source to assert the analyzer catches
        # it) opts out with `# analysis: no-embed` on its opening line
        open_line = ctx.source_line(value.lineno)
        if _NO_EMBED_MARK in open_line or \
                _NO_EMBED_MARK in ctx.source_line(value.lineno - 1):
            continue
        hit = _parse_embedded(text)
        if hit is None:
            continue
        tree, parsed = hit
        units.append(FileContext(
            path=ctx.path, relpath=ctx.relpath, text=parsed, tree=tree,
            # value line 1 sits on the Constant's own line (the
            # canonical `X = r"""\n...` layout starts its code on the
            # next line via a leading blank value line)
            line_base=value.lineno - 1, embedded=True))
    return units


# -- the whole-program pass -------------------------------------------------

#: registered per-file fact collectors: ctx -> {kind: value}.  Rules
#: register these (like rules themselves) so the engine can compute
#: cross-module facts without core importing the rules module.
_FACT_COLLECTORS: List[Any] = []


def register_fact_collector(fn):
    _FACT_COLLECTORS.append(fn)
    return fn


def collect_file_facts(ctx: "FileContext") -> Dict[str, Any]:
    """All registered fact kinds for one file (embedded units
    included — a child program can bind step functions too)."""
    out: Dict[str, Any] = {}
    for fn in _FACT_COLLECTORS:
        for kind, value in fn(ctx).items():
            _merge_fact(out, kind, value)
    return out


def _copy_fact(value: Any) -> Any:
    """One-level copy of a fact value. The merge target must NEVER
    alias a contributor: per-file fact dicts live inside cache entries
    (and are what _save_cache persists), so mutating a contributor
    through the merged table would pollute the cache with other files'
    facts and make cached scans diverge from fresh ones."""
    if isinstance(value, dict):
        return {k: (list(v) if isinstance(v, list) else v)
                for k, v in value.items()}
    if isinstance(value, list):
        return list(value)
    return value


def _merge_fact(into: Dict[str, Any], kind: str, value: Any) -> None:
    cur = into.get(kind)
    if cur is None:
        into[kind] = _copy_fact(value)
    elif isinstance(cur, dict):
        for k, v in value.items():
            if isinstance(cur.get(k), list):
                cur[k] = sorted(set(cur[k]) | set(v))
            else:
                cur.setdefault(k, _copy_fact(v))
    elif isinstance(cur, list):
        into[kind] = sorted(set(cur) | set(value))
    else:
        into[kind] = value


def merge_facts(per_file: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Union per-file fact dicts into the project-wide fact table the
    cross-module rules consume.  Values are JSON-shaped (lists/dicts of
    strings) so facts can be cached and shipped between processes."""
    out: Dict[str, Any] = {}
    for facts in per_file:
        for kind, value in facts.items():
            _merge_fact(out, kind, value)
    return out


def facts_digest(facts: Dict[str, Any]) -> str:
    """Stable content hash of a merged fact table — part of the
    findings-cache key, so editing a file in a way that changes any
    cross-module fact invalidates every file's cached findings."""
    import json

    blob = json.dumps(facts, sort_keys=True, default=sorted)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]


# -- the interprocedural call graph + hot-path reachability -----------------
#
# The ASY3xx async-readiness rules (rules.py) need to know which
# functions the serving SUPER-STEP can actually reach — a readback in
# the decode loop is a stall every step, the same spelling in a bench
# harness is free. Path globs cannot express that (benches construct
# engines; tests copy engine shapes), so the exemption is REACHABILITY:
# a mergeable per-file fact collector emits call edges for every
# module-level function and class method, the engine merges them
# project-wide, and a BFS from the serving plane's hot-path ROOTS
# decides hot vs cold. Edges come in two strengths:
#
# * QUALIFIED — same-file defs, `self.` methods the class defines, and
#   imported callables, resolved through the file's imports (with the
#   suffix matching SRV204 pioneered for sys.path-rooted module
#   spellings);
# * SUFFIX (".name") — attribute calls on objects whose class the AST
#   cannot know (`self.admitter.admit(n)`, `eng.pool.write_prefill`).
#   A suffix edge reaches every METHOD unit with that name — an
#   over-approximation in the safe direction (too-hot means a finding
#   a human reviews; too-cold means a silent stall ships) — but only
#   methods of DISPATCH-SCOPE files (the serving tree, files importing
#   it, files with roots of their own), so a generic method name in an
#   unrelated plane never gets dragged onto the hot path.
#
# Roots are facts too: the serving plane's super-step surface is
# matched by (class, method) name, and any function can opt in with a
# `# analysis: hotpath-root` comment on (or directly above) its `def`
# line — new engine loops are born reachability-checked.

#: the serving plane's built-in hot-path roots, matched by
#: (class name, method name) anywhere they are defined
HOTPATH_ROOT_METHODS = frozenset({
    ("ServingEngine", "step"),
    ("Speculator", "step"),
    ("ChunkedAdmissionController", "pump"),
    ("ServingEngine", "_dispatch"),
})
#: the opt-in annotation for new roots
HOTPATH_MARK = "analysis: hotpath-root"


def _unit_functions(ctx: "FileContext") -> List[Tuple[str, ast.AST,
                                                      Optional[str]]]:
    """The file's call-graph UNITS: ``(qualname, node, class name)``
    for every module-level function and single-level class method
    (nested defs/lambdas belong to their enclosing unit — their calls
    are the unit's calls). Cached per file."""
    units = ctx.cache.get("callgraph_units")
    if units is None:
        units = ctx.cache["callgraph_units"] = []
        mod = ctx.module
        for fn in ctx.by_type(ast.FunctionDef, ast.AsyncFunctionDef):
            parent = ctx.parents.get(fn)
            if isinstance(parent, ast.Module):
                qual = f"{mod}.{fn.name}" if mod else fn.name
                units.append((qual, fn, None))
            elif isinstance(parent, ast.ClassDef) and \
                    isinstance(ctx.parents.get(parent), ast.Module):
                qual = f"{mod}.{parent.name}.{fn.name}" if mod \
                    else f"{parent.name}.{fn.name}"
                units.append((qual, fn, parent.name))
    return units


def enclosing_unit(ctx: "FileContext",
                   node: ast.AST) -> Optional[Tuple[str, ast.AST]]:
    """The call-graph unit ``node`` belongs to: ``(qualname, fn node)``
    of the nearest module-level function / class method enclosing it,
    or None at module level."""
    index = ctx.cache.get("callgraph_unit_index")
    if index is None:
        index = ctx.cache["callgraph_unit_index"] = {
            id(fn): (qual, fn) for qual, fn, _cls in _unit_functions(ctx)}
    cur = ctx.enclosing_function(node)
    while cur is not None:
        hit = index.get(id(cur))
        if hit is not None:
            return hit
        cur = ctx.enclosing_function(cur)
    return None


def _is_hotpath_root(ctx: "FileContext", fn: ast.AST,
                     cls: Optional[str]) -> bool:
    if (cls, fn.name) in HOTPATH_ROOT_METHODS:
        return True
    # the annotation may sit on the def line or the line above it
    for ln in (fn.lineno, fn.lineno - 1):
        if HOTPATH_MARK in ctx.source_line(ln):
            return True
    return False


def _dispatch_scope(ctx: "FileContext") -> bool:
    """True for files whose METHODS are legal suffix-edge targets: the
    serving tree, files importing the serving plane or the transformer
    step caches, and files declaring hot-path roots of their own.
    Keeps `self.pool.free(...)`-style suffix edges from dragging a
    generic method name in an unrelated plane onto the hot path."""
    hit = ctx.cache.get("dispatch_scope")
    if hit is None:
        p = ctx.relpath.replace("\\", "/")
        hit = "bigdl_tpu/serving/" in p
        if not hit:
            for node in ctx.by_type(ast.Import, ast.ImportFrom):
                names = [a.name for a in node.names] \
                    if isinstance(node, ast.Import) \
                    else ([node.module] if node.module else [])
                if any(m.startswith("bigdl_tpu.serving")
                       or m.startswith("bigdl_tpu.models.transformer")
                       for m in names):
                    hit = True
                    break
        if not hit:
            hit = any(_is_hotpath_root(ctx, fn, cls)
                      for _q, fn, cls in _unit_functions(ctx))
        ctx.cache["dispatch_scope"] = hit
    return hit


@register_fact_collector
def _call_graph_facts(ctx: "FileContext") -> Dict[str, Any]:
    """Per-file call-graph facts: ``call_edges`` (unit qual -> callee
    entries, qualified or ``.suffix``), ``method_units`` (bare method
    name -> quals, the suffix-edge index — dispatch-scope files only),
    and ``hotpath_roots``."""
    units = _unit_functions(ctx)
    if not units:
        return {}
    mod = ctx.module
    local_defs = {fn.name for fn in ctx.tree.body
                  if isinstance(fn, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
    class_methods: Dict[str, Set[str]] = {}
    for cls in ctx.by_type(ast.ClassDef):
        class_methods[cls.name] = {
            f.name for f in cls.body
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))}
    edges: Dict[str, List[str]] = {}
    methods: Dict[str, List[str]] = {}
    roots: List[str] = []
    in_scope = _dispatch_scope(ctx)
    for qual, fn, cls in units:
        callees: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                if f.id in local_defs:
                    callees.add(f"{mod}.{f.id}" if mod else f.id)
                else:
                    q = ctx.qualname(f)
                    if q:
                        callees.add(q)
            elif isinstance(f, ast.Attribute):
                q = ctx.qualname(f)
                if q:
                    callees.add(q)
                    continue
                d = ctx.dotted(f)
                if d and cls and d == f"self.{f.attr}" and \
                        f.attr in class_methods.get(cls, ()):
                    callees.add(f"{mod}.{cls}.{f.attr}" if mod
                                else f"{cls}.{f.attr}")
                else:
                    callees.add("." + f.attr)
        edges[qual] = sorted(callees)
        if cls is not None and in_scope:
            methods.setdefault(fn.name, []).append(qual)
        if _is_hotpath_root(ctx, fn, cls):
            roots.append(qual)
    out: Dict[str, Any] = {"call_edges": edges}
    if methods:
        out["method_units"] = {k: sorted(v) for k, v in methods.items()}
    if roots:
        out["hotpath_roots"] = sorted(roots)
    return out


def hotpath_chains(facts: Dict[str, Any]) -> Dict[str, Tuple[str, ...]]:
    """BFS the merged call-edge facts from the hot-path roots:
    ``unit qual -> (root, ..., unit)`` — the shortest root chain — for
    every REACHABLE unit. Qualified edges resolve exactly or by dotted
    suffix (the SRV204 rule for sys.path-rooted spellings); ``.name``
    suffix edges reach every dispatch-scope method of that name."""
    edges: Dict[str, List[str]] = facts.get("call_edges") or {}
    methods: Dict[str, List[str]] = facts.get("method_units") or {}
    roots = list(facts.get("hotpath_roots") or [])
    if not edges or not roots:
        return {r: (r,) for r in roots}
    by_tail: Dict[str, List[str]] = {}
    for q in edges:
        by_tail.setdefault(q.rsplit(".", 1)[-1], []).append(q)
    chains: Dict[str, Tuple[str, ...]] = {}
    queue: List[Tuple[str, Tuple[str, ...]]] = [
        (r, (r,)) for r in roots if r in edges]
    while queue:
        qual, chain = queue.pop(0)
        if qual in chains:
            continue
        chains[qual] = chain
        for callee in edges.get(qual, ()):
            targets: List[str] = []
            if callee.startswith("."):
                targets = methods.get(callee[1:], [])
            elif callee in edges:
                targets = [callee]
            else:
                tail = callee.rsplit(".", 1)[-1]
                targets = [q for q in by_tail.get(tail, ())
                           if q.endswith("." + callee)
                           or callee.endswith("." + q)]
            for t in targets:
                if t not in chains:
                    queue.append((t, chain + (t,)))
    return chains


class ProjectContext:
    """Cross-module state for one analyzer run: every scanned file
    (host files AND their embedded units), the merged cross-module
    FACT table, and a memo cache for rule-computed project-wide state.
    All cross-module resolution flows through the fact collectors
    (``register_fact_collector``) — per-file facts are import-graph
    qualified where they are collected, then merged here — so the
    table is small, JSON-shaped, and the same object the findings
    cache and the parallel workers ship around.

    A single-file run (``analyze_source``, the fixture tests) builds a
    one-file project: cross-module facts simply are not present, and
    rules fall back to their documented per-file approximations."""

    def __init__(self, contexts: Sequence[FileContext]) -> None:
        self.contexts = list(contexts)
        #: rules' project-wide memo (keyed by rule-chosen strings)
        self.cache: Dict[str, Any] = {}
        self._facts: Optional[Dict[str, Any]] = None
        for ctx in self.contexts:
            ctx.project = self

    @property
    def facts(self) -> Dict[str, Any]:
        """The merged cross-module fact table (computed lazily from
        this project's own files, or injected pre-merged by the
        parallel scanner / the findings cache)."""
        if self._facts is None:
            self._facts = merge_facts(
                collect_file_facts(ctx) for ctx in self.contexts)
        return self._facts

    @facts.setter
    def facts(self, value: Dict[str, Any]) -> None:
        self._facts = value


class Rule:
    """Base class: subclasses set ``code``/``name``/``hint`` and
    implement :meth:`check`."""

    code: str = ""
    name: str = ""
    #: one-line description for --list-rules / docs
    summary: str = ""
    hint: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


_REGISTRY: List[Rule] = []


def register(cls):
    """Class decorator adding a rule (instantiated once) to the registry."""
    _REGISTRY.append(cls())
    return cls


def all_rules() -> List[Rule]:
    return list(_REGISTRY)


def rule_codes() -> List[str]:
    return [r.code for r in _REGISTRY]


# -- engine ----------------------------------------------------------------

def _iter_py_files(paths: Sequence[str],
                   exclude_dirs: Iterable[str]) -> Iterator[Path]:
    excl = set(exclude_dirs)
    for p in paths:
        path = Path(p)
        if path.is_file():
            # explicit file paths bypass directory exclusion — that is
            # how the fixture tests point the engine at deliberate
            # violations
            if path.suffix == ".py":
                yield path
        elif path.is_dir():
            for f in sorted(path.rglob("*.py")):
                if not any(part in excl for part in f.parts):
                    yield f


def _relpath(p: Path) -> str:
    try:
        rel = p.resolve().relative_to(Path.cwd().resolve())
        return rel.as_posix()
    except ValueError:
        return p.as_posix()


def _parse_file(text: str, path: str
                ) -> Tuple[Optional[FileContext], Optional[Finding]]:
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return None, Finding(
            path=path, line=e.lineno or 1, col=(e.offset or 1),
            code="SPMD000", message=f"file does not parse: {e.msg}",
            source=(e.text or "").strip())
    return FileContext(path=path, relpath=path, text=text,
                       tree=tree), None


def _check_contexts(all_ctx: Sequence[FileContext],
                    parse_errors: Sequence[Finding],
                    select: Optional[Iterable[str]],
                    ignore: Optional[Iterable[str]]) -> List[Finding]:
    """Run the selected rules over an already-WIRED project (host files
    + embedded units sharing one :class:`ProjectContext`), sort, and
    occurrence-index duplicate (path, code, source) findings so each
    duplicated line needs its own baseline entry."""
    sel = set(select) if select else None
    ign = set(ignore) if ignore else set()
    out: List[Finding] = list(parse_errors)
    for ctx in all_ctx:
        for rule in _REGISTRY:
            if sel is not None and rule.code not in sel:
                continue
            if rule.code in ign:
                continue
            out.extend(rule.check(ctx))
    return _finalize(out)


def _run_rules(contexts: Sequence[FileContext],
               parse_errors: Sequence[Finding],
               select: Optional[Iterable[str]],
               ignore: Optional[Iterable[str]]) -> List[Finding]:
    """Phase two of every analysis: wire the whole-program
    :class:`ProjectContext` over all parsed files + their embedded
    units, then run the selected rules."""
    all_ctx: List[FileContext] = []
    for ctx in contexts:
        all_ctx.append(ctx)
        all_ctx.extend(extract_embedded_units(ctx))
    ProjectContext(all_ctx)
    return _check_contexts(all_ctx, parse_errors, select, ignore)


def analyze_source(text: str, path: str = "<string>",
                   select: Optional[Iterable[str]] = None,
                   ignore: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the selected rules over one source string (test/fixture entry
    point; :func:`analyze_paths` is the file-walking wrapper).  The
    string becomes a one-file project: cross-module resolution degrades
    to per-file fallbacks."""
    ctx, err = _parse_file(text, path)
    if err is not None:
        return [err]
    return _run_rules([ctx], [], select, ignore)


def load_project(paths: Sequence[str],
                 exclude_dirs: Iterable[str] = DEFAULT_EXCLUDE_DIRS,
                 ) -> Tuple[List[FileContext], List[Finding]]:
    """Parse ``paths`` into ONE wired :class:`ProjectContext`: every
    host file plus its embedded units, with parse errors as findings.
    The raw material for non-rule consumers — the sync-point inventory
    (``--report sync-points``) walks these contexts directly."""
    contexts: List[FileContext] = []
    errors: List[Finding] = []
    for f in _iter_py_files(paths, exclude_dirs):
        text = f.read_text(encoding="utf-8", errors="replace")
        ctx, err = _parse_file(text, _relpath(f))
        if err is not None:
            errors.append(err)
        else:
            contexts.append(ctx)
    all_ctx: List[FileContext] = []
    for ctx in contexts:
        all_ctx.append(ctx)
        all_ctx.extend(extract_embedded_units(ctx))
    ProjectContext(all_ctx)
    return all_ctx, errors


def analyze_paths(paths: Sequence[str],
                  select: Optional[Iterable[str]] = None,
                  ignore: Optional[Iterable[str]] = None,
                  exclude_dirs: Iterable[str] = DEFAULT_EXCLUDE_DIRS,
                  ) -> List[Finding]:
    """Walk ``paths`` (files and/or directories), parse everything,
    build the whole-program project, and run the rules."""
    all_ctx, errors = load_project(paths, exclude_dirs)
    return _check_contexts(all_ctx, errors, select, ignore)


# -- baseline --------------------------------------------------------------

def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    """Parse a baseline file into ``{(path, code, fingerprint)}``.

    Format: one entry per line, ``path:CODE:fingerprint``; blank lines
    and ``#`` comments (the required justifications) are skipped."""
    entries: Set[Tuple[str, str, str]] = set()
    p = Path(path)
    if not p.exists():
        return entries
    for raw in p.read_text(encoding="utf-8").splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        # path may itself contain ':' on exotic systems — split from the
        # right, the code and fingerprint never do
        parts = line.rsplit(":", 2)
        if len(parts) == 3:
            entries.add((parts[0], parts[1], parts[2]))
    return entries


def split_baselined(findings: Sequence[Finding],
                    baseline: Set[Tuple[str, str, str]],
                    ) -> Tuple[List[Finding], List[Finding]]:
    """-> (new, grandfathered)."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if f.baseline_key() in baseline else new).append(f)
    return new, old


def format_baseline_entry(f: Finding) -> str:
    """One ready-to-commit baseline line (offending source as a trailing
    comment so reviewers see what is being grandfathered)."""
    path, code, fp = f.baseline_key()
    return f"# line {f.line}: {f.source}\n{path}:{code}:{fp}"


def covered_by_scan(paths: Sequence[str],
                    exclude_dirs: Iterable[str] = DEFAULT_EXCLUDE_DIRS,
                    ) -> Tuple[Set[str], Tuple[str, ...]]:
    """What a scan over ``paths`` can VOUCH for: the set of scanned
    file relpaths plus the directory prefixes the scan walked.  A
    baseline entry is only assessable (stale-warnable, prunable) when
    its path falls inside this coverage — a partial scan must never
    judge entries for files it did not look at (they would all look
    "stale" and a prune would delete live grandfathered findings);
    deleted files under a scanned TREE are covered by the prefix, so
    their dead entries still prune."""
    files = {_relpath(f) for f in _iter_py_files(paths, exclude_dirs)}
    prefixes = tuple(
        _relpath(Path(p)).rstrip("/") + "/"
        for p in paths if Path(p).is_dir())
    return files, prefixes


def stale_entries(findings: Sequence[Finding],
                  baseline: Set[Tuple[str, str, str]],
                  covered: Optional[Tuple[Set[str],
                                          Tuple[str, ...]]] = None,
                  codes: Optional[Set[str]] = None,
                  ) -> Set[Tuple[str, str, str]]:
    """Baseline entries matching NO current finding — the violation was
    fixed (or its line edited, which re-keys it), so the entry is dead
    weight that would silently grandfather a future regression pasted
    at the same spot.  Scans warn about these; ``--prune-baseline``
    removes them.  ``covered`` (from :func:`covered_by_scan`) and
    ``codes`` (the rule selection) restrict the verdict to entries this
    scan actually assessed: entries for unscanned files or unselected
    rules are never stale."""
    live = {f.baseline_key() for f in findings}
    out = set()
    for entry in baseline:
        path, code, _fp = entry
        if entry in live:
            continue
        if codes is not None and code not in codes:
            continue
        if covered is not None:
            files, prefixes = covered
            if path not in files and \
                    not any(path.startswith(p) for p in prefixes):
                continue
        out.add(entry)
    return out


def prune_baseline_text(text: str,
                        live: Set[Tuple[str, str, str]]
                        ) -> Tuple[str, int]:
    """Rewrite a baseline file's text keeping only entries in ``live``
    (each dropped entry takes its immediately preceding comment block —
    the justification — with it).  Returns ``(new_text, n_removed)``;
    header comments and blank lines elsewhere survive."""
    out: List[str] = []
    pending: List[str] = []          # comment run awaiting its entry
    removed = 0
    for raw in text.splitlines():
        line = raw.strip()
        if line.startswith("#"):
            pending.append(raw)
            continue
        if not line:
            out.extend(pending)
            pending = []
            out.append(raw)
            continue
        parts = line.rsplit(":", 2)
        key = tuple(parts) if len(parts) == 3 else None
        if key is not None and key not in live:
            removed += 1
            pending = []             # the justification goes with it
            continue
        out.extend(pending)
        pending = []
        out.append(raw)
    out.extend(pending)
    new = "\n".join(out)
    if text.endswith("\n") and new and not new.endswith("\n"):
        new += "\n"
    return new, removed


# -- the scan driver: content-hash cache + parallel workers -----------------
#
# `python -m bigdl_tpu.analysis` over the whole repo must stay fast
# enough to run as a pre-commit gate (<2s steady-state on the dev box).
# Two levers, both OFF in the library API (analyze_paths) and ON in the
# CLI:
#
# * a FINDINGS CACHE keyed by (analyzer source digest, file path+text
#   hash, merged-facts digest, rule selection): a file's findings are a
#   pure function of those inputs, so unchanged files cost one sha1
#   instead of a parse + six rule passes.  Editing any file re-analyzes
#   it; editing a file in a way that changes a CROSS-MODULE fact (a new
#   step binding, a schema change) flips the facts digest and
#   re-analyzes everything — correctness first.
# * PARALLEL WORKERS (fork) for cache misses: each worker parses its
#   slice and returns per-file facts; the parent merges them with the
#   cached facts and broadcasts the table; workers then run the rules
#   over their already-parsed trees.  Guarded: fork only, and only in
#   processes that have not initialized jax (forking a live XLA client
#   can wedge) — anything else silently degrades to serial.

CACHE_VERSION = 1
#: cache entries untouched for this many runs age out (bounds growth
#: from edited files' dead content-hash keys without evicting the
#: whole-repo table on every subset scan)
_CACHE_KEEP_RUNS = 64

_ANALYZER_DIGEST: Optional[str] = None


def analyzer_digest() -> str:
    """Content hash of the analyzer's own source (core + rules): part
    of every cache key, so editing a rule invalidates the cache."""
    global _ANALYZER_DIGEST
    if _ANALYZER_DIGEST is None:
        h = hashlib.sha1()
        pkg = Path(__file__).resolve().parent
        for name in ("core.py", "rules.py"):
            try:
                h.update((pkg / name).read_bytes())
            except OSError:
                h.update(name.encode())
        _ANALYZER_DIGEST = h.hexdigest()[:16]
    return _ANALYZER_DIGEST


def _file_key(relpath: str, text: str) -> str:
    h = hashlib.sha1()
    h.update(relpath.encode("utf-8", "replace"))
    h.update(b"\0")
    h.update(text.encode("utf-8", "replace"))
    return h.hexdigest()


def _load_cache(path: Optional[str]) -> dict:
    import json

    if not path:
        return {"version": CACHE_VERSION, "analyzer": analyzer_digest(),
                "files": {}}
    try:
        data = json.loads(Path(path).read_text(encoding="utf-8"))
        if data.get("version") == CACHE_VERSION and \
                data.get("analyzer") == analyzer_digest() and \
                isinstance(data.get("files"), dict):
            return data
    except (OSError, ValueError):
        pass
    return {"version": CACHE_VERSION, "analyzer": analyzer_digest(),
            "files": {}}


def _save_cache(path: Optional[str], data: dict) -> None:
    import json

    if not path:
        return
    try:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(p.suffix + ".tmp")
        tmp.write_text(json.dumps(data), encoding="utf-8")
        tmp.replace(p)
    except OSError:
        pass                       # the cache is an optimization only


def _finding_from_dict(d: dict) -> Finding:
    return Finding(path=d["path"], line=d["line"], col=d["col"],
                   code=d["code"], message=d["message"],
                   hint=d.get("hint", ""), source=d.get("source", ""),
                   occurrence=0)


def _file_facts(ctx: Optional[FileContext],
                units: Sequence[FileContext]) -> Dict[str, Any]:
    ctxs = ([ctx] if ctx is not None else []) + list(units)
    return merge_facts(collect_file_facts(c) for c in ctxs)


def _rules_for(select, ignore):
    sel = set(select) if select else None
    ign = set(ignore) if ignore else set()
    return [r for r in _REGISTRY
            if (sel is None or r.code in sel) and r.code not in ign]


def _analyze_one(relpath: str, text: str, merged_facts: Dict[str, Any],
                 rules) -> Tuple[List[Finding], Dict[str, Any]]:
    """Parse + rule-run ONE file (host + embedded units) against a
    pre-merged fact table.  Returns (raw findings, the file's own
    facts)."""
    ctx, err = _parse_file(text, relpath)
    if err is not None:
        return [err], {}
    units = extract_embedded_units(ctx)
    ctxs = [ctx] + units
    project = ProjectContext(ctxs)
    project.facts = merged_facts
    facts = _file_facts(ctx, units)
    out: List[Finding] = []
    for c in ctxs:
        for rule in rules:
            out.extend(rule.check(c))
    return out, facts


#: inline suppression idiom: `# analysis: ok` silences every finding on
#: its line, `# analysis: ok: SRV205` (comma-separable) only the listed
#: codes — for the rare line that is LEGITIMATE despite matching a rule
#: (e.g. a test deliberately exercising an error path).  Prefer fixing;
#: this exists so legitimate code never has to seed the baseline.
_SUPPRESS_RE = re.compile(
    r"#\s*analysis:\s*ok\b(?:\s*:\s*([A-Z0-9_,\s]+))?")


def _suppressed(f: Finding) -> bool:
    m = _SUPPRESS_RE.search(f.source)
    if not m:
        return False
    if not m.group(1):
        return True
    codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
    return f.code in codes


def _finalize(findings: List[Finding]) -> List[Finding]:
    findings[:] = [f for f in findings if not _suppressed(f)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    seen: dict = {}
    for i, f in enumerate(findings):
        k = (f.path, f.code, f.source)
        idx = seen.get(k, 0)
        seen[k] = idx + 1
        if idx != f.occurrence:
            findings[i] = dataclasses.replace(f, occurrence=idx)
    return findings


def _fork_ok() -> bool:
    import sys

    if "jax" in sys.modules:       # forking a live XLA client can hang
        return False
    try:
        import multiprocessing as mp

        return "fork" in mp.get_all_start_methods()
    except Exception:
        return False


def _worker_main(conn, entries, select, ignore) -> None:
    """Parallel-scan worker: phase 1 parse + per-file facts; phase 2
    (after receiving the merged table) rule runs."""
    try:
        rules = _rules_for(select, ignore)
        parsed = []
        facts_out: Dict[str, Dict] = {}
        for relpath, text in entries:
            ctx, err = _parse_file(text, relpath)
            units = extract_embedded_units(ctx) if ctx is not None else []
            facts_out[relpath] = _file_facts(ctx, units)
            parsed.append((relpath, ctx, units, err))
        conn.send(facts_out)
        merged = conn.recv()
        out: Dict[str, List[dict]] = {}
        for relpath, ctx, units, err in parsed:
            fs: List[Finding] = []
            if err is not None:
                fs.append(err)
            else:
                ctxs = [ctx] + units
                project = ProjectContext(ctxs)
                project.facts = merged
                for c in ctxs:
                    for rule in rules:
                        fs.extend(rule.check(c))
            out[relpath] = [f.to_dict() for f in fs]
        conn.send(out)
        conn.close()
    except BaseException as e:                     # surface, don't hang
        try:
            conn.send({"__worker_error__": repr(e)})
            conn.close()
        except Exception:
            pass


def _parallel_fresh(misses, select, ignore, cached_facts, jobs):
    """Run the two-phase fork protocol over the cache-miss files.
    Returns {relpath: (finding dicts, facts)} or None when the
    parallel path is unavailable/failed (caller falls back serial)."""
    import multiprocessing as mp

    ctx_mp = mp.get_context("fork")
    n = max(1, min(jobs, len(misses)))
    if n < 2:
        return None
    # balance slices by text size (parse cost is roughly linear)
    order = sorted(misses, key=lambda e: -len(e[1]))
    slices: List[list] = [[] for _ in range(n)]
    loads = [0] * n
    for entry in order:
        i = loads.index(min(loads))
        slices[i].append(entry)
        loads[i] += len(entry[1])
    conns, procs = [], []
    try:
        for sl in slices:
            parent, child = ctx_mp.Pipe()
            p = ctx_mp.Process(target=_worker_main,
                               args=(child, sl, select, ignore))
            p.start()
            child.close()
            conns.append(parent)
            procs.append(p)
        fresh_facts: Dict[str, Dict] = {}
        for conn in conns:
            got = conn.recv()
            if "__worker_error__" in got:
                raise RuntimeError(got["__worker_error__"])
            fresh_facts.update(got)
        merged = merge_facts(list(cached_facts.values())
                             + list(fresh_facts.values()))
        for conn in conns:
            conn.send(merged)
        results: Dict[str, Tuple[List[dict], Dict]] = {}
        for conn in conns:
            got = conn.recv()
            if "__worker_error__" in got:
                raise RuntimeError(got["__worker_error__"])
            for relpath, fdicts in got.items():
                results[relpath] = (fdicts, fresh_facts[relpath])
        return results
    except Exception:
        return None
    finally:
        for conn in conns:
            try:
                conn.close()
            except Exception:
                pass
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()


def scan(paths: Sequence[str],
         select: Optional[Iterable[str]] = None,
         ignore: Optional[Iterable[str]] = None,
         exclude_dirs: Iterable[str] = DEFAULT_EXCLUDE_DIRS,
         cache_path: Optional[str] = None,
         jobs: int = 1) -> List[Finding]:
    """The CLI's scan driver: :func:`analyze_paths` semantics plus the
    findings cache and the parallel cold path (module comment above).
    ``cache_path=None, jobs=1`` is exactly ``analyze_paths``."""
    select = list(select) if select else None
    ignore = list(ignore) if ignore else None
    entries: List[Tuple[str, str]] = []
    for f in _iter_py_files(paths, exclude_dirs):
        entries.append((_relpath(f),
                        f.read_text(encoding="utf-8", errors="replace")))
    cache = _load_cache(cache_path)
    old_files = cache["files"]
    new_files: Dict[str, dict] = {}
    sel_key = ",".join(sorted(select or [])) + "|" + \
        ",".join(sorted(ignore or []))

    # facts pass: cached per file hash, computed (parse) on miss
    all_facts: Dict[str, Dict] = {}
    misses: List[Tuple[str, str]] = []
    keys: Dict[str, str] = {}
    for relpath, text in entries:
        key = keys[relpath] = _file_key(relpath, text)
        hit = old_files.get(key)
        if hit is not None and "facts" in hit:
            all_facts[relpath] = hit["facts"]
            new_files[key] = hit
        else:
            misses.append((relpath, text))

    parallel_ok = jobs > 1 and _fork_ok()
    results: Dict[str, Tuple[List[dict], Dict]] = {}
    if misses and parallel_ok:
        got = _parallel_fresh(misses, select, ignore, all_facts, jobs)
        if got is not None:
            results = got
            for relpath, (_fd, facts) in got.items():
                all_facts[relpath] = facts
    if len(all_facts) < len(entries):
        # serial facts for the (remaining) misses: parse now; the ctx
        # is not kept — _analyze_one reparses below, and this path only
        # runs when the fork pool is unavailable or declined
        for relpath, text in misses:
            if relpath in all_facts:
                continue
            ctx, _err = _parse_file(text, relpath)
            units = extract_embedded_units(ctx) if ctx is not None else []
            all_facts[relpath] = _file_facts(ctx, units)
    merged = merge_facts(all_facts.values())
    fdig = facts_digest(merged)
    run_key = f"{fdig}|{sel_key}"
    rules = _rules_for(select, ignore)

    # findings misses BEYOND the text misses: a changed cross-module
    # fact (or rule selection) invalidates every file's cached findings
    # even though their facts are still cached — exactly the
    # re-analyze-everything case, so it gets the SAME fork pool as a
    # cold scan instead of a one-core crawl through _analyze_one
    if parallel_ok:
        remaining = [
            (relpath, text) for relpath, text in entries
            if relpath not in results
            and run_key not in (old_files.get(keys[relpath]) or {}).get(
                "findings", {})]
        if remaining:
            other = {rp: f for rp, f in all_facts.items()
                     if rp not in {r for r, _ in remaining}}
            got = _parallel_fresh(remaining, select, ignore, other, jobs)
            if got is not None:
                results.update(got)

    findings: List[Finding] = []
    for relpath, text in entries:
        key = keys[relpath]
        entry = new_files.setdefault(key, old_files.get(key) or {})
        per_run = entry.setdefault("findings", {})
        fdicts = per_run.get(run_key)
        if fdicts is None:
            if relpath in results:
                fdicts, facts = results[relpath]
            else:
                fs, facts = _analyze_one(relpath, text, merged, rules)
                fdicts = [f.to_dict() for f in fs]
            entry["facts"] = facts if "facts" not in entry \
                else entry["facts"]
            # one findings entry per cache file keeps growth bounded
            entry["findings"] = {run_key: fdicts}
        findings.extend(_finding_from_dict(d) for d in fdicts)

    # MERGE this run's entries into the table rather than replacing it:
    # a subset scan (`python -m bigdl_tpu.analysis bigdl_tpu/serving`)
    # must not evict the whole-repo cache the next full gate relies on.
    # Entries untouched for many runs age out so edited files' dead
    # keys do not accumulate forever.
    run_no = int(cache.get("run", 0)) + 1
    cache["run"] = run_no
    for entry in new_files.values():
        entry["r"] = run_no
    merged_files = dict(old_files)
    merged_files.update(new_files)
    cache["files"] = {k: v for k, v in merged_files.items()
                      if run_no - int(v.get("r", 0)) <= _CACHE_KEEP_RUNS}
    _save_cache(cache_path, cache)
    return _finalize(findings)
