"""Rule engine for the SPMD hygiene analyzer.

Pure stdlib ``ast`` — importing this module (or running the CLI) never
imports jax, so the pass costs milliseconds per file and runs anywhere,
including boxes where the SPMD plane itself cannot even trace.

The moving parts:

* :class:`Finding` — one violation: ``path:line:col``, a stable rule
  ``code``, a message, a fix ``hint``, and the stripped offending source
  line (the line content, not the line *number*, feeds the baseline
  fingerprint so baselines survive unrelated edits above the finding).
* :class:`Rule` + :func:`register` — the rule registry.  Each rule walks
  one parsed file (:class:`FileContext`) and yields findings.
* :func:`analyze_paths` — walk files/dirs, parse once, run every
  selected rule.
* :func:`load_baseline` / :func:`format_baseline_entry` — grandfathered
  findings.  An entry matches ``path : code : fingerprint`` so moving a
  violating line does not un-baseline it, while *editing* it does.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
from pathlib import Path
from typing import Iterable, Iterator, List, Optional, Sequence, Set, Tuple

#: directory basenames never walked into — fixture trees hold deliberate
#: violations and must only be scanned when named explicitly as files
DEFAULT_EXCLUDE_DIRS = frozenset(
    {"__pycache__", ".git", "_build", ".cache", "analysis_fixtures"})


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str            # posix-style path as reported (relative when possible)
    line: int            # 1-based
    col: int             # 1-based (ast cols are 0-based; shifted for humans)
    code: str            # e.g. "SPMD101"
    message: str
    hint: str = ""
    source: str = ""     # stripped source line, for fingerprints + context
    occurrence: int = 0  # nth finding with this (code, source) in the file

    def fingerprint(self) -> str:
        """Content hash of (code, offending line, occurrence index) —
        line-number free so baselines survive edits elsewhere in the
        file, occurrence-indexed so a baselined line PASTED a second
        time is a NEW finding, not a silently grandfathered one."""
        h = hashlib.sha1(
            f"{self.code}:{self.source}:{self.occurrence}".encode(
                "utf-8", "replace"))
        return h.hexdigest()[:12]

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.path, self.code, self.fingerprint())

    def format(self, show_hint: bool = True) -> str:
        s = f"{self.path}:{self.line}:{self.col}: {self.code} {self.message}"
        if show_hint and self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def to_dict(self) -> dict:
        return {
            "path": self.path, "line": self.line, "col": self.col,
            "code": self.code, "message": self.message, "hint": self.hint,
            "source": self.source, "occurrence": self.occurrence,
            "fingerprint": self.fingerprint(),
        }


class FileContext:
    """One parsed file handed to every rule: the tree, the raw lines,
    and helpers for building findings and resolving imported names."""

    def __init__(self, path: str, relpath: str, text: str,
                 tree: ast.Module) -> None:
        self.path = path
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()
        self.tree = tree
        self._parents: Optional[dict] = None
        self._imports: Optional[dict] = None

    # -- identity ----------------------------------------------------------

    @property
    def is_compat(self) -> bool:
        """True for ``bigdl_tpu/utils/compat.py`` itself — the one module
        allowed to spell version-moved jax APIs directly."""
        p = self.relpath.replace(os.sep, "/")
        return p.endswith("bigdl_tpu/utils/compat.py") or \
            p.endswith("utils/compat.py")

    # -- finding construction ---------------------------------------------

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, node: ast.AST, code: str, message: str,
                hint: str = "") -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        return Finding(path=self.relpath, line=line, col=col, code=code,
                       message=message, hint=hint,
                       source=self.source_line(line))

    # -- structure helpers -------------------------------------------------

    @property
    def parents(self) -> dict:
        """child-node -> parent-node map (built lazily, once per file)."""
        if self._parents is None:
            self._parents = {}
            for parent in ast.walk(self.tree):
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or None
        at module level."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    # -- import resolution -------------------------------------------------

    @property
    def imports(self) -> dict:
        """local alias -> fully qualified dotted name, from every
        Import/ImportFrom in the file (any nesting level — the repo
        imports jax inside functions deliberately)."""
        if self._imports is not None:
            return self._imports
        amap: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        amap[a.asname] = a.name
                    else:
                        # `import jax.lax` binds `jax`; the chain resolves
                        # attribute-by-attribute from the root
                        amap[a.name.split(".")[0]] = a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    amap[a.asname or a.name] = f"{node.module}.{a.name}"
        self._imports = amap
        return amap

    def qualname(self, node: ast.AST) -> Optional[str]:
        """Resolve a Name/Attribute chain to a fully qualified dotted
        name using the file's imports (``lax.pvary`` -> ``jax.lax.pvary``
        under ``from jax import lax``).  None when the root is not an
        imported name."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.imports.get(cur.id)
        if root is None:
            return None
        return ".".join([root] + list(reversed(parts)))

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Unresolved dotted spelling of a Name/Attribute chain
        (``self._scatter``), for matching local callables and reuse of
        donated buffers.  None for anything else."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        return ".".join([cur.id] + list(reversed(parts)))


class Rule:
    """Base class: subclasses set ``code``/``name``/``hint`` and
    implement :meth:`check`."""

    code: str = ""
    name: str = ""
    #: one-line description for --list-rules / docs
    summary: str = ""
    hint: str = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError


_REGISTRY: List[Rule] = []


def register(cls):
    """Class decorator adding a rule (instantiated once) to the registry."""
    _REGISTRY.append(cls())
    return cls


def all_rules() -> List[Rule]:
    return list(_REGISTRY)


def rule_codes() -> List[str]:
    return [r.code for r in _REGISTRY]


# -- engine ----------------------------------------------------------------

def _iter_py_files(paths: Sequence[str],
                   exclude_dirs: Iterable[str]) -> Iterator[Path]:
    excl = set(exclude_dirs)
    for p in paths:
        path = Path(p)
        if path.is_file():
            # explicit file paths bypass directory exclusion — that is
            # how the fixture tests point the engine at deliberate
            # violations
            if path.suffix == ".py":
                yield path
        elif path.is_dir():
            for f in sorted(path.rglob("*.py")):
                if not any(part in excl for part in f.parts):
                    yield f


def _relpath(p: Path) -> str:
    try:
        rel = p.resolve().relative_to(Path.cwd().resolve())
        return rel.as_posix()
    except ValueError:
        return p.as_posix()


def analyze_source(text: str, path: str = "<string>",
                   select: Optional[Iterable[str]] = None,
                   ignore: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run the selected rules over one source string (test/fixture entry
    point; :func:`analyze_paths` is the file-walking wrapper)."""
    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 1, col=(e.offset or 1),
                        code="SPMD000",
                        message=f"file does not parse: {e.msg}",
                        source=(e.text or "").strip())]
    ctx = FileContext(path=path, relpath=path, text=text, tree=tree)
    sel = set(select) if select else None
    ign = set(ignore) if ignore else set()
    out: List[Finding] = []
    for rule in _REGISTRY:
        if sel is not None and rule.code not in sel:
            continue
        if rule.code in ign:
            continue
        out.extend(rule.check(ctx))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    # occurrence-index repeated (code, source) pairs in source order so
    # each duplicate line needs its own baseline entry
    seen: dict = {}
    for i, f in enumerate(out):
        k = (f.code, f.source)
        idx = seen.get(k, 0)
        seen[k] = idx + 1
        if idx:
            out[i] = dataclasses.replace(f, occurrence=idx)
    return out


def analyze_paths(paths: Sequence[str],
                  select: Optional[Iterable[str]] = None,
                  ignore: Optional[Iterable[str]] = None,
                  exclude_dirs: Iterable[str] = DEFAULT_EXCLUDE_DIRS,
                  ) -> List[Finding]:
    """Walk ``paths`` (files and/or directories) and run the rules."""
    findings: List[Finding] = []
    for f in _iter_py_files(paths, exclude_dirs):
        text = f.read_text(encoding="utf-8", errors="replace")
        findings.extend(analyze_source(text, path=_relpath(f),
                                       select=select, ignore=ignore))
    findings.sort(key=lambda x: (x.path, x.line, x.col, x.code))
    return findings


# -- baseline --------------------------------------------------------------

def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    """Parse a baseline file into ``{(path, code, fingerprint)}``.

    Format: one entry per line, ``path:CODE:fingerprint``; blank lines
    and ``#`` comments (the required justifications) are skipped."""
    entries: Set[Tuple[str, str, str]] = set()
    p = Path(path)
    if not p.exists():
        return entries
    for raw in p.read_text(encoding="utf-8").splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        # path may itself contain ':' on exotic systems — split from the
        # right, the code and fingerprint never do
        parts = line.rsplit(":", 2)
        if len(parts) == 3:
            entries.add((parts[0], parts[1], parts[2]))
    return entries


def split_baselined(findings: Sequence[Finding],
                    baseline: Set[Tuple[str, str, str]],
                    ) -> Tuple[List[Finding], List[Finding]]:
    """-> (new, grandfathered)."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if f.baseline_key() in baseline else new).append(f)
    return new, old


def format_baseline_entry(f: Finding) -> str:
    """One ready-to-commit baseline line (offending source as a trailing
    comment so reviewers see what is being grandfathered)."""
    path, code, fp = f.baseline_key()
    return f"# line {f.line}: {f.source}\n{path}:{code}:{fp}"
