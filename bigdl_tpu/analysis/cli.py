"""Command-line front end: ``python -m bigdl_tpu.analysis``.

Exit status is the contract CI rides on: 0 when every finding is
baselined (or there are none), 1 when NEW findings exist, 2 on usage
errors.  ``--format json`` (or ``--json``) emits a machine-readable
report so future tooling can diff findings across PRs; ``--format
sarif`` emits SARIF 2.1.0 so GitHub code scanning renders findings as
inline annotations.  The JSON schema is frozen — SARIF is a sibling
format, not a replacement.

Baseline hygiene: a normal scan WARNS (stderr, exit code preserved)
when the baseline contains STALE entries — fingerprints matching no
current finding, i.e. fixed-or-edited violations whose entries would
silently grandfather a future regression pasted at the same spot —
and ``--prune-baseline`` rewrites the baseline file without them
(each entry's justification comment goes with it).

Speed: the CLI (not the library API) runs with a content-hash findings
cache (``.cache/analysis_cache.json``; ``--no-cache`` disables) and a
forked parallel parser for cache misses (``--jobs``), so the
steady-state pre-commit gate costs well under a second — see
``core.scan``'s contract for why the cache can never change results.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import List, Optional

from bigdl_tpu.analysis.core import (
    DEFAULT_EXCLUDE_DIRS, all_rules, covered_by_scan,
    format_baseline_entry, load_baseline, load_project,
    prune_baseline_text, rule_codes, scan, split_baselined,
    stale_entries,
)

#: what the pass covers when no paths are given — the three analyzed
#: planes plus their tests/benchmarks, mirroring tests/test_static_analysis
DEFAULT_PATHS = ["bigdl_tpu", "benchmarks", "tests"]
DEFAULT_BASELINE = "analysis_baseline.txt"
DEFAULT_CACHE = os.path.join(".cache", "analysis_cache.json")


def _parse_codes(s: Optional[str]) -> Optional[List[str]]:
    if not s:
        return None
    return [c.strip() for c in s.split(",") if c.strip()]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.analysis",
        description="SPMD hygiene + serving-contract analyzer: "
                    "whole-program AST lint for recompilation, "
                    "sharding-spec, jax-compat, and serving-plane "
                    "invariant drift.")
    p.add_argument("paths", nargs="*", default=None,
                   help=f"files/directories to analyze "
                        f"(default: {' '.join(DEFAULT_PATHS)})")
    p.add_argument("--select", metavar="CODES",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--ignore", metavar="CODES",
                   help="comma-separated rule codes to skip")
    p.add_argument("--baseline", metavar="FILE", default=DEFAULT_BASELINE,
                   help=f"baseline file of grandfathered findings "
                        f"(default: {DEFAULT_BASELINE}; missing file = "
                        f"empty baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="print ready-to-commit baseline entries for the "
                        "current findings and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="rewrite the baseline file dropping STALE "
                        "entries (fingerprints matching no current "
                        "finding), then report as usual")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text", dest="fmt",
                   help="report format (sarif renders as GitHub "
                        "annotations in CI; json is the stable "
                        "machine-readable schema)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="alias for --format json (kept stable for "
                        "existing tooling)")
    p.add_argument("--list-rules", action="store_true",
                   help="list rule codes and exit")
    p.add_argument("--report", choices=("sync-points", "lockstep"),
                   default=None,
                   help="print a whole-program report instead of "
                        "findings: 'sync-points' inventories every "
                        "hot-path device→host sync (declared fences + "
                        "ASY findings) with its root chain — the "
                        "async-refactor worksheet; 'lockstep' "
                        "inventories every cross-process agreement "
                        "point, divergence root, and declared clock "
                        "site — the multi-host pod worksheet "
                        "(exit 0; combine with --format json for the "
                        "machine shape)")
    p.add_argument("--jobs", type=int, default=0, metavar="N",
                   help="parallel parse workers for cache misses "
                        "(default: the host's cores; 1 = serial)")
    p.add_argument("--no-cache", action="store_true",
                   help=f"disable the findings cache "
                        f"({DEFAULT_CACHE})")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress per-finding hints")
    return p


def to_sarif(findings, rules) -> dict:
    """Findings as a minimal SARIF 2.1.0 log (one run, one result per
    NEW finding; the content fingerprint rides along so code-scanning
    dedup matches the baseline's identity rules)."""
    by_code = {}
    for r in rules:
        by_code[r.code] = {
            "id": r.code,
            "name": r.name,
            "shortDescription": {"text": r.summary},
            "help": {"text": r.hint},
        }
    results = []
    for f in findings:
        results.append({
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line,
                               "startColumn": f.col},
                },
            }],
            "partialFingerprints": {
                "bigdlAnalysis/v1": f.fingerprint(),
            },
        })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "bigdl-tpu-analysis",
                "informationUri": "docs/analysis.md",
                "rules": [by_code[c] for c in sorted(by_code)],
            }},
            "results": results,
        }],
    }


def _short_chain(chain: List[str]) -> str:
    """Root chain with module prefixes dropped for the text report
    (``ServingEngine.step -> ChunkedAdmissionController.pump``)."""
    out = []
    for q in chain:
        parts = q.split(".")
        out.append(".".join(parts[-2:]) if len(parts) >= 2 else q)
    return " -> ".join(out)


def _run_report(paths: List[str], fmt: str, name: str, inventory_fn,
                summary_counts, header_fn) -> int:
    """Shared driver for the whole-program reports (`sync-points`,
    `lockstep`): load the project, build the inventory, emit JSON or
    the text shape (header, parse-error warnings, one block per entry
    — findings carry their classification + fix hint). Informational:
    exits 0 (the normal scan is the gate that FAILS on findings)."""
    contexts, errors = load_project(paths,
                                    exclude_dirs=DEFAULT_EXCLUDE_DIRS)
    entries = inventory_fn(contexts)
    counts = {key: sum(1 for e in entries if e["kind"].startswith(pfx))
              for key, pfx in summary_counts.items()}
    if fmt in ("json", "sarif"):
        print(json.dumps({
            "report": name,
            "paths": list(paths),
            "entries": entries,
            "summary": {**counts, "parse_errors": len(errors)},
        }, indent=2))
        return 0
    print(header_fn(counts))
    for err in errors:
        # a file that does not parse is NOT inventoried — the
        # worksheet must say so rather than read as complete
        print(f"# WARNING: {err.path}:{err.line} failed to parse and "
              f"is not inventoried ({err.message})", file=sys.stderr)
    finding_pfx = summary_counts["findings"]
    for e in entries:
        supp = "  [suppressed: # analysis: ok]" if e["suppressed"] else ""
        print(f"{e['path']}:{e['line']} [{e['kind']}]{supp}")
        if e["function"]:
            print(f"    in {e['function']}")
        if e["chain"]:
            print(f"    chain: {_short_chain(e['chain'])}")
        if e.get("window"):
            # sync-points only: which side of the dispatch-ahead
            # window this site sits on (delayed consumer vs inline)
            print(f"    window: {e['window']}")
        if e["kind"].startswith(finding_pfx):
            print(f"    {e['classification']}")
        if e["detail"]:
            print(f"    | {e['detail']}")
        if e["kind"].startswith(finding_pfx) and e["suggestion"]:
            print(f"    fix: {e['suggestion']}")
    return 0


def report_sync_points(paths: List[str], fmt: str) -> int:
    """``--report sync-points``: the async-refactor worksheet — every
    hot-path device→host sync (declared fence sites + any un-fenced
    ASY finding) with its call-graph root chain."""
    from bigdl_tpu.analysis.rules import sync_point_inventory

    return _run_report(
        paths, fmt, "sync-points", sync_point_inventory,
        {"declared": "fence", "findings": "ASY"},
        lambda c: (f"# hot-path sync-point inventory — {c['declared']} "
                   f"declared fence site(s), {c['findings']} un-fenced "
                   f"finding(s)"))


def report_lockstep(paths: List[str], fmt: str) -> int:
    """``--report lockstep``: the multi-host pod worksheet — every
    cross-process agreement point (collectives, compiled-step
    dispatches, block-store barriers) with its root chain, every
    divergence root (process_index/count, per-peer store reads), the
    declared clock sites, and any un-fixed MH finding."""
    from bigdl_tpu.analysis.rules import lockstep_inventory

    return _run_report(
        paths, fmt, "lockstep", lockstep_inventory,
        {"agreement": "agreement", "divergence": "divergence",
         "clock_sites": "clock", "findings": "MH"},
        lambda c: (f"# multi-host lockstep inventory — {c['agreement']} "
                   f"agreement point(s), {c['divergence']} divergence "
                   f"root(s), {c['clock_sites']} declared clock "
                   f"site(s), {c['findings']} MH finding(s)"))


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.code}  {r.name}: {r.summary}")
        return 0

    fmt = "json" if args.as_json else args.fmt
    if args.prune_baseline and args.no_baseline:
        # with the baseline ignored, EVERY entry would look stale and
        # the prune would empty the file — refuse the combination
        print("error: --prune-baseline conflicts with --no-baseline "
              "(pruning judges entries against the baseline-aware scan)",
              file=sys.stderr)
        return 2
    select = _parse_codes(args.select)
    ignore = _parse_codes(args.ignore)
    known = set(rule_codes())
    for c in (select or []) + (ignore or []):
        if c not in known:
            print(f"error: unknown rule code {c!r} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2

    paths = args.paths or DEFAULT_PATHS
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        # a typo'd or wrong-cwd path silently scanning ZERO files would
        # be a false green on the exact exit code CI rides on
        print(f"error: path(s) do not exist: {', '.join(missing)} "
              f"(cwd: {Path.cwd()})", file=sys.stderr)
        return 2
    if args.report == "sync-points":
        return report_sync_points(paths, fmt)
    if args.report == "lockstep":
        return report_lockstep(paths, fmt)
    jobs = args.jobs or (os.cpu_count() or 1)
    findings = scan(paths, select=select, ignore=ignore,
                    exclude_dirs=DEFAULT_EXCLUDE_DIRS,
                    cache_path=None if args.no_cache else DEFAULT_CACHE,
                    jobs=max(1, jobs))

    if args.write_baseline:
        print(f"# SPMD hygiene baseline — {len(findings)} grandfathered "
              "finding(s).")
        print("# Every entry MUST carry a justification comment; prefer "
              "fixing over baselining.")
        for f in findings:
            print(format_baseline_entry(f))
        return 0

    baseline = set() if args.no_baseline else load_baseline(args.baseline)
    # staleness is judged only over what THIS scan covered (files under
    # the scanned paths, rules actually run): a partial scan must never
    # declare other files' grandfathered entries dead, let alone prune
    # them
    run_codes = (set(select) if select else known) - set(ignore or [])
    stale = stale_entries(findings, baseline,
                          covered=covered_by_scan(paths),
                          codes=run_codes)
    if args.prune_baseline and Path(args.baseline).exists():
        keep = set(baseline) - stale
        text = Path(args.baseline).read_text(encoding="utf-8")
        new_text, removed = prune_baseline_text(text, keep)
        if removed:
            Path(args.baseline).write_text(new_text, encoding="utf-8")
        print(f"pruned {removed} stale baseline entr"
              f"{'y' if removed == 1 else 'ies'} from {args.baseline}",
              file=sys.stderr)
        baseline -= stale
        stale = set()
    elif stale:
        # exit-code preserving: a stale entry is hygiene debt, not a
        # failure — but every scan says so until someone prunes
        print(f"warning: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} in {args.baseline} "
              f"match no current finding — run --prune-baseline",
              file=sys.stderr)
    new, grandfathered = split_baselined(findings, baseline)

    if fmt == "sarif":
        print(json.dumps(to_sarif(new, all_rules()), indent=2))
        return 1 if new else 0
    if fmt == "json":
        print(json.dumps({
            "paths": list(paths),
            "rules": sorted(select or known),
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in grandfathered],
            "summary": {
                "new": len(new),
                "baselined": len(grandfathered),
                "total": len(findings),
            },
        }, indent=2))
        return 1 if new else 0

    for f in new:
        print(f.format(show_hint=not args.quiet))
    if new:
        counts: dict = {}
        for f in new:
            counts[f.code] = counts.get(f.code, 0) + 1
        per_code = ", ".join(f"{k} x{v}" for k, v in sorted(counts.items()))
        print(f"\n{len(new)} new finding(s) [{per_code}]"
              + (f", {len(grandfathered)} baselined" if grandfathered
                 else ""))
        return 1
    tail = f" ({len(grandfathered)} baselined)" if grandfathered else ""
    print(f"clean: 0 new findings{tail}")
    return 0
