"""Command-line front end: ``python -m bigdl_tpu.analysis``.

Exit status is the contract CI rides on: 0 when every finding is
baselined (or there are none), 1 when NEW findings exist, 2 on usage
errors.  ``--json`` emits a machine-readable report so future tooling
can diff findings across PRs.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from bigdl_tpu.analysis.core import (
    DEFAULT_EXCLUDE_DIRS, all_rules, analyze_paths,
    format_baseline_entry, load_baseline, rule_codes, split_baselined,
)

#: what the pass covers when no paths are given — the three analyzed
#: planes plus their tests/benchmarks, mirroring tests/test_static_analysis
DEFAULT_PATHS = ["bigdl_tpu", "benchmarks", "tests"]
DEFAULT_BASELINE = "analysis_baseline.txt"


def _parse_codes(s: Optional[str]) -> Optional[List[str]]:
    if not s:
        return None
    return [c.strip() for c in s.split(",") if c.strip()]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.analysis",
        description="SPMD hygiene analyzer: AST lint for recompilation, "
                    "sharding-spec, and jax-compat drift.")
    p.add_argument("paths", nargs="*", default=None,
                   help=f"files/directories to analyze "
                        f"(default: {' '.join(DEFAULT_PATHS)})")
    p.add_argument("--select", metavar="CODES",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--ignore", metavar="CODES",
                   help="comma-separated rule codes to skip")
    p.add_argument("--baseline", metavar="FILE", default=DEFAULT_BASELINE,
                   help=f"baseline file of grandfathered findings "
                        f"(default: {DEFAULT_BASELINE}; missing file = "
                        f"empty baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="print ready-to-commit baseline entries for the "
                        "current findings and exit 0")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report (findings + summary) on stdout")
    p.add_argument("--list-rules", action="store_true",
                   help="list rule codes and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress per-finding hints")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.code}  {r.name}: {r.summary}")
        return 0

    select = _parse_codes(args.select)
    ignore = _parse_codes(args.ignore)
    known = set(rule_codes())
    for c in (select or []) + (ignore or []):
        if c not in known:
            print(f"error: unknown rule code {c!r} "
                  f"(known: {', '.join(sorted(known))})", file=sys.stderr)
            return 2

    paths = args.paths or DEFAULT_PATHS
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        # a typo'd or wrong-cwd path silently scanning ZERO files would
        # be a false green on the exact exit code CI rides on
        print(f"error: path(s) do not exist: {', '.join(missing)} "
              f"(cwd: {Path.cwd()})", file=sys.stderr)
        return 2
    findings = analyze_paths(paths, select=select, ignore=ignore,
                             exclude_dirs=DEFAULT_EXCLUDE_DIRS)

    if args.write_baseline:
        print(f"# SPMD hygiene baseline — {len(findings)} grandfathered "
              "finding(s).")
        print("# Every entry MUST carry a justification comment; prefer "
              "fixing over baselining.")
        for f in findings:
            print(format_baseline_entry(f))
        return 0

    baseline = set() if args.no_baseline else load_baseline(args.baseline)
    new, grandfathered = split_baselined(findings, baseline)

    if args.as_json:
        print(json.dumps({
            "paths": list(paths),
            "rules": sorted(select or known),
            "findings": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in grandfathered],
            "summary": {
                "new": len(new),
                "baselined": len(grandfathered),
                "total": len(findings),
            },
        }, indent=2))
        return 1 if new else 0

    for f in new:
        print(f.format(show_hint=not args.quiet))
    if new:
        counts: dict = {}
        for f in new:
            counts[f.code] = counts.get(f.code, 0) + 1
        per_code = ", ".join(f"{k} x{v}" for k, v in sorted(counts.items()))
        print(f"\n{len(new)} new finding(s) [{per_code}]"
              + (f", {len(grandfathered)} baselined" if grandfathered
                 else ""))
        return 1
    tail = f" ({len(grandfathered)} baselined)" if grandfathered else ""
    print(f"clean: 0 new findings{tail}")
    return 0
