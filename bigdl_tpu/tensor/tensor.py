"""Tensor — BigDL-style tensor facade over ``jax.Array``.

Reference role (UNVERIFIED, SURVEY.md §0): ``.../bigdl/tensor/Tensor.scala`` +
``DenseTensor.scala`` — a Torch7-style strided dense tensor with ~400 mutating
methods, generic over the scalar type via the ``TensorNumeric`` type class,
with BLAS fast paths in ``DenseTensorBLAS`` / ``DenseTensorMath`` that call
Intel MKL over JNI.

TPU-native redesign — deliberately NOT a strided-storage port:

* Storage/stride machinery is XLA's job. ``Tensor`` wraps one immutable
  ``jax.Array``; views (``select``/``narrow``/``t``) are lazy XLA slices that
  fuse into consumers, which beats materialized strided views on TPU.
* "In-place" reference methods (``add``, ``mul_``-style) rebind the wrapped
  array on the host object. Inside jitted code the pure functional form is
  used; the mutating surface exists for source-level parity at user level.
* ``TensorNumeric[T]`` collapses to the dtype: ``Tensor(..., dtype=...)``.
  MKL BLAS calls (``MKL.vsgemm`` etc.) become ``jnp.dot``/``lax`` ops that
  XLA lowers to MXU matmuls in bf16/f32.
* Registered as a JAX pytree, so Tensors can cross jit boundaries and live
  inside param pytrees (they mostly don't need to — modules use raw arrays).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import numpy as np

ArrayLike = Any


def _unwrap(x: Any):
    return x.data if isinstance(x, Tensor) else x


def _x64_enabled() -> bool:
    import jax

    return bool(jax.config.jax_enable_x64)


def _resolve_dim(dim: int, ndim: int) -> int:
    """1-based positive dims; negative dims count from the end (numpy
    style); 0 is invalid in the 1-based convention."""
    if dim > 0:
        return dim - 1
    if dim < 0 and -dim <= ndim:
        return ndim + dim
    raise ValueError(f"invalid 1-based dim {dim} for ndim {ndim}")


class Tensor:
    """Dense tensor facade. ``Tensor(np_or_jax_array)`` or ``Tensor(*sizes)``."""

    __slots__ = ("data",)
    # Let `np_array * tensor` dispatch to our __rmul__ instead of numpy
    # broadcasting over the wrapper object.
    __array_priority__ = 100

    def __init__(self, *args: Any, dtype: Any = None) -> None:
        import jax.numpy as jnp

        if len(args) == 1 and not isinstance(args[0], (int, np.integer)):
            arr = _unwrap(args[0])
            self.data = jnp.asarray(arr, dtype=dtype)
        elif len(args) == 0:
            self.data = jnp.zeros((), dtype=dtype or jnp.float32)
        else:  # Tensor(2, 3) — zero-filled with the given shape
            sizes = tuple(int(a) for a in args)
            self.data = jnp.zeros(sizes, dtype=dtype or jnp.float32)

    # -- shape/meta --------------------------------------------------------

    def size(self, dim: Optional[int] = None):
        """1-based ``dim`` like the reference; no arg returns the full shape."""
        if dim is None:
            return tuple(self.data.shape)
        return self.data.shape[dim - 1]

    def dim(self) -> int:
        return self.data.ndim

    def n_element(self) -> int:
        return int(np.prod(self.data.shape)) if self.data.ndim else 1

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    # -- construction helpers ---------------------------------------------

    @staticmethod
    def zeros(*sizes: int, dtype: Any = None) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.zeros(sizes, dtype=dtype or jnp.float32))

    @staticmethod
    def ones(*sizes: int, dtype: Any = None) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.ones(sizes, dtype=dtype or jnp.float32))

    @staticmethod
    def arange(start: float, end: float, step: float = 1.0) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.arange(start, end, step, dtype=jnp.float32))

    @staticmethod
    def randn(*sizes: int, seed: int = 0) -> "Tensor":
        import jax

        return Tensor(jax.random.normal(jax.random.PRNGKey(seed), sizes))

    def fill(self, value: float) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.full_like(self.data, value)
        return self

    def zero(self) -> "Tensor":
        return self.fill(0.0)

    def copy(self, other: "Tensor") -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.asarray(_unwrap(other), dtype=self.data.dtype).reshape(
            self.data.shape
        )
        return self

    def clone(self) -> "Tensor":
        return Tensor(self.data)

    # -- views (lazy XLA slices, not strided storage) ----------------------

    def view(self, *sizes: int) -> "Tensor":
        return Tensor(self.data.reshape(sizes))

    def reshape(self, sizes: Sequence[int]) -> "Tensor":
        return Tensor(self.data.reshape(tuple(sizes)))

    def resize(self, *sizes: int) -> "Tensor":
        """Reference ``resize`` reallocates; here: reshape if same count else new zeros."""
        import jax.numpy as jnp

        if int(np.prod(sizes)) == self.n_element():
            self.data = self.data.reshape(sizes)
        else:
            self.data = jnp.zeros(sizes, dtype=self.data.dtype)
        return self

    def select(self, dim: int, index: int) -> "Tensor":
        """1-based dim and index, like the reference."""
        import jax.numpy as jnp

        return Tensor(jnp.take(self.data, index - 1, axis=dim - 1))

    def narrow(self, dim: int, index: int, size: int) -> "Tensor":
        import jax.lax as lax

        starts = [0] * self.data.ndim
        sizes = list(self.data.shape)
        starts[dim - 1] = index - 1
        sizes[dim - 1] = size
        return Tensor(lax.dynamic_slice(self.data, starts, sizes))

    def t(self) -> "Tensor":
        return Tensor(self.data.T)

    def transpose(self, dim1: int, dim2: int) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.swapaxes(self.data, dim1 - 1, dim2 - 1))

    def squeeze(self, dim: Optional[int] = None) -> "Tensor":
        import jax.numpy as jnp

        if dim is None:
            return Tensor(jnp.squeeze(self.data))
        return Tensor(jnp.squeeze(self.data, axis=dim - 1))

    def unsqueeze(self, dim: int) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.expand_dims(self.data, axis=dim - 1))

    def contiguous(self) -> "Tensor":
        return self  # XLA arrays are always "contiguous" logically

    # -- elementwise math (mutating surface rebinds; pure forms return new) --

    def add(self, *args) -> "Tensor":
        """add(value) | add(other) | add(alpha, other) — in-place like reference."""
        if len(args) == 1:
            self.data = self.data + _unwrap(args[0])
        else:
            alpha, other = args
            self.data = self.data + alpha * _unwrap(other)
        return self

    def sub(self, *args) -> "Tensor":
        if len(args) == 1:
            self.data = self.data - _unwrap(args[0])
        else:
            alpha, other = args
            self.data = self.data - alpha * _unwrap(other)
        return self

    def mul(self, value) -> "Tensor":
        self.data = self.data * _unwrap(value)
        return self

    def cmul(self, other) -> "Tensor":
        self.data = self.data * _unwrap(other)
        return self

    def div(self, value) -> "Tensor":
        self.data = self.data / _unwrap(value)
        return self

    def cdiv(self, other) -> "Tensor":
        self.data = self.data / _unwrap(other)
        return self

    def pow(self, n: float) -> "Tensor":
        self.data = self.data ** n
        return self

    def sqrt(self) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.sqrt(self.data)
        return self

    def log(self) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.log(self.data)
        return self

    def exp(self) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.exp(self.data)
        return self

    def abs(self) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.abs(self.data)
        return self

    def negative(self) -> "Tensor":
        self.data = -self.data
        return self

    def clamp(self, min_v: float, max_v: float) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.clip(self.data, min_v, max_v)
        return self

    # -- reductions --------------------------------------------------------

    def sum(self, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.sum(self.data))
        return Tensor(jnp.sum(self.data, axis=dim - 1, keepdims=True))

    def mean(self, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.mean(self.data))
        return Tensor(jnp.mean(self.data, axis=dim - 1, keepdims=True))

    def max(self, dim: Optional[int] = None):
        """No-arg: scalar max. With dim: (values, 1-based indices) like reference."""
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.max(self.data))
        vals = jnp.max(self.data, axis=dim - 1, keepdims=True)
        idx = jnp.argmax(self.data, axis=dim - 1, keepdims=True) + 1
        return Tensor(vals), Tensor(idx)

    def min(self, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.min(self.data))
        vals = jnp.min(self.data, axis=dim - 1, keepdims=True)
        idx = jnp.argmin(self.data, axis=dim - 1, keepdims=True) + 1
        return Tensor(vals), Tensor(idx)

    def norm(self, p: float = 2.0) -> float:
        import jax.numpy as jnp

        return float(jnp.sum(jnp.abs(self.data) ** p) ** (1.0 / p))

    def dot(self, other) -> float:
        import jax.numpy as jnp

        return float(jnp.vdot(self.data, _unwrap(other)))

    # -- linear algebra (MXU path) ----------------------------------------

    def mm(self, a, b) -> "Tensor":
        """self = a @ b (reference ``Tensor.mm``)."""
        import jax.numpy as jnp

        self.data = jnp.matmul(_unwrap(a), _unwrap(b))
        return self

    def addmm(self, *args) -> "Tensor":
        """addmm([beta,] [t,] [alpha,] a, b): self = beta*t + alpha*(a@b).

        Accepts the common reference arities: (a, b), (t, a, b),
        (beta, t, alpha, a, b).
        """
        import jax.numpy as jnp

        beta, alpha = 1.0, 1.0
        if len(args) == 2:
            t, (a, b) = self.data, args
        elif len(args) == 3:
            t, a, b = args
            t = _unwrap(t)
        elif len(args) == 5:
            beta, t, alpha, a, b = args
            t = _unwrap(t)
        else:
            raise TypeError(f"addmm: unsupported arity {len(args)}")
        self.data = beta * t + alpha * jnp.matmul(_unwrap(a), _unwrap(b))
        return self

    def addmv(self, alpha, mat, vec) -> "Tensor":
        import jax.numpy as jnp

        self.data = self.data + alpha * jnp.matmul(_unwrap(mat), _unwrap(vec))
        return self

    def addr(self, alpha, vec1, vec2) -> "Tensor":
        import jax.numpy as jnp

        self.data = self.data + alpha * jnp.outer(_unwrap(vec1), _unwrap(vec2))
        return self

    # -- indexing / comparison --------------------------------------------

    def value_at(self, *indices: int) -> float:
        """1-based scalar read (reference ``valueAt``)."""
        idx = tuple(i - 1 for i in indices)
        return float(self.data[idx])

    def set_value(self, *args) -> "Tensor":
        """1-based scalar write: set_value(i, j, ..., value)."""
        idx = tuple(i - 1 for i in args[:-1])
        self.data = self.data.at[idx].set(args[-1])
        return self

    def almost_equal(self, other, tolerance: float = 1e-6) -> bool:
        return bool(
            np.allclose(np.asarray(self.data), np.asarray(_unwrap(other)),
                        atol=tolerance, rtol=0)
        )

    # -- numpy/jax interop -------------------------------------------------

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def __array__(self, dtype=None):
        a = np.asarray(self.data)
        return a.astype(dtype) if dtype is not None else a

    # -- operators ---------------------------------------------------------

    def __add__(self, other):
        return Tensor(self.data + _unwrap(other))

    __radd__ = __add__

    def __sub__(self, other):
        return Tensor(self.data - _unwrap(other))

    def __rsub__(self, other):
        return Tensor(_unwrap(other) - self.data)

    def __mul__(self, other):
        return Tensor(self.data * _unwrap(other))

    __rmul__ = __mul__

    def __truediv__(self, other):
        return Tensor(self.data / _unwrap(other))

    def __neg__(self):
        return Tensor(-self.data)

    def __matmul__(self, other):
        import jax.numpy as jnp

        return Tensor(jnp.matmul(self.data, _unwrap(other)))

    def __getitem__(self, item):
        return Tensor(self.data[item])

    # -- batched linear algebra -------------------------------------------

    def bmm(self, other: "Tensor") -> "Tensor":
        """Batched matmul (reference ``baddbmm`` family's core)."""
        import jax.numpy as jnp

        return Tensor(jnp.matmul(self.data, _unwrap(other)))

    def baddbmm(self, beta: float, alpha: float, a, b) -> "Tensor":
        import jax.numpy as jnp

        self.data = beta * self.data + alpha * jnp.matmul(
            _unwrap(a), _unwrap(b))
        return self

    # -- selection / indexing ---------------------------------------------

    def index_select(self, dim: int, index) -> "Tensor":
        """1-based dim; 1-based indices (reference ``indexSelect``)."""
        import jax.numpy as jnp

        idx = jnp.asarray(_unwrap(index)).astype(jnp.int32) - 1
        return Tensor(jnp.take(self.data, idx, axis=dim - 1))

    def gather(self, dim: int, index) -> "Tensor":
        """1-based dim; 1-based index tensor of the output shape."""
        import jax.numpy as jnp

        idx = jnp.asarray(_unwrap(index)).astype(jnp.int32) - 1
        return Tensor(jnp.take_along_axis(self.data, idx, axis=dim - 1))

    def scatter(self, dim: int, index, src) -> "Tensor":
        import jax.numpy as jnp

        idx = jnp.asarray(_unwrap(index)).astype(jnp.int32) - 1
        ax = dim - 1
        # build open meshgrid index tuple with idx substituted on ax
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape],
                             indexing="ij")
        loc = tuple(idx if i == ax else g for i, g in enumerate(grids))
        return Tensor(self.data.at[loc].set(_unwrap(src)))

    def masked_fill(self, mask, value: float) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.where(jnp.asarray(_unwrap(mask), bool),
                              value, self.data)
        return self

    def masked_select(self, mask):
        """Host-eager: returns the selected elements as a 1-D numpy array
        (dynamic shape — facade level only, never inside jit)."""
        m = np.asarray(_unwrap(mask)).astype(bool)
        return np.asarray(self.data)[m]

    # -- round-3 long tail (demand-driven, torch-oracle-tested) ------------

    def all(self) -> bool:
        import jax.numpy as jnp

        return bool(jnp.all(self.data != 0))

    def any(self) -> bool:
        import jax.numpy as jnp

        return bool(jnp.any(self.data != 0))

    def topk(self, k: int, dim: Optional[int] = None, largest: bool = True,
             sorted: bool = True):
        """(values, 1-based indices) along ``dim`` (1-based; default last),
        reference ``topk(k, dim, increase, ...)`` with ``largest`` being
        the torch dialect of ``increase=false``."""
        import jax.numpy as jnp

        from jax import lax

        ax = (self.data.ndim if dim is None else dim) - 1
        x = jnp.moveaxis(self.data, ax, -1)
        if largest:
            vals, idx = lax.top_k(x, k)
        else:
            vals, idx = lax.top_k(-x, k)
            vals = -vals
        return (Tensor(jnp.moveaxis(vals, -1, ax)),
                Tensor(jnp.moveaxis(idx + 1, -1, ax)))

    def apply_(self, fn) -> "Tensor":
        """Host-eager elementwise scalar function (reference ``apply1``);
        facade-only — never inside jit."""
        import jax.numpy as jnp

        host = np.asarray(self.data)
        out = np.vectorize(fn, otypes=[host.dtype])(host)
        self.data = jnp.asarray(out)
        return self

    def index_fill_(self, dim: int, index, value: float) -> "Tensor":
        """Fill rows at 1-based ``index`` along 1-based ``dim``."""
        import jax.numpy as jnp

        idx = jnp.asarray(_unwrap(index)).astype(jnp.int32).reshape(-1) - 1
        sl = tuple([slice(None)] * (dim - 1) + [idx])
        self.data = self.data.at[sl].set(value)
        return self

    def index_copy_(self, dim: int, index, src) -> "Tensor":
        import jax.numpy as jnp

        idx = jnp.asarray(_unwrap(index)).astype(jnp.int32).reshape(-1) - 1
        sl = tuple([slice(None)] * (dim - 1) + [idx])
        self.data = self.data.at[sl].set(_unwrap(src))
        return self

    def index_add_(self, dim: int, index, src) -> "Tensor":
        import jax.numpy as jnp

        idx = jnp.asarray(_unwrap(index)).astype(jnp.int32).reshape(-1) - 1
        sl = tuple([slice(None)] * (dim - 1) + [idx])
        self.data = self.data.at[sl].add(_unwrap(src))
        return self

    def top_k(self, k: int, dim: int = -1, increase: bool = False):
        """(values, 1-based indices); ``increase=False`` = largest first
        (reference ``topk``)."""
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        data = self.data if not increase else -self.data
        idx = jnp.argsort(-data, axis=ax)
        idx = jnp.take(idx, jnp.arange(k), axis=ax)
        vals = jnp.take_along_axis(self.data, idx, axis=ax)
        return Tensor(vals), Tensor(idx + 1)

    def sort(self, dim: int = -1, descending: bool = False):
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        idx = jnp.argsort(-self.data if descending else self.data, axis=ax)
        return (Tensor(jnp.take_along_axis(self.data, idx, axis=ax)),
                Tensor(idx + 1))

    # -- shape manipulation -----------------------------------------------

    def expand(self, *sizes) -> "Tensor":
        import jax.numpy as jnp

        sizes = sizes[0] if len(sizes) == 1 and isinstance(
            sizes[0], (list, tuple)) else sizes
        return Tensor(jnp.broadcast_to(self.data, tuple(int(s) for s in sizes)))

    def expand_as(self, other: "Tensor") -> "Tensor":
        return self.expand(*_unwrap(other).shape)

    def repeat_tensor(self, *reps) -> "Tensor":
        """Tile (reference ``repeatTensor``)."""
        import jax.numpy as jnp

        reps = reps[0] if len(reps) == 1 and isinstance(
            reps[0], (list, tuple)) else reps
        return Tensor(jnp.tile(self.data, tuple(int(r) for r in reps)))

    def split(self, size: int, dim: int = 1):
        """List of chunks of ``size`` along 1-based ``dim`` (reference
        ``split``); last chunk may be smaller."""
        import jax.lax as lax

        ax = dim - 1
        n = self.data.shape[ax]
        return [
            Tensor(lax.slice_in_dim(self.data, i, min(i + size, n), axis=ax))
            for i in range(0, n, size)
        ]

    def chunk(self, n_chunks: int, dim: int = 1):
        import math

        size = math.ceil(self.data.shape[dim - 1] / n_chunks)
        return self.split(size, dim)

    @staticmethod
    def cat(tensors, dim: int = 1) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.concatenate([_unwrap(t) for t in tensors],
                                      axis=dim - 1))

    # -- elementwise extras -----------------------------------------------

    def cmax(self, other) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.maximum(self.data, _unwrap(other))
        return self

    def cmin(self, other) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.minimum(self.data, _unwrap(other))
        return self

    def sign(self) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.sign(self.data)
        return self

    def floor(self) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.floor(self.data)
        return self

    def ceil(self) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.ceil(self.data)
        return self

    def round(self) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.round(self.data)
        return self

    def tanh(self) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.tanh(self.data)
        return self

    def sigmoid(self) -> "Tensor":
        import jax

        self.data = jax.nn.sigmoid(self.data)
        return self

    def addcmul(self, scale: float, a, b) -> "Tensor":
        self.data = self.data + scale * _unwrap(a) * _unwrap(b)
        return self

    def addcdiv(self, scale: float, a, b) -> "Tensor":
        self.data = self.data + scale * _unwrap(a) / _unwrap(b)
        return self

    # -- reductions / scans -----------------------------------------------

    def prod(self, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.prod(self.data))
        return Tensor(jnp.prod(self.data, axis=dim - 1))

    def std(self, dim: Optional[int] = None, unbiased: bool = True):
        import jax.numpy as jnp

        dd = 1 if unbiased else 0
        if dim is None:
            return float(jnp.std(self.data, ddof=dd))
        return Tensor(jnp.std(self.data, axis=dim - 1, ddof=dd))

    def var(self, dim: Optional[int] = None, unbiased: bool = True):
        import jax.numpy as jnp

        dd = 1 if unbiased else 0
        if dim is None:
            return float(jnp.var(self.data, ddof=dd))
        return Tensor(jnp.var(self.data, axis=dim - 1, ddof=dd))

    def cumsum(self, dim: int = 1) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.cumsum(self.data, axis=dim - 1))

    # -- comparisons (reference ge/gt/le/lt/eq return 0/1 tensors) --------

    def ge(self, other) -> "Tensor":
        return Tensor((self.data >= _unwrap(other)).astype(self.data.dtype))

    def gt(self, other) -> "Tensor":
        return Tensor((self.data > _unwrap(other)).astype(self.data.dtype))

    def le(self, other) -> "Tensor":
        return Tensor((self.data <= _unwrap(other)).astype(self.data.dtype))

    def lt(self, other) -> "Tensor":
        return Tensor((self.data < _unwrap(other)).astype(self.data.dtype))

    def eq(self, other) -> "Tensor":
        return Tensor((self.data == _unwrap(other)).astype(self.data.dtype))

    # -- random fills (reference uniform/normal/bernoulli) ----------------

    def uniform(self, lower: float = 0.0, upper: float = 1.0) -> "Tensor":
        import jax

        from bigdl_tpu.utils.random_gen import RNG

        self.data = jax.random.uniform(
            RNG.next_key(), self.data.shape, self.data.dtype, lower, upper)
        return self

    def normal(self, mean: float = 0.0, stdv: float = 1.0) -> "Tensor":
        import jax

        from bigdl_tpu.utils.random_gen import RNG

        self.data = mean + stdv * jax.random.normal(
            RNG.next_key(), self.data.shape, self.data.dtype)
        return self

    def bernoulli(self, p: float = 0.5) -> "Tensor":
        import jax

        from bigdl_tpu.utils.random_gen import RNG

        self.data = jax.random.bernoulli(
            RNG.next_key(), p, self.data.shape).astype(self.data.dtype)
        return self

    # -- elementwise math breadth (DenseTensorMath parity batch 2) ---------

    def _el(self, fn) -> "Tensor":
        self.data = fn(self.data)
        return self

    def _np_el(self, name: str) -> "Tensor":
        import jax.numpy as jnp

        return self._el(getattr(jnp, name))

    def sin(self):
        return self._np_el("sin")

    def cos(self):
        return self._np_el("cos")

    def tan(self):
        return self._np_el("tan")

    def asin(self):
        return self._np_el("arcsin")

    def acos(self):
        return self._np_el("arccos")

    def atan(self):
        return self._np_el("arctan")

    def sinh(self):
        return self._np_el("sinh")

    def cosh(self):
        return self._np_el("cosh")

    def expm1(self):
        return self._np_el("expm1")

    def log1p(self):
        return self._np_el("log1p")

    def square(self):
        return self._el(lambda x: x * x)

    def reciprocal(self):
        return self._el(lambda x: 1.0 / x)

    def rsqrt(self):
        import jax.lax as lax

        return self._el(lax.rsqrt)

    def erf(self):
        import jax

        return self._el(jax.scipy.special.erf)

    def erfc(self):
        import jax

        return self._el(jax.scipy.special.erfc)

    def atan2(self, other) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.arctan2(self.data, _unwrap(other))
        return self

    def lerp(self, other, weight: float) -> "Tensor":
        o = _unwrap(other)
        self.data = self.data + weight * (o - self.data)
        return self

    def fmod(self, value) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.fmod(self.data, _unwrap(value))
        return self

    def remainder(self, value) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.remainder(self.data, _unwrap(value))
        return self

    def cpow(self, other) -> "Tensor":
        self.data = self.data ** _unwrap(other)
        return self

    def ne(self, other):
        return Tensor((self.data != _unwrap(other)))

    def any_true(self) -> bool:
        return bool(np.asarray(self.data).any())

    def all_true(self) -> bool:
        return bool(np.asarray(self.data).all())

    # -- reductions / scans ------------------------------------------------

    def cumprod(self, dim: int = 1) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.cumprod(self.data, axis=_resolve_dim(dim, self.data.ndim))
        return self

    def median(self, dim: Optional[int] = None):
        """No dim: scalar median (lower of the two for even counts, torch
        convention). With 1-based dim: (values, 1-based indices)."""
        import jax.numpy as jnp

        if dim is None:
            flat = jnp.sort(self.data.reshape(-1))
            return Tensor(flat[(flat.shape[0] - 1) // 2])
        ax = _resolve_dim(dim, self.data.ndim)
        n = self.data.shape[ax]
        srt = jnp.sort(self.data, axis=ax)
        idx = jnp.argsort(self.data, axis=ax)
        take = (n - 1) // 2
        val = jnp.take(srt, take, axis=ax)
        ind = jnp.take(idx, take, axis=ax)
        return Tensor(val), Tensor(ind + 1)

    def kthvalue(self, k: int, dim: int = -1):
        """k-th smallest (1-based k) along 1-based dim → (values, indices)."""
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        srt = jnp.sort(self.data, axis=ax)
        idx = jnp.argsort(self.data, axis=ax)
        return (Tensor(jnp.take(srt, k - 1, axis=ax)),
                Tensor(jnp.take(idx, k - 1, axis=ax) + 1))

    def dist(self, other, norm: float = 2.0) -> float:
        import jax.numpy as jnp

        d = jnp.abs(self.data - _unwrap(other)) ** norm
        return float(jnp.sum(d) ** (1.0 / norm))

    def max_all(self) -> float:
        return float(np.asarray(self.data).max())

    def min_all(self) -> float:
        return float(np.asarray(self.data).min())

    def sum_all(self) -> float:
        return float(np.asarray(self.data).sum())

    # -- linear algebra ----------------------------------------------------

    def trace(self) -> float:
        import jax.numpy as jnp

        return float(jnp.trace(self.data))

    def diag(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.diag(self.data))

    def tril(self, k: int = 0) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.tril(self.data, k))

    def triu(self, k: int = 0) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.triu(self.data, k))

    def ger(self, vec1, vec2) -> "Tensor":
        """Outer product accumulate: self += vec1 ⊗ vec2."""
        import jax.numpy as jnp

        self.data = self.data + jnp.outer(_unwrap(vec1), _unwrap(vec2))
        return self

    def cross(self, other, dim: int = -1) -> "Tensor":
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        return Tensor(jnp.cross(self.data, _unwrap(other), axis=ax))

    def mv(self, mat, vec) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.matmul(_unwrap(mat), _unwrap(vec))
        return self

    def addbmm(self, alpha, mat1, mat2) -> "Tensor":
        """self += alpha * Σ_b mat1[b] @ mat2[b]."""
        import jax.numpy as jnp

        prod = jnp.einsum("bij,bjk->ik", _unwrap(mat1), _unwrap(mat2))
        self.data = self.data + alpha * prod
        return self

    def renorm(self, p: float, dim: int, max_norm: float) -> "Tensor":
        """Clamp the p-norm of every slice along 1-based ``dim``."""
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        moved = jnp.moveaxis(self.data, ax, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-12), 1.0)
        flat = flat * scale[:, None]
        self.data = jnp.moveaxis(flat.reshape(moved.shape), 0, ax)
        return self

    def conv2(self, kernel, mode: str = "V") -> "Tensor":
        """2-D cross-correlation-free convolution (kernel flipped), "V"alid
        or "F"ull — the reference DenseTensorConv role."""
        return self._conv2(kernel, mode, flip=True)

    def xcorr2(self, kernel, mode: str = "V") -> "Tensor":
        """2-D cross-correlation, "V"alid or "F"ull."""
        return self._conv2(kernel, mode, flip=False)

    def _conv2(self, kernel, mode, flip):
        import jax.lax as lax
        import jax.numpy as jnp

        k = jnp.asarray(_unwrap(kernel))
        if flip:
            k = k[::-1, ::-1]
        kh, kw = k.shape
        pad = ((kh - 1, kh - 1), (kw - 1, kw - 1)) if mode == "F" else "VALID"
        out = lax.conv_general_dilated(
            self.data[None, None], k[None, None], (1, 1), pad,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return Tensor(out[0, 0])

    # -- index ops ---------------------------------------------------------

    def nonzero(self) -> "Tensor":
        """(nnz, ndim) 1-based coordinates (host-side; data-dependent shape)."""
        idx = np.nonzero(np.asarray(self.data))
        return Tensor(np.stack(idx, axis=1) + 1)

    def index_add(self, dim: int, index, src) -> "Tensor":
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        ids = jnp.asarray(_unwrap(index), jnp.int32) - 1  # 1-based
        moved = jnp.moveaxis(self.data, ax, 0)
        srcm = jnp.moveaxis(jnp.asarray(_unwrap(src)), ax, 0)
        moved = moved.at[ids].add(srcm)
        self.data = jnp.moveaxis(moved, 0, ax)
        return self

    def index_copy(self, dim: int, index, src) -> "Tensor":
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        ids = jnp.asarray(_unwrap(index), jnp.int32) - 1
        moved = jnp.moveaxis(self.data, ax, 0)
        srcm = jnp.moveaxis(jnp.asarray(_unwrap(src)), ax, 0)
        moved = moved.at[ids].set(srcm)
        self.data = jnp.moveaxis(moved, 0, ax)
        return self

    def index_fill(self, dim: int, index, value) -> "Tensor":
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        ids = jnp.asarray(_unwrap(index), jnp.int32) - 1
        moved = jnp.moveaxis(self.data, ax, 0)
        moved = moved.at[ids].set(value)
        self.data = jnp.moveaxis(moved, 0, ax)
        return self

    def masked_copy(self, mask, src) -> "Tensor":
        """Copy src values (taken in order) into the masked slots —
        host-side like the reference (data-dependent gather order)."""
        dense = np.asarray(self.data).copy()
        m = np.asarray(_unwrap(mask)).astype(bool)
        vals = np.asarray(_unwrap(src)).reshape(-1)
        dense[m] = vals[: int(m.sum())]
        self.data = type(self)(dense).data
        return self

    def unfold(self, dim: int, size: int, step: int) -> "Tensor":
        """Sliding windows along 1-based dim: new trailing axis of length
        ``size`` (torch semantics)."""
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        n = self.data.shape[ax]
        starts = list(range(0, n - size + 1, step))
        slabs = [jnp.take(self.data, jnp.arange(s, s + size), axis=ax)
                 for s in starts]
        # windows stack on axis ax; window elements move to the END (torch)
        stacked = jnp.stack(slabs, axis=ax)
        self.data = jnp.moveaxis(stacked, ax + 1, -1)
        return self

    def permute(self, *dims: int) -> "Tensor":
        order = tuple(_resolve_dim(d, self.data.ndim) for d in dims)
        import jax.numpy as jnp

        self.data = jnp.transpose(self.data, order)
        return self

    def resize_as(self, other) -> "Tensor":
        import jax.numpy as jnp

        o = _unwrap(other)
        self.data = jnp.zeros(o.shape, self.data.dtype)
        return self

    def is_same_size_as(self, other) -> bool:
        return tuple(self.data.shape) == tuple(_unwrap(other).shape)

    # -- constructors ------------------------------------------------------

    @staticmethod
    def linspace(start: float, stop: float, n: int) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.linspace(start, stop, n))

    @staticmethod
    def logspace(start: float, stop: float, n: int) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.logspace(start, stop, n))

    @staticmethod
    def range(start: float, stop: float, step: float = 1.0) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.arange(start, stop + step * 0.5, step))

    # -- long-tail reference surface (round-2: Tensor.scala's wider trait) -

    # storage introspection — the strided-storage machinery is XLA's job
    # here (module docstring), so these report the CONTIGUOUS equivalents
    # the reference would for a fresh tensor.

    def storage(self) -> np.ndarray:
        """Flat element view (reference ``storage()``); host copy."""
        return np.asarray(self.data).reshape(-1)

    def storage_offset(self) -> int:
        """1-based offset into storage — always 1: views materialize as
        XLA slices instead of aliasing a shared storage."""
        return 1

    def stride(self, dim: Optional[int] = None):
        """Contiguous row-major strides in elements (1-based ``dim``)."""
        strides = []
        acc = 1
        for s in reversed(self.data.shape):
            strides.append(acc)
            acc *= s
        strides = tuple(reversed(strides))
        if dim is None:
            return strides
        return strides[_resolve_dim(dim, self.data.ndim)]

    def is_contiguous(self) -> bool:
        return True

    def element_size(self) -> int:
        return int(np.dtype(self.data.dtype).itemsize)

    def n_dimension(self) -> int:
        return self.data.ndim

    # dtype conversions (reference Tensor type family / TensorNumeric)

    def _cast(self, dtype) -> "Tensor":
        return Tensor(self.data, dtype=dtype)

    def float(self) -> "Tensor":
        return self._cast(np.float32)

    def double(self) -> "Tensor":
        return self._cast(np.float64)

    def half(self) -> "Tensor":
        return self._cast(np.float16)

    def int(self) -> "Tensor":
        return self._cast(np.int32)

    def long(self) -> "Tensor":
        return self._cast(np.int64)

    def short(self) -> "Tensor":
        return self._cast(np.int16)

    def char(self) -> "Tensor":
        return self._cast(np.int8)

    def byte(self) -> "Tensor":
        return self._cast(np.uint8)

    def bool(self) -> "Tensor":
        return self._cast(np.bool_)

    def type_as(self, other: "Tensor") -> "Tensor":
        return self._cast(_unwrap(other).dtype)

    # apply/map family (reference ``apply1``/``map`` — host-side scalar
    # functions over every element; eager numpy, not jittable by design)

    def apply1(self, fn) -> "Tensor":
        import jax.numpy as jnp

        host = np.asarray(self.data)
        self.data = jnp.asarray(np.vectorize(fn, otypes=[host.dtype])(host))
        return self

    def map(self, other, fn) -> "Tensor":
        """``self[i] = fn(self[i], other[i])`` (reference ``map``)."""
        import jax.numpy as jnp

        a = np.asarray(self.data)
        b = np.asarray(_unwrap(other))
        self.data = jnp.asarray(np.vectorize(fn, otypes=[a.dtype])(a, b))
        return self

    # elementwise math long tail

    def frac(self):
        import jax.numpy as jnp

        return self._el(lambda a: a - jnp.trunc(a))

    def trunc(self):
        return self._np_el("trunc")

    def log2(self):
        return self._np_el("log2")

    def log10(self):
        return self._np_el("log10")

    def exp2(self):
        return self._np_el("exp2")

    def neg(self):
        return self.negative()

    def cinv(self):
        """Elementwise 1/x (reference ``cinv``)."""
        return self.reciprocal()

    def hypot(self, other) -> "Tensor":
        import jax.numpy as jnp

        self.data = jnp.hypot(self.data, _unwrap(other))
        return self

    def lgamma(self):
        import jax.scipy.special as jsp

        return self._el(jsp.gammaln)

    def digamma(self):
        import jax.scipy.special as jsp

        return self._el(jsp.digamma)

    def erfinv(self):
        import jax.scipy.special as jsp

        return self._el(jsp.erfinv)

    def isnan(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.isnan(self.data))

    def isinf(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.isinf(self.data))

    def isfinite(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.isfinite(self.data))

    def equal(self, other) -> bool:
        """Exact shape+value equality (reference ``equals``)."""
        b = _unwrap(other)
        return (tuple(self.data.shape) == tuple(b.shape)
                and bool(np.array_equal(np.asarray(self.data), np.asarray(b))))

    # shape long tail

    def flatten(self) -> "Tensor":
        return Tensor(self.data.reshape(-1))

    ravel = flatten

    def view_as(self, other) -> "Tensor":
        return self.view(*_unwrap(other).shape)

    def flip(self, dim: int) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.flip(self.data, _resolve_dim(dim, self.data.ndim)))

    def roll(self, shift: int, dim: int) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.roll(self.data, shift,
                               _resolve_dim(dim, self.data.ndim)))

    def rot90(self, k: int = 1) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.rot90(self.data, k))

    def tile(self, *reps: int) -> "Tensor":
        return self.repeat_tensor(*reps)

    def take(self, indices) -> "Tensor":
        """1-based LINEAR indices into the flattened tensor (reference
        Torch ``take``)."""
        import jax.numpy as jnp

        idx = jnp.asarray(_unwrap(indices), jnp.int32) - 1
        return Tensor(jnp.take(self.data.reshape(-1), idx))

    def put(self, indices, values) -> "Tensor":
        """1-based linear scatter-write (reference ``put``)."""
        import jax.numpy as jnp

        idx = jnp.asarray(_unwrap(indices), jnp.int32).reshape(-1) - 1
        vals = jnp.asarray(_unwrap(values)).reshape(-1)
        flat = self.data.reshape(-1).at[idx].set(vals)
        self.data = flat.reshape(self.data.shape)
        return self

    def scatter_add(self, dim: int, index, src) -> "Tensor":
        """Like ``scatter`` but accumulating (1-based indices)."""
        import jax.numpy as jnp

        d = _resolve_dim(dim, self.data.ndim)
        idx = jnp.asarray(_unwrap(index), jnp.int32) - 1
        s = jnp.asarray(_unwrap(src))
        grids = jnp.meshgrid(*[jnp.arange(n) for n in idx.shape],
                             indexing="ij")
        grids[d] = idx
        self.data = self.data.at[tuple(grids)].add(s)
        return self

    def argmax(self, dim: Optional[int] = None) -> "Tensor":
        """1-based indices along 1-based ``dim`` (flat 1-based if None)."""
        import jax.numpy as jnp

        if dim is None:
            return Tensor(jnp.argmax(self.data.reshape(-1)) + 1)
        return Tensor(
            jnp.argmax(self.data, _resolve_dim(dim, self.data.ndim)) + 1)

    def argmin(self, dim: Optional[int] = None) -> "Tensor":
        import jax.numpy as jnp

        if dim is None:
            return Tensor(jnp.argmin(self.data.reshape(-1)) + 1)
        return Tensor(
            jnp.argmin(self.data, _resolve_dim(dim, self.data.ndim)) + 1)

    def argsort(self, dim: int = -1, descending: bool = False) -> "Tensor":
        import jax.numpy as jnp

        d = _resolve_dim(dim, self.data.ndim)
        order = jnp.argsort(self.data, axis=d)
        if descending:
            order = jnp.flip(order, axis=d)
        return Tensor(order + 1)

    def msort(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.sort(self.data, axis=0))

    def histc(self, bins: int = 100, min_v: float = 0.0,
              max_v: float = 0.0) -> "Tensor":
        import jax.numpy as jnp

        host = self.data
        if min_v == 0.0 and max_v == 0.0:
            min_v = float(jnp.min(host))
            max_v = float(jnp.max(host))
        hist, _ = jnp.histogram(host.reshape(-1), bins=bins,
                                range=(min_v, max_v))
        return Tensor(hist.astype(self.data.dtype))

    def unique(self) -> "Tensor":
        return Tensor(np.unique(np.asarray(self.data)))

    # linear algebra (reference DenseTensorMath/LAPACK family)

    def inverse(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.linalg.inv(self.data))

    def det(self) -> float:
        import jax.numpy as jnp

        return float(jnp.linalg.det(self.data))

    def svd(self):
        import jax.numpy as jnp

        u, s, vt = jnp.linalg.svd(self.data, full_matrices=False)
        return Tensor(u), Tensor(s), Tensor(vt.T)

    def symeig(self):
        """Eigen-decomposition of a symmetric matrix (reference
        ``symeig``): returns (eigenvalues, eigenvectors)."""
        import jax.numpy as jnp

        w, v = jnp.linalg.eigh(self.data)
        return Tensor(w), Tensor(v)

    def qr(self):
        import jax.numpy as jnp

        q, r = jnp.linalg.qr(self.data)
        return Tensor(q), Tensor(r)

    def potrf(self, upper: bool = True) -> "Tensor":
        """Cholesky factor (reference ``potrf``)."""
        import jax.numpy as jnp

        l = jnp.linalg.cholesky(self.data)
        return Tensor(l.T if upper else l)

    def potrs(self, b, upper: bool = True) -> "Tensor":
        """Solve ``A x = b`` where ``self`` is the ``potrf`` factor
        (upper: ``A = UᵀU``; lower: ``A = LLᵀ``)."""
        import jax.scipy.linalg as jsl

        return Tensor(jsl.cho_solve((self.data, not upper), _unwrap(b)))

    def gesv(self, b) -> "Tensor":
        """Solve ``self @ x = b`` (reference ``gesv``)."""
        import jax.numpy as jnp

        return Tensor(jnp.linalg.solve(self.data, _unwrap(b)))

    def gels(self, b) -> "Tensor":
        """Least squares solve (reference ``gels``)."""
        import jax.numpy as jnp

        sol, _, _, _ = jnp.linalg.lstsq(self.data, _unwrap(b))
        return Tensor(sol)

    def inner(self, other) -> float:
        import jax.numpy as jnp

        return float(jnp.vdot(self.data, _unwrap(other)))

    def matmul(self, other) -> "Tensor":
        return self.__matmul__(other)

    def kron(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.kron(self.data, _unwrap(other)))

    # 3-D convolution family (reference DenseTensorConv conv3/xcorr3)

    def conv3(self, kernel, mode: str = "V") -> "Tensor":
        """3-D convolution (kernel flipped), "V"alid or "F"ull."""
        return self._conv3(kernel, mode, flip=True)

    def xcorr3(self, kernel, mode: str = "V") -> "Tensor":
        """3-D cross-correlation, "V"alid or "F"ull."""
        return self._conv3(kernel, mode, flip=False)

    def _conv3(self, kernel, mode, flip):
        import jax.lax as lax
        import jax.numpy as jnp

        k = jnp.asarray(_unwrap(kernel))
        if flip:
            k = k[::-1, ::-1, ::-1]
        kd, kh, kw = k.shape
        pad = (((kd - 1, kd - 1), (kh - 1, kh - 1), (kw - 1, kw - 1))
               if mode == "F" else "VALID")
        out = lax.conv_general_dilated(
            self.data[None, None].astype(jnp.float32),
            k[None, None].astype(jnp.float32),
            window_strides=(1, 1, 1), padding=pad,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        return Tensor(out[0, 0].astype(self.data.dtype))

    # random fills (reference TH random family; deterministic via RNG)

    def _rng_fill(self, sampler) -> "Tensor":
        import jax

        from bigdl_tpu.utils.random_gen import RNG

        key = RNG.next_key()
        self.data = sampler(key, self.data.shape).astype(self.data.dtype)
        return self

    def exponential(self, lam: float = 1.0) -> "Tensor":
        import jax

        return self._rng_fill(
            lambda k, s: jax.random.exponential(k, s) / lam)

    def cauchy(self, median: float = 0.0, sigma: float = 1.0) -> "Tensor":
        import jax

        return self._rng_fill(
            lambda k, s: jax.random.cauchy(k, s) * sigma + median)

    def log_normal(self, mean: float = 1.0, std: float = 2.0) -> "Tensor":
        import jax
        import jax.numpy as jnp

        return self._rng_fill(
            lambda k, s: jnp.exp(jax.random.normal(k, s) * std + mean))

    def geometric(self, p: float = 0.5) -> "Tensor":
        import jax
        import jax.numpy as jnp

        return self._rng_fill(
            lambda k, s: jnp.floor(
                jnp.log1p(-jax.random.uniform(k, s)) / np.log(1 - p)) + 1)

    def random(self, low: int = 1, high: Optional[int] = None) -> "Tensor":
        """Uniform integers in ``[low, high]`` (1-based Torch default)."""
        import jax

        if high is None:
            low, high = 1, low
        return self._rng_fill(
            lambda k, s: jax.random.randint(k, s, low, high + 1))

    def multinomial(self, n: int, replacement: bool = False) -> "Tensor":
        """Sample 1-based category indices from an unnormalized row of
        probabilities."""
        import jax

        from bigdl_tpu.utils.random_gen import RNG

        probs = np.asarray(self.data, np.float64).reshape(-1)
        probs = probs / probs.sum()
        key = RNG.next_key()
        seed = int(np.asarray(jax.random.key_data(key)).reshape(-1)[-1])
        rs = np.random.RandomState(seed % (2 ** 31))
        idx = rs.choice(len(probs), size=n, replace=replacement, p=probs)
        return Tensor(idx.astype(np.int64) + 1)

    @staticmethod
    def randperm(n: int) -> "Tensor":
        """1-based random permutation of 1..n (reference ``randperm``)."""
        import jax

        from bigdl_tpu.utils.random_gen import RNG

        return Tensor(jax.random.permutation(RNG.next_key(), n) + 1)

    @staticmethod
    def eye(n: int, m: Optional[int] = None) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.eye(n, m))

    def logical_and(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.logical_and(self.data, _unwrap(other)))

    def logical_or(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.logical_or(self.data, _unwrap(other)))

    def logical_xor(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.logical_xor(self.data, _unwrap(other)))

    def logical_not(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.logical_not(self.data))

    def count_nonzero(self) -> int:
        return int(np.count_nonzero(np.asarray(self.data)))

    def mode(self, dim: int = 1) -> "Tensor":
        """Most frequent value along 1-based ``dim`` (host-side; the
        reference's mode is an eager reduction too)."""
        import jax.numpy as jnp

        d = _resolve_dim(dim, self.data.ndim)
        host = np.asarray(self.data)

        def mode1(v):
            vals, counts = np.unique(v, return_counts=True)
            return vals[np.argmax(counts)]

        return Tensor(jnp.asarray(np.apply_along_axis(mode1, d, host)))

    # reference-name aliases
    def repeat(self, *reps: int) -> "Tensor":
        return self.repeat_tensor(*reps)

    def clip(self, min_v, max_v) -> "Tensor":
        return self.clamp(min_v, max_v)

    def outer(self, other) -> "Tensor":
        """Outer product of two vectors (non-accumulating, unlike ger)."""
        import jax.numpy as jnp

        return Tensor(jnp.outer(self.data, _unwrap(other)))

    def allclose(self, other, tolerance: float = 1e-6) -> bool:
        return self.almost_equal(other, tolerance)

    def numel(self) -> int:
        return self.n_element()

    nelement = numel

    # -- round-3b tranche: storage-set, BigDL axpy family, apply variants -

    def set(self, other: Optional["Tensor"] = None) -> "Tensor":
        """Reference ``set``: rebind this facade to ``other``'s array
        (``set()`` with no argument empties the tensor). The reference
        aliases the underlying *storage* so later mutations are shared;
        this facade's arrays are immutable XLA values (module docstring),
        so ``set`` shares the current VALUE — each facade then evolves
        independently. Code that uses set() for buffer reuse (its dominant
        reference idiom) behaves identically; code that relies on spooky
        cross-tensor mutation must be restructured."""
        import jax.numpy as jnp

        if other is None:
            self.data = jnp.zeros((0,), self.data.dtype)
        else:
            self.data = _unwrap(other)
        return self

    def cadd(self, *args) -> "Tensor":
        """``cadd(value, y)`` → self += value*y (the reference's axpy
        spelling, used by its SGD); ``cadd(y)`` → self += y."""
        if len(args) == 1:
            self.data = self.data + _unwrap(args[0])
        else:
            value, y = args
            self.data = self.data + value * _unwrap(y)
        return self

    def csub(self, *args) -> "Tensor":
        """``csub(value, y)`` → self -= value*y; ``csub(y)`` → self -= y."""
        if len(args) == 1:
            self.data = self.data - _unwrap(args[0])
        else:
            value, y = args
            self.data = self.data - value * _unwrap(y)
        return self

    def tpow(self, value: float) -> "Tensor":
        """self = value ** self (reference ``tpow``: scalar base raised to
        each element)."""
        self.data = value ** self.data
        return self

    def sum_square(self) -> float:
        """Reference ``sumSquare()`` — sum of squared elements."""
        import jax.numpy as jnp

        return float(jnp.sum(jnp.square(
            self.data.astype(jnp.float32))))

    def add_singleton_dimension(self, dim: int = 1) -> "Tensor":
        """Reference ``addSingletonDimension``: in-place unsqueeze at
        1-based ``dim`` (negative dims count from the end)."""
        if dim < 0:  # normalize: unsqueeze computes axis = dim - 1 itself
            dim = _resolve_dim(dim, self.data.ndim + 1) + 1
        self.data = self.unsqueeze(dim).data
        return self

    def del_singleton_dimension(self, dim: int = 1) -> "Tensor":
        """Reference ``delSingletonDimension``: in-place squeeze of the
        1-based ``dim`` (must be size 1; negative dims count from the
        end)."""
        d = _resolve_dim(dim, self.data.ndim)
        if self.data.shape[d] != 1:
            raise ValueError(
                f"dim {dim} has size {self.data.shape[d]}, not 1")
        self.data = self.squeeze(d + 1).data
        return self

    def get_type(self) -> str:
        """Reference ``getType()`` — the scalar type tag."""
        return str(self.data.dtype)

    def is_empty(self) -> bool:
        return self.n_element() == 0

    def is_scalar(self) -> bool:
        return self.data.ndim == 0 or tuple(self.data.shape) == (1,)

    def potri(self, uplo: str = "U") -> "Tensor":
        """Inverse from a Cholesky factor (reference ``potri``; pairs with
        ``potrf``). ``uplo`` names which triangle of self holds the
        factor."""
        import jax.numpy as jnp

        host = np.asarray(self.data, np.float64)  # eager LAPACK-style op:
        chol = np.triu(host) if uplo == "U" else np.tril(host)
        a = chol.T @ chol if uplo == "U" else chol @ chol.T
        return Tensor(jnp.asarray(np.linalg.inv(a),
                                  dtype=self.data.dtype))

    @staticmethod
    def rand(*sizes: int, seed: int = 0) -> "Tensor":
        import jax

        return Tensor(jax.random.uniform(jax.random.PRNGKey(seed), sizes))

    def new(self, *sizes: int) -> "Tensor":
        """Torch idiom ``t.new(sizes)``: fresh zero tensor, same dtype."""
        import jax.numpy as jnp

        return Tensor(jnp.zeros(sizes, self.data.dtype))

    def apply2(self, other, func) -> "Tensor":
        """Two-tensor apply (reference ``DenseTensorApply.apply2``):
        self[i] = func(self[i], other[i]) with a host Python function —
        ``map`` is the trait-level spelling and provides the kernel."""
        return self.map(other, func)

    def apply3(self, t1, t2, func) -> "Tensor":
        """Three-tensor apply (reference ``DenseTensorApply.apply3``):
        self[i] = func(t1[i], t2[i])."""
        import jax.numpy as jnp

        a = np.asarray(_unwrap(t1))
        b = np.asarray(_unwrap(t2))
        out = np.vectorize(func,
                           otypes=[np.asarray(self.data).dtype])(a, b)
        self.data = jnp.asarray(out)
        return self

    zip_with = apply3  # reference ``zipWith`` spelling

    def bhistc(self, bins: int = 100, min_v: float = 0.0,
               max_v: float = 0.0) -> "Tensor":
        """Per-row histogram of a 2-D tensor (reference ``bhistc``);
        min==max → use each row's own range, like ``histc``."""
        import jax.numpy as jnp

        host = np.asarray(self.data)
        if host.ndim != 2:
            raise ValueError("bhistc expects a 2-D tensor")
        rows = []
        for r in host:
            lo, hi = (min_v, max_v) if min_v != max_v else (
                float(r.min()), float(r.max()))
            rows.append(np.histogram(r, bins=bins, range=(lo, hi))[0])
        return Tensor(jnp.asarray(np.stack(rows), jnp.float32))

    # -- round-4 long tail (tranche 4: torch-dialect breadth + distinct
    # in-place spellings; every method numpy/torch-oracle-tested in
    # test_tensor_longtail.py) ---------------------------------------------

    def amax(self, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.max(self.data))
        return Tensor(jnp.max(self.data, axis=_resolve_dim(
            dim, self.data.ndim)))

    def amin(self, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.min(self.data))
        return Tensor(jnp.min(self.data, axis=_resolve_dim(
            dim, self.data.ndim)))

    def aminmax(self, dim: Optional[int] = None):
        return self.amin(dim), self.amax(dim)

    def diff(self, n: int = 1, dim: int = -1) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.diff(self.data, n=n,
                               axis=_resolve_dim(dim, self.data.ndim)))

    def fliplr(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.fliplr(self.data))

    def flipud(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.flipud(self.data))

    def movedim(self, source: int, destination: int) -> "Tensor":
        import jax.numpy as jnp

        nd = self.data.ndim
        return Tensor(jnp.moveaxis(self.data, _resolve_dim(source, nd),
                                   _resolve_dim(destination, nd)))

    def take_along_dim(self, indices, dim: int) -> "Tensor":
        """1-based indices along 1-based ``dim`` (gather-family
        convention)."""
        import jax.numpy as jnp

        idx = jnp.asarray(_unwrap(indices)).astype(jnp.int32) - 1
        return Tensor(jnp.take_along_axis(
            self.data, idx, axis=_resolve_dim(dim, self.data.ndim)))

    def repeat_interleave(self, repeats: int,
                          dim: Optional[int] = None) -> "Tensor":
        import jax.numpy as jnp

        if dim is None:
            return Tensor(jnp.repeat(self.data.reshape(-1), repeats))
        return Tensor(jnp.repeat(self.data, repeats,
                                 axis=_resolve_dim(dim, self.data.ndim)))

    def broadcast_to(self, *sizes: int) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.broadcast_to(self.data, tuple(sizes)))

    def logaddexp(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.logaddexp(self.data, _unwrap(other)))

    def logaddexp2(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.logaddexp2(self.data, _unwrap(other)))

    def logit(self, eps: Optional[float] = None) -> "Tensor":
        import jax.numpy as jnp

        x = self.data
        if eps is not None:
            x = jnp.clip(x, eps, 1.0 - eps)
        return Tensor(jnp.log(x / (1.0 - x)))

    def nan_to_num(self, nan: float = 0.0, posinf: Optional[float] = None,
                   neginf: Optional[float] = None) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.nan_to_num(self.data, nan=nan, posinf=posinf,
                                     neginf=neginf))

    def heaviside(self, values) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.heaviside(self.data, _unwrap(values)))

    def xlogy(self, other) -> "Tensor":
        import jax

        return Tensor(jax.scipy.special.xlogy(self.data, _unwrap(other)))

    def copysign(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.copysign(self.data, _unwrap(other)))

    def deg2rad(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.deg2rad(self.data))

    def rad2deg(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.rad2deg(self.data))

    def float_power(self, exponent) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.float_power(self.data, _unwrap(exponent)))

    def floor_divide(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.floor_divide(self.data, _unwrap(other)))

    def true_divide(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.true_divide(self.data, _unwrap(other)))

    def isclose(self, other, rtol: float = 1e-5,
                atol: float = 1e-8) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.isclose(self.data, _unwrap(other), rtol=rtol,
                                  atol=atol))

    def isneginf(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.isneginf(self.data))

    def isposinf(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.isposinf(self.data))

    def bincount(self, weights=None, minlength: int = 0) -> "Tensor":
        """Host-eager (output length is data-dependent)."""
        w = None if weights is None else np.asarray(_unwrap(weights))
        return Tensor(np.bincount(np.asarray(self.data).astype(np.int64)
                                  .reshape(-1),
                                  weights=w, minlength=minlength))

    def searchsorted(self, values, right: bool = False) -> "Tensor":
        """1-based insertion positions into this (sorted 1-D) tensor."""
        import jax.numpy as jnp

        side = "right" if right else "left"
        return Tensor(jnp.searchsorted(self.data, _unwrap(values),
                                       side=side) + 1)

    def tensor_split(self, n_or_indices, dim: int = 1):
        import jax.numpy as jnp

        ax = _resolve_dim(dim, self.data.ndim)
        parts = jnp.array_split(self.data, n_or_indices, axis=ax) \
            if isinstance(n_or_indices, int) else \
            jnp.split(self.data, [i - 1 for i in n_or_indices], axis=ax)
        return [Tensor(p) for p in parts]

    @staticmethod
    def hstack(tensors) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.hstack([_unwrap(t) for t in tensors]))

    @staticmethod
    def vstack(tensors) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.vstack([_unwrap(t) for t in tensors]))

    @staticmethod
    def dstack(tensors) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.dstack([_unwrap(t) for t in tensors]))

    @staticmethod
    def column_stack(tensors) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.column_stack([_unwrap(t) for t in tensors]))

    def cast(self, target) -> "Tensor":
        """Reference ``Tensor.cast[D]``: convert to the dtype of
        ``target`` (a Tensor) or to an explicit dtype."""
        dtype = target.dtype if isinstance(target, Tensor) else target
        return Tensor(self.data.astype(dtype))

    def sinc(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.sinc(self.data))

    def nextafter(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.nextafter(self.data, _unwrap(other)))

    def cov(self, correction: int = 1) -> "Tensor":
        """Covariance of a (vars, observations) matrix (torch.cov)."""
        import jax.numpy as jnp

        return Tensor(jnp.cov(self.data, ddof=correction))

    def corrcoef(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.corrcoef(self.data))

    # distinct in-place spellings (the pure forms above return NEW
    # tensors; these rebind self — torch dialect)

    def eq_(self, other) -> "Tensor":
        self.data = self.eq(other).data
        return self

    def ne_(self, other) -> "Tensor":
        self.data = self.ne(other).data
        return self

    def lt_(self, other) -> "Tensor":
        self.data = self.lt(other).data
        return self

    def gt_(self, other) -> "Tensor":
        self.data = self.gt(other).data
        return self

    def le_(self, other) -> "Tensor":
        self.data = self.le(other).data
        return self

    def ge_(self, other) -> "Tensor":
        self.data = self.ge(other).data
        return self

    def cumsum_(self, dim: int = 1) -> "Tensor":
        self.data = self.cumsum(dim).data
        return self

    def cumprod_(self, dim: int = 1) -> "Tensor":
        self.data = self.cumprod(dim).data
        return self

    def tril_(self, k: int = 0) -> "Tensor":
        self.data = self.tril(k).data
        return self

    def triu_(self, k: int = 0) -> "Tensor":
        self.data = self.triu(k).data
        return self

    def scatter_(self, dim: int, index, src) -> "Tensor":
        self.data = self.scatter(dim, index, src).data
        return self

    # -- tranche 5 (final): the remaining torch/reference spellings -------
    # (reference ``tensor/Tensor.scala`` long tail — the JVM-only residue
    # is documented as an exclusion list in COVERAGE.md)

    def value(self) -> float:
        """Scalar read of a 1-element tensor (reference ``value()``)."""
        if self.data.size != 1:
            raise ValueError(
                f"value() needs a 1-element tensor, got shape "
                f"{tuple(self.data.shape)}")
        return float(self.data.reshape(()))

    def acosh(self):
        return self._np_el("arccosh")

    def asinh(self):
        return self._np_el("arcsinh")

    def atanh(self):
        return self._np_el("arctanh")

    def positive(self) -> "Tensor":
        return Tensor(self.data)

    def swapaxes(self, axis0: int, axis1: int) -> "Tensor":
        """0-based numpy/torch.swapaxes spelling (the 1-based heritage
        form is ``transpose``)."""
        import jax.numpy as jnp

        return Tensor(jnp.swapaxes(self.data, axis0, axis1))

    def swapdims(self, dim0: int, dim1: int) -> "Tensor":
        return self.swapaxes(dim0, dim1)

    def unbind(self, dim: int = 1):
        """Tuple of views with 1-based ``dim`` removed (torch.unbind)."""
        ax = _resolve_dim(dim, self.data.ndim)
        n = self.data.shape[ax]
        import jax.numpy as jnp

        return tuple(Tensor(jnp.take(self.data, i, axis=ax))
                     for i in range(n))

    def unflatten(self, dim: int, sizes) -> "Tensor":
        ax = _resolve_dim(dim, self.data.ndim)
        shape = list(self.data.shape)
        new_shape = shape[:ax] + list(sizes) + shape[ax + 1:]
        return Tensor(self.data.reshape(new_shape))

    def diagonal(self, offset: int = 0, dim1: int = 1,
                 dim2: int = 2) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.diagonal(
            self.data, offset=offset,
            axis1=_resolve_dim(dim1, self.data.ndim),
            axis2=_resolve_dim(dim2, self.data.ndim)))

    def diagflat(self, offset: int = 0) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.diagflat(self.data, k=offset))

    def diag_embed(self, offset: int = 0) -> "Tensor":
        """Batched (n, n) diagonal matrices from the last axis
        (torch.diag_embed, dim1/dim2 fixed at the trailing pair,
        n = last_dim + |offset|)."""
        import jax.numpy as jnp

        x = self.data
        n = x.shape[-1] + abs(offset)
        eye = jnp.eye(n, k=offset, dtype=x.dtype)
        # row r of the output carries x[r - max(0, -offset)] on its one
        # nonzero column; pad x so that index aligns with the row index
        pad = [(0, 0)] * (x.ndim - 1) + [(max(0, -offset), max(0, offset))]
        xpad = jnp.pad(x, pad)
        return Tensor(eye * xpad[..., :, None])

    def cummax(self, dim: int = 1):
        """(values, 1-based indices of the latest max) along ``dim``
        (host-eager — accumulate has no jnp ufunc form)."""
        ax = _resolve_dim(dim, self.data.ndim)
        a = np.asarray(self.data)
        vals = np.maximum.accumulate(a, axis=ax)
        pos = np.arange(a.shape[ax]).reshape(
            [-1 if i == ax else 1 for i in range(a.ndim)])
        idx = np.maximum.accumulate(np.where(a == vals, pos, 0), axis=ax)
        return Tensor(vals), Tensor((idx + 1).astype(np.int32))

    def cummin(self, dim: int = 1):
        ax = _resolve_dim(dim, self.data.ndim)
        a = np.asarray(self.data)
        vals = np.minimum.accumulate(a, axis=ax)
        pos = np.arange(a.shape[ax]).reshape(
            [-1 if i == ax else 1 for i in range(a.ndim)])
        idx = np.maximum.accumulate(np.where(a == vals, pos, 0), axis=ax)
        return Tensor(vals), Tensor((idx + 1).astype(np.int32))

    def logcumsumexp(self, dim: int = 1) -> "Tensor":
        ax = _resolve_dim(dim, self.data.ndim)
        return Tensor(np.logaddexp.accumulate(
            np.asarray(self.data, np.float64), axis=ax).astype(
                np.asarray(self.data).dtype))

    def logsumexp(self, dim: Optional[int] = None):
        import jax.scipy.special as jsp

        if dim is None:
            return float(jsp.logsumexp(self.data))
        return Tensor(jsp.logsumexp(
            self.data, axis=_resolve_dim(dim, self.data.ndim)))

    def nansum(self, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.nansum(self.data))
        return Tensor(jnp.nansum(self.data,
                                 axis=_resolve_dim(dim, self.data.ndim)))

    def nanmean(self, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.nanmean(self.data))
        return Tensor(jnp.nanmean(self.data,
                                  axis=_resolve_dim(dim, self.data.ndim)))

    def nanmedian(self, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            return float(jnp.nanmedian(self.data))
        return Tensor(jnp.nanmedian(self.data,
                                    axis=_resolve_dim(dim, self.data.ndim)))

    def quantile(self, q, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            out = jnp.quantile(self.data, q)
            return float(out) if jnp.ndim(out) == 0 else Tensor(out)
        return Tensor(jnp.quantile(self.data, q,
                                   axis=_resolve_dim(dim, self.data.ndim)))

    def nanquantile(self, q, dim: Optional[int] = None):
        import jax.numpy as jnp

        if dim is None:
            out = jnp.nanquantile(self.data, q)
            return float(out) if jnp.ndim(out) == 0 else Tensor(out)
        return Tensor(jnp.nanquantile(
            self.data, q, axis=_resolve_dim(dim, self.data.ndim)))

    def std_mean(self, dim: Optional[int] = None, unbiased: bool = True):
        return self.std(dim, unbiased), self.mean(dim)

    def var_mean(self, dim: Optional[int] = None, unbiased: bool = True):
        return self.var(dim, unbiased), self.mean(dim)

    def gcd(self, other) -> "Tensor":
        # host numpy in int64: under JAX's default x64-off config a jnp
        # int64 cast silently truncates to int32 (gcd itself never
        # exceeds its inputs, so no overflow guard needed)
        return Tensor(np.gcd(np.asarray(self.data, np.int64),
                             np.asarray(_unwrap(other), np.int64)))

    def lcm(self, other) -> "Tensor":
        out = np.lcm(np.asarray(self.data, np.int64),
                     np.asarray(_unwrap(other), np.int64))
        if np.any(np.abs(out) > np.iinfo(np.int32).max) and \
                not _x64_enabled():
            raise OverflowError(
                "lcm result exceeds int32 and JAX x64 is disabled — the "
                "facade's device storage would silently truncate it; "
                "enable jax.config.update('jax_enable_x64', True) or "
                "compute on to_numpy()")
        return Tensor(out)

    def ldexp(self, other) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.ldexp(self.data,
                                jnp.asarray(_unwrap(other), jnp.int32)))

    def frexp(self):
        m, e = np.frexp(np.asarray(self.data))
        return Tensor(m), Tensor(e.astype(np.int32))

    def i0(self) -> "Tensor":
        import jax.scipy.special as jsp

        return Tensor(jsp.i0(self.data))

    def mvlgamma(self, p: int) -> "Tensor":
        """Multivariate log-gamma (torch.mvlgamma):
        ``p(p-1)/4·ln π + Σ_{j=1..p} lgamma(x + (1-j)/2)``."""
        import jax.numpy as jnp
        import jax.scipy.special as jsp

        x = self.data
        js = jnp.arange(1, p + 1, dtype=jnp.float32)
        terms = jsp.gammaln(x[..., None] + (1.0 - js) / 2.0)
        return Tensor(terms.sum(-1) + p * (p - 1) / 4.0 * jnp.log(jnp.pi))

    def polygamma(self, n: int) -> "Tensor":
        from scipy.special import polygamma as _pg

        return Tensor(np.asarray(_pg(n, np.asarray(self.data)),
                                 np.asarray(self.data).dtype))

    def trapz(self, dx: float = 1.0, dim: int = -1) -> "Tensor":
        ax = _resolve_dim(dim, self.data.ndim)
        trap = getattr(np, "trapezoid", None) or np.trapz
        out = trap(np.asarray(self.data), dx=dx, axis=ax)
        return float(out) if np.ndim(out) == 0 else Tensor(out)

    def vdot(self, other) -> float:
        return float(np.vdot(np.asarray(self.data),
                             np.asarray(_unwrap(other))))

    def histogram(self, bins: int = 100, min_v: Optional[float] = None,
                  max_v: Optional[float] = None):
        """(hist, bin_edges) — torch.histogram (histc returns counts
        only)."""
        a = np.asarray(self.data).reshape(-1)
        rng = None
        if min_v is not None or max_v is not None:
            rng = (min_v if min_v is not None else float(a.min()),
                   max_v if max_v is not None else float(a.max()))
        h, edges = np.histogram(a, bins=bins, range=rng)
        # edges stay floating even for integer inputs — casting back to
        # the input dtype truncates bin boundaries into duplicates
        return Tensor(h.astype(np.float32)), Tensor(edges.astype(np.float32))

    def signbit(self) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.signbit(self.data))

    def rsub(self, other, alpha: float = 1.0) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.asarray(_unwrap(other)) - alpha * self.data)

    def matrix_power(self, n: int) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.linalg.matrix_power(self.data, n))

    def pinverse(self, rcond: float = 1e-15) -> "Tensor":
        import jax.numpy as jnp

        return Tensor(jnp.linalg.pinv(self.data, rtol=rcond))

    def slogdet(self):
        import jax.numpy as jnp

        sign, logabs = jnp.linalg.slogdet(self.data)
        return float(sign), float(logabs)

    def cholesky(self, upper: bool = False) -> "Tensor":
        """torch.cholesky spelling (lower by default; ``potrf`` is the
        reference spelling, upper by default)."""
        return self.potrf(upper=upper)

    def lstsq(self, b) -> "Tensor":
        return self.gels(b)

    def masked_scatter(self, mask, source) -> "Tensor":
        """Fill ``mask``-true positions with consecutive ``source``
        elements (host-eager: data-dependent layout)."""
        a = np.asarray(self.data).copy()
        # broadcast first (torch semantics), so a broadcastable mask
        # counts — and consumes source for — every EXPANDED position
        m = np.broadcast_to(np.asarray(_unwrap(mask), bool), a.shape)
        src = np.asarray(_unwrap(source)).reshape(-1)
        n = int(m.sum())
        if src.size < n:
            raise ValueError(
                f"masked_scatter: source has {src.size} elements for "
                f"{n} masked positions")
        a[m] = src[:n]
        return Tensor(a)

    def index_put(self, indices, values) -> "Tensor":
        """Write ``values`` at 1-based coordinate arrays (one per dim —
        the facade's 1-based heritage convention, like ``index_fill``)."""
        import jax.numpy as jnp

        idx = tuple(jnp.asarray(_unwrap(i), jnp.int32) - 1
                    for i in indices)
        return Tensor(self.data.at[idx].set(
            jnp.asarray(_unwrap(values), self.data.dtype)))

    def narrow_copy(self, dim: int, start: int, length: int) -> "Tensor":
        return self.narrow(dim, start, length).clone()

    def __repr__(self) -> str:
        return f"Tensor(shape={tuple(self.data.shape)}, dtype={self.data.dtype})"


def _squeeze_(self, dim=None):
    """In-place squeeze (torch dialect) — the plain ``squeeze`` returns a
    new Tensor, unlike the other facade mutators."""
    self.data = self.squeeze(dim).data
    return self


Tensor.squeeze_ = _squeeze_

# Torch-dialect underscore aliases: these facade mutators are already
# in-place under their plain names (Torch-heritage API); ported user code
# often uses the torch spellings.
for _plain in ("abs", "add", "ceil", "clamp", "copy", "div", "exp", "fill",
               "floor", "log", "masked_fill", "mul", "pow", "round",
               "sub", "zero",
               # round-3b batch — all in-place under their plain names
               "sqrt", "rsqrt", "sin", "cos", "tan", "tanh", "sigmoid",
               "reciprocal", "erf", "erfc", "trunc", "frac", "lerp",
               "fmod", "remainder", "uniform", "normal", "bernoulli",
               "random", "cadd", "csub", "tpow", "cmul", "cdiv",
               "log2", "log10", "log1p", "expm1", "sign", "neg",
               "exponential", "cauchy", "geometric", "log_normal"):
    setattr(Tensor, _plain + "_", getattr(Tensor, _plain))
del _plain


def _make_rebinder(name):
    def rebind(self, *a, **kw):
        self.data = getattr(self, name)(*a, **kw).data
        return self

    rebind.__name__ = name + "_"
    rebind.__doc__ = (f"In-place {name} (torch dialect): the plain "
                      f"``{name}`` returns a new Tensor.")
    return rebind


for _viewer in ("t", "transpose", "unsqueeze"):
    setattr(Tensor, _viewer + "_", _make_rebinder(_viewer))
del _viewer

# tranche 5: torch's "spelled-out" aliases (same objects — both names are
# torch-legit and ported user code uses either; in-place semantics follow
# the aliased method)
for _alias, _target in (("arccos", "acos"), ("arcsin", "asin"),
                        ("arctan", "atan"), ("arctan2", "atan2"),
                        ("arccosh", "acosh"), ("arcsinh", "asinh"),
                        ("arctanh", "atanh"), ("absolute", "abs"),
                        ("divide", "div"), ("multiply", "mul"),
                        ("subtract", "sub"), ("fix", "trunc"),
                        ("greater", "gt"), ("greater_equal", "ge"),
                        ("less", "lt"), ("less_equal", "le"),
                        ("not_equal", "ne"), ("moveaxis", "movedim"),
                        ("concat", "cat"), ("concatenate", "cat")):
    # __dict__ (not getattr) so staticmethod descriptors (cat) survive
    setattr(Tensor, _alias, Tensor.__dict__[_target])
del _alias, _target

# tranche 5 underscore variants for the in-place-under-plain-name family
for _plain in ("acos", "asin", "atan", "sinh", "cosh", "square",
               "exp2", "lgamma", "digamma", "erfinv", "acosh", "asinh",
               "atanh", "cinv"):
    setattr(Tensor, _plain + "_", getattr(Tensor, _plain))
del _plain


def _tensor_flatten(t: Tensor):
    return [t.data], None


def _tensor_unflatten(aux, children) -> Tensor:
    out = object.__new__(Tensor)
    out.data = children[0]
    return out


try:
    import jax.tree_util as _jtu

    _jtu.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
except Exception:  # pragma: no cover
    pass
