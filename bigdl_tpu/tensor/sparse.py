"""SparseTensor — COO sparse tensor for sparse-input layers.

Reference role (UNVERIFIED, SURVEY.md §0): ``.../bigdl/tensor/SparseTensor.scala``
(+ ``SparseTensorMath``/``SparseTensorBLAS``) — a COO-ish sparse tensor
backing ``SparseLinear``/``SparseJoinTable`` for wide sparse features.

TPU-native redesign: XLA wants static shapes, so a SparseTensor is a fixed-
capacity COO triple ``(indices (ndim, cap), values (cap,), shape)`` with a
validity convention — unused slots carry value 0 and index 0, making every
kernel a dense einsum/segment-sum over the capacity axis (no gather/scatter,
no dynamic shapes; zero-valued padding contributes nothing). Registered as a
JAX pytree (shape is static aux data) so sparse activations flow through
``jit`` like any array.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import numpy as np


class SparseTensor:
    """Fixed-capacity COO sparse tensor."""

    __slots__ = ("indices", "values", "shape")

    def __init__(self, indices, values, shape: Sequence[int]) -> None:
        import jax.numpy as jnp

        self.indices = jnp.asarray(indices, dtype=jnp.int32)  # (ndim, cap)
        self.values = jnp.asarray(values)                     # (cap,)
        self.shape = tuple(int(s) for s in shape)
        assert self.indices.ndim == 2 and self.indices.shape[0] == len(self.shape)

    # -- constructors ------------------------------------------------------

    @staticmethod
    def from_dense(dense, capacity: Optional[int] = None) -> "SparseTensor":
        """Host-side: keep the nonzeros (padded to ``capacity`` slots)."""
        arr = np.asarray(dense)
        idx = np.nonzero(arr)
        nnz = len(idx[0])
        cap = capacity if capacity is not None else max(nnz, 1)
        assert cap >= nnz, f"capacity {cap} < nnz {nnz}"
        indices = np.zeros((arr.ndim, cap), np.int32)
        values = np.zeros((cap,), arr.dtype)
        for d in range(arr.ndim):
            indices[d, :nnz] = idx[d]
        values[:nnz] = arr[idx]
        return SparseTensor(indices, values, arr.shape)

    @staticmethod
    def coo(indices, values, shape) -> "SparseTensor":
        return SparseTensor(np.asarray(indices).T, values, shape)

    # -- meta --------------------------------------------------------------

    def nnz(self) -> int:
        """Number of stored nonzeros (padding slots hold value 0)."""
        import numpy as _np

        return int(_np.count_nonzero(_np.asarray(self.values)))

    def capacity(self) -> int:
        return int(self.values.shape[0])

    def dim(self) -> int:
        return len(self.shape)

    def size(self, d: Optional[int] = None):
        return self.shape if d is None else self.shape[d - 1]  # 1-based

    # -- conversions -------------------------------------------------------

    def to_dense(self):
        """Scatter-add into a dense array (pure; jit-safe)."""
        import jax.numpy as jnp

        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[tuple(self.indices)].add(self.values)

    def astype(self, dtype) -> "SparseTensor":
        return SparseTensor(self.indices, self.values.astype(dtype), self.shape)

    # -- math (reference SparseTensorMath surface; all jit-safe: fixed
    # capacity in, fixed capacity or dense out) ---------------------------

    def t(self) -> "SparseTensor":
        """2-D transpose: swap index rows (no data movement)."""
        assert self.dim() == 2
        import jax.numpy as jnp

        return SparseTensor(jnp.flip(self.indices, axis=0), self.values,
                            self.shape[::-1])

    def mul(self, scalar) -> "SparseTensor":
        return SparseTensor(self.indices, self.values * scalar, self.shape)

    def div(self, scalar) -> "SparseTensor":
        return SparseTensor(self.indices, self.values / scalar, self.shape)

    def sum(self, dim: Optional[int] = None):
        """Scalar total, or reduce OVER the 1-based ``dim`` (same dim
        semantics as the dense ``Tensor.sum``): the 2-D result is the
        dense vector indexed by the OTHER axis."""
        import jax
        import jax.numpy as jnp

        if dim is None:
            return jnp.sum(self.values)
        assert self.dim() == 2, "dim-reduction implemented for 2-D"
        if dim not in (1, 2):
            raise ValueError(f"invalid 1-based dim {dim} for 2-D sparse")
        kept = 1 - (dim - 1)
        return jax.ops.segment_sum(self.values, self.indices[kept],
                                   num_segments=self.shape[kept])

    def narrow(self, dim: int, start: int, length: int) -> "SparseTensor":
        """1-based narrow along ``dim`` (reference mini-batch slicing).
        Jit-safe: out-of-range slots are zeroed in place (capacity kept)."""
        import jax.numpy as jnp

        d = dim - 1
        s0 = start - 1
        keep = jnp.logical_and(self.indices[d] >= s0,
                               self.indices[d] < s0 + length)
        values = jnp.where(keep, self.values, 0)
        # dropped slots reset to index 0 on EVERY dim (the module's padding
        # invariant), live slots shift by the narrow offset on dim d only
        idx = jnp.where(keep[None, :], self.indices, 0)
        idx = idx.at[d].add(jnp.where(keep, -s0, 0))
        shape = list(self.shape)
        shape[d] = length
        return SparseTensor(idx, values, shape)

    def cmul_dense(self, dense) -> "SparseTensor":
        """Elementwise multiply by a dense tensor (stays sparse)."""
        return SparseTensor(self.indices,
                            self.values * dense[tuple(self.indices)],
                            self.shape)

    def vdot(self, dense) -> Any:
        """⟨self, dense⟩ — sum of values times gathered dense entries."""
        import jax.numpy as jnp

        return jnp.sum(self.values * dense[tuple(self.indices)])

    def mm(self, dense):
        """``self (B, D) @ dense (D, O)`` → dense (B, O)."""
        return sparse_dense_matmul(self, dense)

    def mv(self, vec):
        """``self (B, D) @ vec (D,)`` → dense (B,)."""
        return sparse_dense_matmul(self, vec[:, None])[:, 0]

    def add_to_dense(self, dense):
        """``dense + self`` as a dense tensor (scatter-add)."""
        return dense.at[tuple(self.indices)].add(self.values)

    def __repr__(self) -> str:
        return (f"SparseTensor(shape={self.shape}, capacity="
                f"{int(self.values.shape[0])})")


def sparse_dense_matmul(sp: SparseTensor, dense):
    """``sp (B, D) @ dense (D, O) -> (B, O)`` as one segment-sum.

    Each stored element (b, d, v) contributes ``v * dense[d]`` to row b —
    a gather + segment_sum, which XLA lowers without materializing the
    dense form. Zero-padded slots add zero rows.
    """
    import jax

    assert sp.dim() == 2, "sparse_dense_matmul wants a 2-D sparse LHS"
    rows, cols = sp.indices[0], sp.indices[1]
    contrib = sp.values[:, None] * dense[cols]          # (cap, O)
    return jax.ops.segment_sum(contrib, rows, num_segments=sp.shape[0])


def sparse_addmm(beta, c, alpha, sp: SparseTensor, dense):
    """``beta * c + alpha * (sp @ dense)`` (reference
    ``SparseTensorMath.addmm``)."""
    return beta * c + alpha * sparse_dense_matmul(sp, dense)


def sparse_addmv(beta, y, alpha, sp: SparseTensor, x):
    """``beta * y + alpha * (sp @ x)`` (reference
    ``SparseTensorMath.addmv``)."""
    return beta * y + alpha * sp.mv(x)


def dense_sparse_matmul(dense, sp: SparseTensor):
    """``dense (N, B) @ sp (B, D)`` → dense (N, D) — via the transpose
    identity ``(spᵀ @ denseᵀ)ᵀ`` so one segment-sum kernel serves both
    orientations (reference SparseTensorBLAS dense×sparse path)."""
    return sparse_dense_matmul(sp.t(), dense.T).T


def sparse_join(tensors: Sequence[SparseTensor], dim: int = 2) -> SparseTensor:
    """Concatenate 2-D sparse tensors along feature dim (1-based ``dim=2``,
    the reference SparseJoinTable's case) or batch dim (``dim=1``)."""
    import jax.numpy as jnp

    assert all(t.dim() == 2 for t in tensors)
    axis = dim - 1
    offs, off = [], 0
    for t in tensors:
        offs.append(off)
        off += t.shape[axis]
    fixed = 1 - axis
    base = tensors[0].shape[fixed]
    assert all(t.shape[fixed] == base for t in tensors), "mismatched join"
    idx_parts, val_parts = [], []
    for t, o in zip(tensors, offs):
        shifted = t.indices.at[axis].add(
            jnp.where(t.values != 0, o, 0)  # keep padding slots at index 0
        )
        idx_parts.append(shifted)
        val_parts.append(t.values)
    indices = jnp.concatenate(idx_parts, axis=1)
    values = jnp.concatenate(val_parts)
    shape = list(tensors[0].shape)
    shape[axis] = off
    return SparseTensor(indices, values, shape)


def _sparse_flatten(t: SparseTensor):
    return (t.indices, t.values), t.shape


def _sparse_unflatten(shape, children):
    indices, values = children
    obj = object.__new__(SparseTensor)
    obj.indices = indices
    obj.values = values
    obj.shape = shape
    return obj


def _register():
    import jax.tree_util as jtu

    jtu.register_pytree_node(SparseTensor, _sparse_flatten, _sparse_unflatten)


_register()
