from bigdl_tpu.tensor.sparse import (
    SparseTensor, sparse_dense_matmul, sparse_join,
)
from bigdl_tpu.tensor.tensor import Tensor

__all__ = ["Tensor", "SparseTensor", "sparse_dense_matmul", "sparse_join"]
