from bigdl_tpu.tensor.tensor import Tensor

__all__ = ["Tensor"]
